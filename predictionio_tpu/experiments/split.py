"""A/B traffic splitting for the replica fleet router (ISSUE 16 b).

Design constraints, in order:

* **Deterministic stickiness.** A variant assignment is a pure function
  of ``(salt, variant names, weights, affinity key)`` — no assignment
  table, no state file. The same cache scope maps to the same variant
  after a router restart, a replica SIGKILL, a fleet membership change,
  or a second router pointed at the same experiment, by construction.
  (Consistent-hash rings re-shuffle keys when members change; an
  experiment must not, so the split hashes into a weight interval, not
  onto a member ring.)
* **No cross-variant cache hits.** Variant names are validated against
  ``[A-Za-z0-9._-]{1,64}`` — the ``|`` and ``:`` separators used by the
  router's key-generation map and the replica cache namespaces cannot
  occur in a name, so ``f"{variant}|{key}"`` tags are collision-free
  for ANY adversarial scope string (the scope lives inside ``key``,
  after the first separator).
* **Stdlib-only.** The router is stdlib-only by piolint manifest; this
  module is declared stdlib-only with no allow-list at all.

Assignment maps a 64-bit keyed blake2b digest of the affinity key onto
exact integer cumulative-weight thresholds over ``2**64`` — float
rounding never moves a boundary, so two processes computing the same
split always agree.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from bisect import bisect_right
from collections import deque
from hashlib import blake2b

__all__ = ["Variant", "SplitConfig", "TrafficSplit"]

_NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")
#: weights are scaled to integers at this resolution before threshold
#: arithmetic — exact, platform-independent boundaries
_WEIGHT_SCALE = 1_000_000
_SPAN = 1 << 64


@dataclasses.dataclass(frozen=True)
class Variant:
    """One arm of the experiment: a name and a relative traffic weight."""

    name: str
    weight: float = 1.0

    def __post_init__(self):
        if not _NAME_RE.match(self.name or ""):
            raise ValueError(
                f"variant name {self.name!r} must match [A-Za-z0-9._-]{{1,64}} "
                "(separator characters would break cache-key namespacing)"
            )
        if self.weight < 0 or self.weight != self.weight:
            raise ValueError(f"variant {self.name!r} weight must be >= 0")


@dataclasses.dataclass(frozen=True)
class SplitConfig:
    """Immutable experiment description (variants + hash salt)."""

    variants: tuple = ()
    salt: str = "pio-exp"

    def __post_init__(self):
        names = [v.name for v in self.variants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate variant names: {names}")
        if self.variants and not any(v.weight > 0 for v in self.variants):
            raise ValueError("at least one variant needs weight > 0")

    @property
    def enabled(self) -> bool:
        return len(self.variants) >= 2

    @staticmethod
    def parse(spec: str, salt: str = "pio-exp") -> "SplitConfig":
        """``"control:2,treatment:1"`` (or bare names, weight 1) -> config.

        The CLI surface for ``pio deploy --variants``; at least two
        variants are required (one variant is not an experiment).
        """
        variants = []
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            name, _, w = part.partition(":")
            try:
                weight = float(w) if w else 1.0
            except ValueError:
                raise ValueError(
                    f"--variants weight {w!r} for {name!r} is not a number"
                ) from None
            variants.append(Variant(name=name.strip(), weight=weight))
        if len(variants) < 2:
            raise ValueError(
                f"--variants needs at least two name[:weight] entries, got {spec!r}"
            )
        return SplitConfig(variants=tuple(variants), salt=salt)

    def thresholds(self) -> list[tuple[int, str]]:
        """Cumulative integer thresholds over ``2**64``, one per variant
        (zero-weight variants get an empty interval and are never
        assigned). Exact integer arithmetic — deterministic everywhere."""
        scaled = [max(0, round(v.weight * _WEIGHT_SCALE)) for v in self.variants]
        total = sum(scaled)
        if total <= 0:
            return []
        out, acc = [], 0
        for v, s in zip(self.variants, scaled):
            acc += s
            out.append(((_SPAN * acc) // total, v.name))
        return out


class _VariantStats:
    """Per-variant counters: routed/errors, latency percentiles from a
    bounded reservoir, reward aggregates."""

    __slots__ = ("routed", "errors", "rewards", "reward_sum", "latencies")

    def __init__(self):
        self.routed = 0
        self.errors = 0
        self.rewards = 0
        self.reward_sum = 0.0
        self.latencies = deque(maxlen=512)

    def percentile_ms(self, q: float):
        snap = sorted(self.latencies)
        if not snap:
            return None
        idx = min(len(snap) - 1, int(q * (len(snap) - 1) + 0.5))
        return round(snap[idx] * 1000.0, 3)


class TrafficSplit:
    """Live experiment state for one router: assignment + counters +
    promotion. Everything except the counters is derivable from the
    (immutable) config, which is the whole stickiness story."""

    def __init__(self, config: SplitConfig):
        if not config.variants:
            raise ValueError("TrafficSplit needs at least one variant")
        self._lock = threading.Lock()
        self._config = config
        self._bounds, self._names = self._compile(config)
        self._stats = {v.name: _VariantStats() for v in config.variants}
        self.promoted: dict | None = None

    @staticmethod
    def _compile(config: SplitConfig):
        pairs = config.thresholds()
        return [b for b, _ in pairs], [n for _, n in pairs]

    @property
    def config(self) -> SplitConfig:
        with self._lock:
            return self._config

    def variant_names(self) -> list[str]:
        return [v.name for v in self.config.variants]

    # ------------------------------------------------------------ assignment
    def assign(self, key: str | None) -> str:
        """Affinity key -> variant name. ``None`` (an uncacheable body —
        no scope, not canonicalizable) pins to the first variant so an
        anonymous probe stream stays internally consistent."""
        with self._lock:
            bounds, names = self._bounds, self._names
            salt = self._config.salt
            first = self._config.variants[0].name
        if not bounds:
            return first
        if key is None:
            return names[0]
        h = int.from_bytes(
            blake2b(
                key.encode("utf-8", "surrogatepass"),
                digest_size=8,
                key=salt.encode("utf-8")[:64],
            ).digest(),
            "big",
        )
        idx = bisect_right(bounds, h)
        return names[min(idx, len(names) - 1)]

    # -------------------------------------------------------------- counters
    def note_routed(self, variant: str, seconds: float, ok: bool = True) -> None:
        with self._lock:
            st = self._stats.get(variant)
            if st is None:
                return
            st.routed += 1
            if not ok:
                st.errors += 1
            st.latencies.append(max(0.0, float(seconds)))

    def note_reward(self, variant: str, value: float = 1.0) -> None:
        with self._lock:
            st = self._stats.get(variant)
            if st is None:
                return
            st.rewards += 1
            try:
                st.reward_sum += float(value)
            except (TypeError, ValueError):
                st.reward_sum += 1.0

    # ------------------------------------------------------------- promotion
    def promote(self, winner: str) -> dict:
        """Collapse the split onto ``winner`` (weight 1, everything else
        0). Counters survive so the post-promotion stats still show the
        experiment's full history; the final pre-promotion weights are
        recorded in the promotion stamp."""
        with self._lock:
            cfg = self._config
            if winner not in {v.name for v in cfg.variants}:
                raise ValueError(
                    f"unknown variant {winner!r}; have {[v.name for v in cfg.variants]}"
                )
            before = {v.name: v.weight for v in cfg.variants}
            new_cfg = dataclasses.replace(
                cfg,
                variants=tuple(
                    dataclasses.replace(v, weight=1.0 if v.name == winner else 0.0)
                    for v in cfg.variants
                ),
            )
            self._config = new_cfg
            self._bounds, self._names = self._compile(new_cfg)
            self.promoted = {
                "variant": winner,
                "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "weightsBefore": before,
            }
            return dict(self.promoted)

    # ----------------------------------------------------------------- stats
    def stats_json(self) -> dict:
        with self._lock:
            cfg = self._config
            out = {
                "salt": cfg.salt,
                "promoted": dict(self.promoted) if self.promoted else None,
                "variants": [],
            }
            for v in cfg.variants:
                st = self._stats[v.name]
                out["variants"].append(
                    {
                        "name": v.name,
                        "weight": v.weight,
                        "routed": st.routed,
                        "errors": st.errors,
                        "p50Ms": st.percentile_ms(0.50),
                        "p99Ms": st.percentile_ms(0.99),
                        "rewardCount": st.rewards,
                        "rewardSum": round(st.reward_sum, 6),
                        "rewardMean": (
                            round(st.reward_sum / st.rewards, 6)
                            if st.rewards
                            else None
                        ),
                    }
                )
            return out
