"""Vmapped eval sweeps: ``pio eval --grid`` (ISSUE 16 c).

``MetricEvaluator`` trains one candidate at a time — C candidates pay C
full train dispatch sequences even when every candidate shares the data
and the array shapes. When the grid is *vmap-compatible* (one shared
datasource/preparator/serving config, one algorithm whose candidates
differ only along the scalar axes ``lambda`` / ``alpha`` / ``seed``),
this module trains ALL candidates as one ``vmap``-of-train jitted
program: a dense per-fold ALS (normal-equation half-sweeps, explicit and
implicit) with the ranking metric computed in-program, so one dispatch
per fold scores the whole grid.

Shape discipline (compile-budget.json carries the ledger entry): fold
matrices are padded to pow2 user/item buckets and the candidate axis is
part of the shape, so a C-candidate sweep over K folds of similar size
compiles ONCE and the jit-witness sees no per-candidate retraces. Grids
that are not vmap-compatible (different ranks, multiple algorithms,
different datasources), or whose padded fold would blow the dense-cell
budget, fall back to the sequential ``MetricEvaluator`` with a logged
reason — ``pio eval --grid`` never fails where ``pio eval`` would
succeed.

The in-program metric is precision@k with train-seen masking, matching
the recommendation template's ``PrecisionAtK`` unit semantics (held-out
positives hit / k served unseen items, averaged over eval users,
fold-weighted by eval-user count). Candidates are RANKED by this score;
the sequential path remains the reference for absolute metric values.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import logging
import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.controller.evaluation import (
    MetricEvaluator,
    MetricEvaluatorResult,
    MetricScores,
)
from predictionio_tpu.controller.params import params_to_json

__all__ = ["grid_axes", "GridAxes", "grid_train_eval", "run_grid_evaluation"]

logger = logging.getLogger(__name__)

_MIN_BUCKET = 8
#: dense-cell ceiling per fold across the whole candidate axis
#: (C * U_pad * I_pad); past this the vmapped dense solve loses to the
#: sequential sparse path anyway, so fall back instead of OOMing
MAX_GRID_CELLS = 64_000_000
#: scalar axes a vmap-compatible grid may vary (JSON key names, i.e.
#: post-alias: ``lambda`` is the ALS regularizer's wire name)
SWEEP_AXES = ("lambda", "lambda_", "alpha", "seed")


def _pow2(n: int) -> int:
    return max(_MIN_BUCKET, 1 << (max(1, n) - 1).bit_length())


# ------------------------------------------------------------ compatibility
@dataclasses.dataclass(frozen=True)
class GridAxes:
    """The scalar axes of a vmap-compatible grid + its static config."""

    regs: tuple
    alphas: tuple
    seeds: tuple
    rank: int
    iterations: int
    implicit: bool

    @property
    def candidates(self) -> int:
        return len(self.regs)


def grid_axes(engine_params_list) -> GridAxes | None:
    """``None`` unless every candidate shares datasource/preparator/
    serving params and a single same-named algorithm whose params differ
    only along ``SWEEP_AXES`` — the precondition for one vmapped train."""
    eps = list(engine_params_list)
    if not eps:
        return None

    def _stable(params) -> str:
        return json.dumps(params_to_json(params), sort_keys=True, default=str)

    shared0 = (_stable(eps[0].datasource), _stable(eps[0].preparator),
               _stable(eps[0].serving))
    name0 = static0 = None
    regs, alphas, seeds = [], [], []
    for ep in eps:
        if (_stable(ep.datasource), _stable(ep.preparator),
                _stable(ep.serving)) != shared0:
            return None
        if len(ep.algorithms) != 1:
            return None
        name, p = ep.algorithms[0]
        if name0 is None:
            name0 = name
        elif name != name0:
            return None
        rank = getattr(p, "rank", None)
        iters = getattr(p, "num_iterations", None)
        implicit = getattr(p, "implicit_prefs", None)
        if not isinstance(rank, int) or not isinstance(iters, int):
            return None
        pj = params_to_json(p)
        static = {k: v for k, v in pj.items() if k not in SWEEP_AXES}
        if static0 is None:
            static0 = static
        elif static != static0:
            return None
        regs.append(float(getattr(p, "lambda_", 0.0) or 0.0))
        alphas.append(float(getattr(p, "alpha", 1.0) or 1.0))
        seeds.append(int(getattr(p, "seed", 0) or 0))
    return GridAxes(
        regs=tuple(regs),
        alphas=tuple(alphas),
        seeds=tuple(seeds),
        rank=int(rank),
        iterations=int(iters),
        implicit=bool(implicit),
    )


# ------------------------------------------------------------------ kernels
@functools.partial(
    jax.jit, static_argnames=("rank", "iterations", "implicit", "k")
)
def grid_train_eval(
    R, M, T, seen, user_w, item_valid, regs, alphas, seeds,
    *, rank, iterations, implicit, k,
):
    """Train C dense-ALS candidates on one fold and score precision@k,
    all inside one program.

    Arrays: ``R``/``M``/``T``/``seen`` are ``[U_pad, I_pad]`` (ratings,
    observed mask, held-out positives, train-seen mask), ``user_w`` is
    the ``[U_pad]`` eval-user weight, ``item_valid`` masks padding
    columns, and ``regs``/``alphas``/``seeds`` are the ``[C]`` candidate
    axes. Returns ``[C]`` fold scores.
    """
    eye = jnp.eye(rank, dtype=jnp.float32)

    def solve_side(Rm, Mm, F, reg, alpha):
        if implicit:
            # Hu-Koren-Volinsky: confidence c = 1 + alpha*r on observed
            # cells, preference p = 1 observed / 0 elsewhere
            G = (
                F.T @ F
                + alpha * jnp.einsum("ui,ik,il->ukl", Rm * Mm, F, F)
                + reg * eye
            )
            B = ((1.0 + alpha * Rm) * Mm) @ F
        else:
            G = jnp.einsum("ui,ik,il->ukl", Mm, F, F) + reg * eye
            B = (Rm * Mm) @ F
        return jnp.linalg.solve(G, B[..., None])[..., 0]

    def one(reg, alpha, seed):
        key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
        ku, ki = jax.random.split(key)
        X = 0.1 * jax.random.normal(ku, (R.shape[0], rank), jnp.float32)
        Y = 0.1 * jax.random.normal(ki, (R.shape[1], rank), jnp.float32)

        def body(_, carry):
            X, Y = carry
            X = solve_side(R, M, Y, reg, alpha)
            Y = solve_side(R.T, M.T, X, reg, alpha)
            return X, Y

        X, Y = jax.lax.fori_loop(0, iterations, body, (X, Y))
        scores = X @ Y.T
        # PrecisionAtK semantics: train-seen items are skipped (not
        # penalized), padding columns can never be served
        blocked = (seen > 0) | (item_valid < 0.5)[None, :]
        scores = jnp.where(blocked, -jnp.inf, scores)
        top_idx = jax.lax.top_k(scores, k)[1]
        hits = jnp.take_along_axis(T, top_idx, axis=1).sum(axis=1)
        prec = hits / float(k)
        return (user_w * prec).sum() / jnp.maximum(user_w.sum(), 1.0)

    return jax.vmap(one)(regs, alphas, seeds)


def fold_arrays(td, qa_pairs, k: int):
    """One eval fold -> padded dense arrays for :func:`grid_train_eval`.

    Duck-typed over the recommendation template's shapes (``td`` COO +
    BiMaps, ``qa_pairs`` of ``(Query, Actual)``) without importing
    templates/ (forbidden by manifest). Returns ``(arrays, n_eval_users,
    k_eff)`` — ``None`` arrays when the fold has no usable eval users.
    """
    n_users = len(td.user_index)
    n_items = len(td.item_index)
    if not n_users or not n_items:
        return None, 0, 0
    U, I = _pow2(n_users), _pow2(n_items)
    R = np.zeros((U, I), np.float32)
    M = np.zeros((U, I), np.float32)
    rows = np.asarray(td.rows, np.int64)
    cols = np.asarray(td.cols, np.int64)
    R[rows, cols] = np.asarray(td.vals, np.float32)
    M[rows, cols] = 1.0
    T = np.zeros((U, I), np.float32)
    seen = np.zeros((U, I), np.float32)
    user_w = np.zeros((U,), np.float32)
    for q, a in qa_pairs:
        uid = td.user_index.get(getattr(q, "user", None))
        if uid is None:
            continue
        user_w[uid] = 1.0
        for it in getattr(a, "items", ()) or ():
            iid = td.item_index.get(it)
            if iid is not None:
                T[uid, iid] = 1.0
        for it in getattr(a, "seen", ()) or ():
            iid = td.item_index.get(it)
            if iid is not None:
                seen[uid, iid] = 1.0
    n_eval = int(user_w.sum())
    if not n_eval:
        return None, 0, 0
    item_valid = np.zeros((I,), np.float32)
    item_valid[:n_items] = 1.0
    k_eff = max(1, min(int(k), n_items))
    arrays = dict(
        R=R, M=M, T=T, seen=seen, user_w=user_w, item_valid=item_valid
    )
    return arrays, n_eval, k_eff


# ------------------------------------------------------------------- runner
def run_grid_evaluation(
    evaluation,
    generator,
    ctx,
    workflow_params=None,
    evaluation_class: str = "",
    generator_class: str = "",
):
    """``pio eval --grid``: :func:`run_evaluation` parity (same
    ``EvaluationInstance`` lifecycle, same ``(instance, result)``
    return) with the candidate loop replaced by one vmapped program per
    fold when the grid allows it."""
    import datetime as _dt

    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.base import EvaluationInstance
    from predictionio_tpu.workflow.core import WorkflowParams

    if workflow_params is None:
        workflow_params = WorkflowParams()

    def _now():
        return _dt.datetime.now(_dt.timezone.utc)

    eps_list = list(generator.engine_params_list)
    axes = grid_axes(eps_list)
    instances = Storage.get_meta_data_evaluation_instances()
    instance = EvaluationInstance(
        id=uuid.uuid4().hex,
        status="EVALUATING",
        start_time=_now(),
        end_time=_now(),
        evaluation_class=evaluation_class or type(evaluation).__name__,
        engine_params_generator_class=(
            generator_class or type(generator).__name__
        ),
        batch=workflow_params.batch,
    )
    instances.insert(instance)
    try:
        result = None
        if axes is not None:
            result = _vmapped_sweep(evaluation, eps_list, axes, ctx)
        if result is None:
            logger.info(
                "--grid: candidates are not vmap-compatible (or the fold "
                "blows the dense budget); sequential MetricEvaluator"
            )
            evaluator = MetricEvaluator(
                metric=evaluation.metric,
                other_metrics=tuple(evaluation.other_metrics),
            )
            result = evaluator.evaluate_base(ctx, evaluation.engine, eps_list)
        instance = dataclasses.replace(
            instance,
            status="EVALCOMPLETED",
            end_time=_now(),
            evaluator_results=result.leaderboard(),
            evaluator_results_json=json.dumps(result.to_json(), default=str),
        )
        instances.update(instance)
        return instance, result
    except Exception:
        instances.update(
            dataclasses.replace(instance, status="FAILED", end_time=_now())
        )
        raise


def _vmapped_sweep(evaluation, eps_list, axes: GridAxes, ctx):
    """Score the whole grid via :func:`grid_train_eval`; ``None`` when a
    fold exceeds the dense-cell budget (caller falls back)."""
    engine = evaluation.engine
    metric = evaluation.metric
    k = int(getattr(metric, "k", 10) or 10)
    folds = engine.read_eval_folds(ctx, eps_list[0])
    C = axes.candidates
    prepared = []
    for td, _info, qa in folds:
        arrays, n_eval, k_eff = fold_arrays(td, qa, k)
        if arrays is None:
            continue
        if C * arrays["R"].size > MAX_GRID_CELLS:
            logger.info(
                "--grid: fold of %s cells x %d candidates exceeds the dense "
                "budget (%d)", arrays["R"].size, C, MAX_GRID_CELLS,
            )
            return None
        prepared.append((arrays, n_eval, k_eff))
    if not prepared:
        return None
    t0 = time.perf_counter()
    regs = jnp.asarray(axes.regs, jnp.float32)
    alphas = jnp.asarray(axes.alphas, jnp.float32)
    seeds = jnp.asarray(axes.seeds, jnp.int32)
    num = np.zeros(C, np.float64)
    den = 0.0
    for arrays, n_eval, k_eff in prepared:
        scores = grid_train_eval(
            jnp.asarray(arrays["R"]),
            jnp.asarray(arrays["M"]),
            jnp.asarray(arrays["T"]),
            jnp.asarray(arrays["seen"]),
            jnp.asarray(arrays["user_w"]),
            jnp.asarray(arrays["item_valid"]),
            regs, alphas, seeds,
            rank=axes.rank,
            iterations=axes.iterations,
            implicit=axes.implicit,
            k=k_eff,
        )
        num += np.asarray(scores, np.float64) * n_eval
        den += n_eval
    elapsed = time.perf_counter() - t0
    avg = num / max(den, 1.0)

    def better(i: int, j: int) -> bool:
        a, b = float(avg[i]), float(avg[j])
        a_nan, b_nan = a != a, b != b
        if a_nan or b_nan:
            return b_nan and not a_nan
        return metric.compare(a, b) > 0

    order = sorted(
        range(C),
        key=functools.cmp_to_key(
            lambda i, j: -1 if better(i, j) else (1 if better(j, i) else 0)
        ),
    )
    best = order[0]
    per_cand = round(elapsed / C, 3)
    scored = tuple(
        (ep, MetricScores(float(avg[i]), (), per_cand))
        for i, ep in enumerate(eps_list)
    )
    logger.info(
        "--grid: %d candidates x %d folds in one vmapped program per fold "
        "(%.2fs total); best candidate[%d] score=%.6f",
        C, len(prepared), elapsed, best, float(avg[best]),
    )
    return MetricEvaluatorResult(
        best_score=scored[best][1],
        best_engine_params=eps_list[best],
        best_index=best,
        metric_header=metric.header(),
        other_metric_headers=(),
        engine_params_scores=scored,
        ranking=tuple(order),
    )
