"""Replica-fleet serving: router, health gating, rolling model swap.

One process — even a multi-device mesh process (``--shard-factors``) —
is still one SIGKILL away from an outage. This package composes **R
replicas** behind a router so the served product survives any single
replica dying, reloading, or draining (ROADMAP item 1; the serving-fleet
topology of PredictionIO's query-server tier, scaled the way ALX scales
model-parallel serving beyond one host — PAPERS.md):

* :mod:`predictionio_tpu.fleet.ring` — consistent-hash-by-cache-scope
  routing, so PR 4's result cache *shards* across replicas instead of
  duplicating (a scope's repeated queries always land on the same
  replica) and a membership change remaps only ~1/R of scopes;
* :mod:`predictionio_tpu.fleet.router` — the router process behind
  ``pio deploy --replicas N``: per-replica health tracking (active
  ``/readyz`` probes + passive failure counting + a
  :class:`~predictionio_tpu.resilience.CircuitBreaker` per backend),
  bounded same-query failover for idempotent requests, ``Retry-After``-
  aware draining avoidance, optional p95-triggered hedged requests,
  invalidation broadcast, and router-orchestrated rolling ``/reload``;
* :mod:`predictionio_tpu.fleet.registry` — a generation-stamped model
  registry over shared-filesystem storage, so every replica of a fleet
  (and every fleet of a cluster) agrees on which model generation is
  being rolled out — plus the **endpoint registry**: lease-stamped
  per-replica entry files through which replicas on ANY host
  self-report their port-0-bound address and join the ring (``pio
  deploy --endpoint-registry DIR``), with expiry-based eviction claimed
  exactly once across an HA router pair;
* :mod:`predictionio_tpu.fleet.supervisor` — spawns the N query-server
  subprocesses, respawns any that die, and records the fleet topology
  where operators (``pio status``) and the chaos drills
  (``pio chaos-serve``, ``pio chaos-fleet``) can find it; the
  autoscaler adds/retires replicas through it at runtime;
* :mod:`predictionio_tpu.fleet.autoscaler` — watermark-driven elastic
  capacity (``--autoscale MIN:MAX``): scale-up on q/s or p99 pressure,
  drain-aware scale-down that loses zero in-flight queries.

Stdlib-only by contract (piolint manifest): the fleet layer is host
orchestration over HTTP and must run with no jax, numpy, or storage
imports — replicas are opaque processes behind URLs. The only framework
imports allowed are the equally stdlib-only resilience primitives, the
HTTP transport, and ``serving.cache``'s key helpers. Everything is
strictly opt-in: without ``--replicas`` nothing here is ever imported
and serving is byte-identical (tests/test_ci_guards.py).
"""

from __future__ import annotations

from predictionio_tpu.fleet.autoscaler import Autoscaler, AutoscalerConfig
from predictionio_tpu.fleet.registry import (
    EndpointRecord,
    EndpointRegistry,
    ModelRegistry,
    RegistryRecord,
)
from predictionio_tpu.fleet.ring import HashRing
from predictionio_tpu.fleet.router import (
    ReplicaState,
    RouterConfig,
    RouterService,
)
from predictionio_tpu.fleet.supervisor import (
    FleetSupervisor,
    ReplicaSpec,
    fleet_state_path,
    read_fleet_state,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "EndpointRecord",
    "EndpointRegistry",
    "FleetSupervisor",
    "HashRing",
    "ModelRegistry",
    "RegistryRecord",
    "ReplicaSpec",
    "ReplicaState",
    "RouterConfig",
    "RouterService",
    "fleet_state_path",
    "read_fleet_state",
]
