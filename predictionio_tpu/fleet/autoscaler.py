"""Watermark autoscaler: spawn/retire replicas on q/s + p99 pressure.

The autoscaler closes the loop between the router's observed load and
the supervisor's process control (``pio deploy --replicas N --autoscale
MIN:MAX``): each interval it reads the router's trailing-window load
snapshot (queries/second and p99 latency), and

* **scales up** when per-replica q/s exceeds ``scale_up_qps`` OR p99
  exceeds ``scale_up_p99_ms`` — one replica at a time, up to ``max``;
  the new replica binds port 0, self-reports through the shared
  :class:`~predictionio_tpu.fleet.registry.EndpointRegistry`, and joins
  the ring at the router's next reconcile;
* **scales down** when per-replica q/s falls below ``scale_down_qps``
  (and p99 is calm) — **drain-aware**: retirement is a SIGTERM, so the
  replica finishes its in-flight queries (PR 5's ``--drain-deadline-s``
  contract), answers new work with drain 503s the router treats as a
  routing signal, withdraws its own registry entry on clean exit, and
  only then disappears from the ring. Zero in-flight queries are lost;
  ``pio chaos-fleet`` asserts it.

Decisions are damped three ways so the fleet cannot flap: a cooldown
after every action, a floor of ``min`` replicas, and scale-down only
when the fleet is at steady state (no replica currently retiring).

Stdlib-only by contract, like the rest of the fleet package.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable

from predictionio_tpu.fleet.supervisor import FleetSupervisor, ReplicaSpec

__all__ = ["Autoscaler", "AutoscalerConfig"]

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Watermarks and damping (CLI: ``--autoscale MIN:MAX`` + knobs)."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: per-replica q/s above which one replica is added
    scale_up_qps: float = 50.0
    #: p99 latency (ms) above which one replica is added regardless of q/s
    scale_up_p99_ms: float = 250.0
    #: per-replica q/s below which one replica is drained away
    scale_down_qps: float = 5.0
    #: seconds between scaling actions (damping)
    cooldown_s: float = 10.0
    #: seconds between load evaluations
    interval_s: float = 1.0
    #: trailing window the load snapshot aggregates over
    window_s: float = 5.0

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.scale_down_qps >= self.scale_up_qps:
            raise ValueError(
                "scale_down_qps must be < scale_up_qps (hysteresis band)"
            )
        if self.interval_s <= 0 or self.cooldown_s < 0:
            raise ValueError("interval_s must be > 0, cooldown_s >= 0")


class Autoscaler:
    """Periodic evaluate→act loop over (router load, supervisor fleet).

    ``spawn_spec`` mints the launch recipe for a new replica id — the
    console builds it from the operator's own deploy flags, so scaled-up
    replicas compose with ``--shard-factors``/``--quantize``/... exactly
    like the initial fleet.
    """

    def __init__(
        self,
        router,  # RouterService (duck-typed: load_snapshot())
        supervisor: FleetSupervisor,
        spawn_spec: Callable[[str], ReplicaSpec],
        config: AutoscalerConfig | None = None,
    ):
        self.router = router
        self.supervisor = supervisor
        self.spawn_spec = spawn_spec
        self.config = config or AutoscalerConfig()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._next_id = 0
        self._last_action_at = 0.0  # monotonic; 0 = never acted
        self._history: list[dict] = []  # bounded action log
        self.scale_ups = 0
        self.scale_downs = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="fleet-autoscaler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.evaluate_once()
            except Exception as e:  # the loop must survive any one tick
                logger.error("autoscaler tick failed: %s", e)

    # ------------------------------------------------------------- decision
    def _fleet_size(self) -> int:
        with self.supervisor._lock:
            return len(self.supervisor.specs)

    def _mint_replica_id(self) -> str:
        with self.supervisor._lock:
            taken = {s.replica_id for s in self.supervisor.specs}
        while True:
            with self._lock:
                self._next_id += 1
                rid = f"scale{self._next_id}"
            if rid not in taken:
                return rid

    def decide(self, load: dict, size: int) -> str:
        """Pure watermark decision: ``"up"``, ``"down"``, or ``"hold"``."""
        if size < self.config.min_replicas:
            return "up"
        qps_per_replica = load.get("qps", 0.0) / max(1, size)
        p99_ms = load.get("p99Seconds", 0.0) * 1000.0
        if size < self.config.max_replicas and (
            qps_per_replica > self.config.scale_up_qps
            or p99_ms > self.config.scale_up_p99_ms
        ):
            return "up"
        if (
            size > self.config.min_replicas
            and qps_per_replica < self.config.scale_down_qps
            and p99_ms <= self.config.scale_up_p99_ms
        ):
            return "down"
        return "hold"

    def evaluate_once(self) -> dict:
        """One evaluate→act tick; returns what happened (for tests and
        ``/fleet/endpoints.json``-adjacent observability)."""
        now = time.monotonic()
        load = self.router.load_snapshot(self.config.window_s)
        size = self._fleet_size()
        action = self.decide(load, size)
        cooled = now - self._last_action_at >= self.config.cooldown_s
        outcome = {
            "action": action,
            "applied": False,
            "size": size,
            "qps": round(load.get("qps", 0.0), 3),
            "p99Ms": round(load.get("p99Seconds", 0.0) * 1000.0, 3),
        }
        if action == "hold" or not cooled:
            if action != "hold":
                outcome["action"] = f"{action}_cooldown"
            return self._record(outcome)
        if action == "down" and self.supervisor.retiring_count() > 0:
            # steady-state gate: never stack drains — a second retirement
            # while one replica is still draining could dip capacity two
            # replicas below the decision's basis
            outcome["action"] = "down_waiting_drain"
            return self._record(outcome)
        if action == "up":
            rid = self._mint_replica_id()
            spec = self.spawn_spec(rid)
            self.supervisor.add_replica(spec)
            self.scale_ups += 1
            outcome.update(applied=True, replicaId=rid, size=size + 1)
            logger.info(
                "scale-up → %d replicas (qps=%.1f p99=%.0fms): spawned %s",
                size + 1, load.get("qps", 0.0), outcome["p99Ms"], rid,
            )
        else:
            rid = self._pick_retiree()
            if rid is None:
                return self._record(outcome)
            if self.supervisor.retire_replica(rid):
                self.scale_downs += 1
                outcome.update(applied=True, replicaId=rid, size=size - 1)
                logger.info(
                    "scale-down → %d replicas (qps=%.1f): draining %s",
                    size - 1, load.get("qps", 0.0), rid,
                )
        with self._lock:
            self._last_action_at = time.monotonic()
        return self._record(outcome)

    def _pick_retiree(self) -> str | None:
        """Retire the youngest scaled-up replica first (``scaleN`` ids),
        falling back to the highest-numbered original — the initial
        fleet's low-numbered replicas are the last to go."""
        with self.supervisor._lock:
            ids = [s.replica_id for s in self.supervisor.specs]
        if not ids:
            return None
        scaled = sorted(
            (i for i in ids if i.startswith("scale")), reverse=True
        )
        return scaled[0] if scaled else sorted(ids)[-1]

    def _record(self, outcome: dict) -> dict:
        with self._lock:
            self._history.append(outcome)
            del self._history[:-100]
        return outcome

    def to_json(self) -> dict:
        with self._lock:
            history = list(self._history[-20:])
        return {
            "config": dataclasses.asdict(self.config),
            "scaleUps": self.scale_ups,
            "scaleDowns": self.scale_downs,
            "recent": history,
        }
