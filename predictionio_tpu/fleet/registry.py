"""Shared-filesystem fleet registries: model generations and endpoints.

Two ledgers live here, both plain JSON on a filesystem every fleet host
mounts (the same sharedfs idiom the storage layer's ``TYPE=sharedfs``
driver uses), both readable with nothing installed:

* :class:`ModelRegistry` — ONE document answering "which model should
  every replica be serving?" (generation-stamped, atomic rename).
* :class:`EndpointRegistry` — a DIRECTORY of per-replica entry files
  answering "which replicas exist right now, and where?". Replicas bind
  port 0, then announce their *actually bound* address here (closing the
  pick-then-spawn loopback TOCTOU for good: nothing ever picks a port it
  has not already bound), and keep the entry alive with heartbeat
  leases. Routers on ANY host reconcile their consistent-hash ring from
  the live entries; an entry whose lease expired is **evicted exactly
  once** across however many routers share the directory (atomic
  rename-claim), so an HA router pair never double-counts a membership
  change. Torn or unparsable entry files are surfaced as loud
  ``problems``, never silently skipped.

A fleet needs one answer to "which model should every replica be
serving?". Each replica's in-process reload counter says where *that
process* is; the registry says where the *fleet* should converge:

* ``publish(instance_id)`` — stamp a new fleet generation pointing at a
  trained engine instance. Atomic (tmp + fsync + rename) so a reader
  never sees a torn record; the generation counter is monotonic even
  across concurrent publishers (last writer wins the pointer, but never
  reuses a generation number).
* ``current()`` — the record replicas/routers/operators gate on.
* ``history()`` — recent generations, newest first (bounded), so a
  rollback target is always one read away.

The router's rolling ``/reload`` stamps the registry before rotating
replicas, then verifies every replica reports the fleet generation on
``/readyz`` — "rollout complete" is a registry⇄fleet convergence check,
not a hope (docs/operations.md, fleet runbook).

Stdlib-only by contract: the registry must be readable from the router,
``pio status``, and CI hosts with nothing installed.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import os
import tempfile
import time
from typing import Any

__all__ = [
    "EndpointRecord",
    "EndpointRegistry",
    "ModelRegistry",
    "RegistryRecord",
]

_HISTORY_LIMIT = 50


def _fsync_dir(directory: str) -> None:
    """Make a just-renamed directory entry durable: without this, the
    rename itself lives only in the page cache and a crash can forget
    the file ever had its new name (PIO502)."""
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


@dataclasses.dataclass(frozen=True)
class RegistryRecord:
    """One published fleet generation."""

    generation: int
    engine_instance_id: str
    published_at: str  # ISO-8601 UTC
    meta: dict | None = None

    def to_json(self) -> dict:
        out: dict[str, Any] = {
            "generation": self.generation,
            "engineInstanceId": self.engine_instance_id,
            "publishedAt": self.published_at,
        }
        if self.meta:
            out["meta"] = dict(self.meta)
        return out

    @staticmethod
    def from_json(d: dict) -> "RegistryRecord":
        return RegistryRecord(
            generation=int(d["generation"]),
            engine_instance_id=str(d["engineInstanceId"]),
            published_at=str(d.get("publishedAt", "")),
            meta=d.get("meta"),
        )


class ModelRegistry:
    """The fleet's model-generation ledger at ``<dir>/model-registry.json``."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, "model-registry.json")

    # --------------------------------------------------------------- read
    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return {"current": None, "history": []}
        except (json.JSONDecodeError, OSError):
            # a torn read can only happen if rename atomicity was violated
            # (non-POSIX mount): treat as empty rather than wedging the
            # fleet on a parse error; the next publish rewrites it whole
            return {"current": None, "history": []}
        if not isinstance(doc, dict):
            return {"current": None, "history": []}
        return doc

    def current(self) -> RegistryRecord | None:
        cur = self._load().get("current")
        if not isinstance(cur, dict):
            return None
        try:
            return RegistryRecord.from_json(cur)
        except (KeyError, ValueError, TypeError):
            return None

    def history(self) -> list[RegistryRecord]:
        out = []
        for d in self._load().get("history", []):
            try:
                out.append(RegistryRecord.from_json(d))
            except (KeyError, ValueError, TypeError):
                continue
        return out

    # -------------------------------------------------------------- write
    def publish(
        self, engine_instance_id: str, meta: dict | None = None
    ) -> RegistryRecord:
        """Stamp the next fleet generation. Atomic rename; fsync'd so an
        acked publish survives a host crash (same durability contract as
        the model blobs it points at)."""
        doc = self._load()
        prev = doc.get("current") or {}
        generation = int(prev.get("generation", 0)) + 1
        record = RegistryRecord(
            generation=generation,
            engine_instance_id=engine_instance_id,
            published_at=_dt.datetime.now(_dt.timezone.utc).isoformat(),
            meta=meta,
        )
        history = [record.to_json()] + list(doc.get("history", []))
        new_doc = {
            "current": record.to_json(),
            "history": history[:_HISTORY_LIMIT],
        }
        os.makedirs(self.directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            prefix=".model-registry.", suffix=".tmp", dir=self.directory
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(new_doc, f, indent=2)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            _fsync_dir(self.directory)
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
        return record


# ---------------------------------------------------------------------------
# Endpoint registry (cross-host replica discovery; ISSUE 17)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EndpointRecord:
    """One replica's self-reported address + lease."""

    replica_id: str
    host: str
    port: int
    generation: int = 0
    #: wall-clock epoch seconds the lease expires at (wall clock, not
    #: monotonic: the whole point is cross-process, cross-host validity)
    lease_expires: float = 0.0
    announced_at: float = 0.0
    meta: dict | None = None

    def to_json(self) -> dict:
        out: dict[str, Any] = {
            "replicaId": self.replica_id,
            "host": self.host,
            "port": self.port,
            "generation": self.generation,
            "leaseExpires": self.lease_expires,
            "announcedAt": self.announced_at,
        }
        if self.meta:
            out["meta"] = dict(self.meta)
        return out

    @staticmethod
    def from_json(d: dict) -> "EndpointRecord":
        return EndpointRecord(
            replica_id=str(d["replicaId"]),
            host=str(d["host"]),
            port=int(d["port"]),
            generation=int(d.get("generation", 0)),
            lease_expires=float(d.get("leaseExpires", 0.0)),
            announced_at=float(d.get("announcedAt", 0.0)),
            meta=d.get("meta"),
        )

    def lease_age_s(self, now: float | None = None) -> float:
        """Seconds since the entry was last (re)announced."""
        return max(0.0, (time.time() if now is None else now) - self.announced_at)

    def live(self, now: float | None = None) -> bool:
        return (time.time() if now is None else now) < self.lease_expires


class EndpointRegistry:
    """Directory of lease-stamped endpoint entries, one file per replica.

    Every write is atomic (tmp + fsync + rename in the same directory),
    so a reader sees either the previous whole entry or the next whole
    entry — two writers racing on the same ``replica_id`` converge on
    whichever rename lands last, never on a torn file. Filenames are
    derived from the replica id through a character allow-list, so an
    adversarial id cannot escape the directory.
    """

    #: entry filename suffix — anything else in the directory is ignored
    SUFFIX = ".endpoint.json"
    #: suffix an eviction claim renames the losing entry to before unlink
    _EVICT_SUFFIX = ".evicting"

    def __init__(self, directory: str, lease_ttl_s: float = 5.0):
        if lease_ttl_s <= 0:
            raise ValueError("lease_ttl_s must be > 0")
        self.directory = directory
        self.lease_ttl_s = float(lease_ttl_s)
        self._claim_seq = 0

    # --------------------------------------------------------------- paths
    def _entry_path(self, replica_id: str) -> str:
        safe = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in replica_id
        )[:128]
        if not safe:
            raise ValueError(f"unusable replica id {replica_id!r}")
        return os.path.join(self.directory, safe + self.SUFFIX)

    # --------------------------------------------------------------- write
    def announce(
        self,
        replica_id: str,
        host: str,
        port: int,
        generation: int = 0,
        meta: dict | None = None,
        now: float | None = None,
    ) -> EndpointRecord:
        """Publish (or renew — a heartbeat IS a re-announce) one
        replica's bound address with a fresh lease."""
        now = time.time() if now is None else now
        record = EndpointRecord(
            replica_id=replica_id,
            host=host,
            port=int(port),
            generation=int(generation),
            lease_expires=now + self.lease_ttl_s,
            announced_at=now,
            meta=meta,
        )
        os.makedirs(self.directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            prefix=".endpoint.", suffix=".tmp", dir=self.directory
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(record.to_json(), f)
                f.flush()
                os.fsync(f.fileno())
            # piolint: waive=PIO502 -- leases are ephemeral by contract: a crash-forgotten rename is indistinguishable from lease expiry, which every reader tolerates, and announce/heartbeat is the TTL/3 hot path where a per-beat dir fsync would tax the whole fleet
            os.replace(tmp, self._entry_path(replica_id))
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
        return record

    def heartbeat(
        self,
        replica_id: str,
        host: str,
        port: int,
        generation: int = 0,
        meta: dict | None = None,
    ) -> EndpointRecord:
        """Lease renewal — an atomic whole-entry rewrite, so a heartbeat
        racing an eviction claim simply re-creates the entry (the replica
        is alive; the claim evicted a lease that was genuinely stale when
        claimed)."""
        return self.announce(
            replica_id, host, port, generation=generation, meta=meta
        )

    def withdraw(self, replica_id: str) -> bool:
        """Clean retirement: remove the entry now instead of letting the
        lease run out. Returns whether an entry was actually removed."""
        try:
            os.unlink(self._entry_path(replica_id))
            return True
        except FileNotFoundError:
            return False

    # ---------------------------------------------------------------- read
    def snapshot(
        self, now: float | None = None
    ) -> tuple[list[EndpointRecord], list[EndpointRecord], list[dict]]:
        """Read every entry: ``(live, expired, problems)``.

        ``problems`` carries one dict per torn/unparsable entry file —
        loud, never silently dropped: ``pio status`` prints them and the
        router surfaces them on ``/fleet/endpoints.json``. Expired
        entries are returned separately so callers can distinguish "gone"
        from "lease ran out but not yet evicted"."""
        now = time.time() if now is None else now
        live: list[EndpointRecord] = []
        expired: list[EndpointRecord] = []
        problems: list[dict] = []
        try:
            names = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return [], [], []
        for name in names:
            if not name.endswith(self.SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path) as f:
                    record = EndpointRecord.from_json(json.load(f))
            except FileNotFoundError:
                continue  # lost a race with withdraw/evict — fine
            except (json.JSONDecodeError, KeyError, TypeError, ValueError,
                    OSError) as e:
                problems.append(
                    {"file": name, "error": f"{type(e).__name__}: {e}"[:200]}
                )
                continue
            (live if record.live(now) else expired).append(record)
        return live, expired, problems

    def live(self, now: float | None = None) -> list[EndpointRecord]:
        return self.snapshot(now)[0]

    # ---------------------------------------------------------------- evict
    def evict_expired(self, now: float | None = None) -> list[str]:
        """Remove entries whose lease expired (and torn entry files older
        than one lease), returning the replica ids THIS caller evicted.

        Exactly-once across concurrent callers: each eviction first
        claims the entry with an atomic ``os.rename`` to a caller-unique
        name — of N racing routers exactly one rename succeeds, and only
        the winner counts (and unlinks) the eviction. The losers see
        ``FileNotFoundError`` and report nothing, so an HA router pair
        never double-counts one membership change."""
        now = time.time() if now is None else now
        evicted: list[str] = []
        try:
            names = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return []
        for name in names:
            if not name.endswith(self.SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            stale_unparsable = False
            try:
                with open(path) as f:
                    record = EndpointRecord.from_json(json.load(f))
            except FileNotFoundError:
                continue
            except (json.JSONDecodeError, KeyError, TypeError, ValueError,
                    OSError):
                record = None
                try:
                    stale_unparsable = (
                        now - os.path.getmtime(path) > self.lease_ttl_s
                    )
                except OSError:
                    continue
            if record is not None and record.live(now):
                continue
            if record is None and not stale_unparsable:
                continue  # torn but fresh: give its writer a lease to fix it
            self._claim_seq += 1
            claim = (
                f"{path}{self._EVICT_SUFFIX}.{os.getpid()}.{self._claim_seq}"
            )
            try:
                os.rename(path, claim)  # the atomic exactly-once gate
            except FileNotFoundError:
                continue  # another router (or a heartbeat) won this entry
            except OSError:
                continue
            try:
                os.unlink(claim)
            except OSError:
                pass
            evicted.append(
                record.replica_id
                if record is not None
                else name[: -len(self.SUFFIX)]
            )
        return evicted
