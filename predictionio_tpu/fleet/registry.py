"""Shared-filesystem fleet registries: model generations and endpoints.

Two ledgers live here, both plain JSON on a filesystem every fleet host
mounts (the same sharedfs idiom the storage layer's ``TYPE=sharedfs``
driver uses), both readable with nothing installed:

* :class:`ModelRegistry` — ONE document answering "which model should
  every replica be serving?" (generation-stamped, atomic rename).
* :class:`EndpointRegistry` — a DIRECTORY of per-replica entry files
  answering "which replicas exist right now, and where?". Replicas bind
  port 0, then announce their *actually bound* address here (closing the
  pick-then-spawn loopback TOCTOU for good: nothing ever picks a port it
  has not already bound), and keep the entry alive with heartbeat
  leases. Routers on ANY host reconcile their consistent-hash ring from
  the live entries; an entry whose lease expired is **evicted exactly
  once** across however many routers share the directory (atomic
  rename-claim), so an HA router pair never double-counts a membership
  change. Torn or unparsable entry files are surfaced as loud
  ``problems``, never silently skipped.

A fleet needs one answer to "which model should every replica be
serving?". Each replica's in-process reload counter says where *that
process* is; the registry says where the *fleet* should converge:

* ``publish(instance_id)`` — stamp a new fleet generation pointing at a
  trained engine instance. Atomic (tmp + fsync + rename) so a reader
  never sees a torn record; the generation counter is monotonic even
  across concurrent publishers (last writer wins the pointer, but never
  reuses a generation number).
* ``current()`` — the record replicas/routers/operators gate on.
* ``history()`` — recent generations, newest first (bounded), so a
  rollback target is always one read away.

The router's rolling ``/reload`` stamps the registry before rotating
replicas, then verifies every replica reports the fleet generation on
``/readyz`` — "rollout complete" is a registry⇄fleet convergence check,
not a hope (docs/operations.md, fleet runbook).

Stdlib-only by contract: the registry must be readable from the router,
``pio status``, and CI hosts with nothing installed.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import hashlib
import json
import os
import shutil
import tempfile
import time
from typing import Any

__all__ = [
    "AOT_MANIFEST_NAME",
    "EndpointRecord",
    "EndpointRegistry",
    "ModelRegistry",
    "RegistryRecord",
    "aot_artifact_dir",
    "read_aot_manifest",
    "verify_aot_artifacts",
]

_HISTORY_LIMIT = 50


def _fsync_dir(directory: str) -> None:
    """Make a just-renamed directory entry durable: without this, the
    rename itself lives only in the page cache and a crash can forget
    the file ever had its new name (PIO502)."""
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# AOT artifact schema (read side — stdlib by contract)
# ---------------------------------------------------------------------------
#
# ``pio train --aot`` (workflow/aot.py, the jax write side) serializes
# each generation's serving programs into ``<root>/<instance>/`` beside a
# ``manifest.json`` carrying the environment fingerprint and per-blob
# SHA-256 + byte-size records. The READ side lives here because the
# consumers that gate on artifact readiness — the router's rolling-reload
# gate, ``pio status`` — are stdlib-only by manifest: presence, parse,
# size, and digest checks need hashlib+json, nothing more. Fingerprint
# MATCHING against the live jax environment is the replica's job at
# deserialize time (it has jax by definition); a reader here only
# reports the manifest's fingerprint for display/compare.

AOT_MANIFEST_NAME = "manifest.json"


def aot_artifact_dir(root: str, engine_instance_id: str) -> str:
    """``<root>/<instance>`` through a character allow-list, so an
    adversarial instance id cannot escape the artifact root (same
    contract as endpoint entry filenames)."""
    safe = "".join(
        c if c.isalnum() or c in "._-" else "_" for c in engine_instance_id
    )[:128]
    if not safe:
        raise ValueError(f"unusable engine instance id {engine_instance_id!r}")
    return os.path.join(root, safe)


def read_aot_manifest(instance_dir: str) -> dict | None:
    """The artifact manifest, or None when absent/torn."""
    try:
        with open(os.path.join(instance_dir, AOT_MANIFEST_NAME)) as f:
            doc = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None
    return doc if isinstance(doc, dict) else None


def verify_aot_artifacts(instance_dir: str, deep: bool = True) -> dict:
    """Pure-stdlib readiness check of one artifact directory: manifest
    present + parseable, every blob present with the manifested size,
    and (``deep``) a matching SHA-256. Returns ``{"ok": bool,
    "problems": [...], "programs": N, "bytes": N, "fingerprint": {...}}``."""
    problems: list[str] = []
    manifest = read_aot_manifest(instance_dir)
    if manifest is None:
        return {
            "ok": False,
            "problems": [f"missing or torn {AOT_MANIFEST_NAME}"],
            "programs": 0,
            "bytes": 0,
            "fingerprint": None,
        }
    total = 0
    entries = manifest.get("entries", [])
    for entry in entries:
        path = os.path.join(instance_dir, entry.get("file", ""))
        try:
            size = os.path.getsize(path)
        except OSError:
            problems.append(f"missing blob {entry.get('file')}")
            continue
        if size != entry.get("bytes"):
            problems.append(
                f"size mismatch {entry.get('file')}: "
                f"{size} != {entry.get('bytes')}"
            )
            continue
        if deep:
            h = hashlib.sha256()
            try:
                with open(path, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
            except OSError as e:
                problems.append(f"unreadable blob {entry.get('file')}: {e}")
                continue
            if h.hexdigest() != entry.get("sha256"):
                problems.append(f"digest mismatch {entry.get('file')}")
                continue
        total += size
    if not entries:
        problems.append("manifest lists no programs")
    return {
        "ok": not problems,
        "problems": problems,
        "programs": len(entries),
        "bytes": total,
        "fingerprint": manifest.get("fingerprint"),
    }


@dataclasses.dataclass(frozen=True)
class RegistryRecord:
    """One published fleet generation."""

    generation: int
    engine_instance_id: str
    published_at: str  # ISO-8601 UTC
    meta: dict | None = None
    #: AOT artifact stamp (``pio train --aot``): ``{"dir", "programs",
    #: "bytes", "fingerprint"}`` — the router's rolling gate and `pio
    #: status` verify readiness against this; None = generation published
    #: without AOT (replicas serve through the JIT path)
    artifacts: dict | None = None

    def to_json(self) -> dict:
        out: dict[str, Any] = {
            "generation": self.generation,
            "engineInstanceId": self.engine_instance_id,
            "publishedAt": self.published_at,
        }
        if self.meta:
            out["meta"] = dict(self.meta)
        if self.artifacts:
            out["artifacts"] = dict(self.artifacts)
        return out

    @staticmethod
    def from_json(d: dict) -> "RegistryRecord":
        return RegistryRecord(
            generation=int(d["generation"]),
            engine_instance_id=str(d["engineInstanceId"]),
            published_at=str(d.get("publishedAt", "")),
            meta=d.get("meta"),
            artifacts=d.get("artifacts"),
        )


class ModelRegistry:
    """The fleet's model-generation ledger at ``<dir>/model-registry.json``."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, "model-registry.json")

    # --------------------------------------------------------------- read
    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return {"current": None, "history": []}
        except (json.JSONDecodeError, OSError):
            # a torn read can only happen if rename atomicity was violated
            # (non-POSIX mount): treat as empty rather than wedging the
            # fleet on a parse error; the next publish rewrites it whole
            return {"current": None, "history": []}
        if not isinstance(doc, dict):
            return {"current": None, "history": []}
        return doc

    def current(self) -> RegistryRecord | None:
        cur = self._load().get("current")
        if not isinstance(cur, dict):
            return None
        try:
            return RegistryRecord.from_json(cur)
        except (KeyError, ValueError, TypeError):
            return None

    def history(self) -> list[RegistryRecord]:
        out = []
        for d in self._load().get("history", []):
            try:
                out.append(RegistryRecord.from_json(d))
            except (KeyError, ValueError, TypeError):
                continue
        return out

    # -------------------------------------------------------------- write
    def publish(
        self,
        engine_instance_id: str,
        meta: dict | None = None,
        artifacts: dict | None = None,
    ) -> RegistryRecord:
        """Stamp the next fleet generation. Atomic rename; fsync'd so an
        acked publish survives a host crash (same durability contract as
        the model blobs it points at).

        ``artifacts`` (``pio train --aot``) stamps the generation's AOT
        artifact set — ``{"dir", "programs", "bytes", "fingerprint"}`` —
        beside the instance pointer. A re-publish of an instance whose
        artifacts are already on file (e.g. the router's post-rotation
        publish) inherits the newest prior stamp automatically, so
        rolling swaps never orphan a live artifact set.

        Artifact GC rides every publish: the bounded history is the ONLY
        thing keeping artifact blobs alive, so generations evicted off
        its tail take their artifact directories with them (unless a
        surviving generation still references the same dir) — repeated
        rolling swaps cannot grow the artifact root without bound."""
        doc = self._load()
        prev = doc.get("current") or {}
        generation = int(prev.get("generation", 0)) + 1
        if artifacts is None:
            # inherit the newest prior stamp for this instance
            for d in [prev] + list(doc.get("history", [])):
                if (
                    isinstance(d, dict)
                    and d.get("engineInstanceId") == engine_instance_id
                    and d.get("artifacts")
                ):
                    artifacts = dict(d["artifacts"])
                    break
        record = RegistryRecord(
            generation=generation,
            engine_instance_id=engine_instance_id,
            published_at=_dt.datetime.now(_dt.timezone.utc).isoformat(),
            meta=meta,
            artifacts=artifacts,
        )
        history = [record.to_json()] + list(doc.get("history", []))
        kept, evicted = history[:_HISTORY_LIMIT], history[_HISTORY_LIMIT:]
        new_doc = {
            "current": record.to_json(),
            "history": kept,
        }
        os.makedirs(self.directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            prefix=".model-registry.", suffix=".tmp", dir=self.directory
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(new_doc, f, indent=2)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            _fsync_dir(self.directory)
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
        if evicted:
            self._gc_artifacts(kept, evicted)
        return record

    @staticmethod
    def _gc_artifacts(kept: list, evicted: list) -> None:
        """Delete artifact directories that left the bounded history with
        their generations. Deletion is gated twice: the dir must not be
        referenced by ANY surviving record (current is kept[0]), and it
        must actually look like an artifact set (its manifest file
        exists) — a corrupted record can never aim the rmtree at an
        arbitrary path."""
        live_dirs = {
            (d.get("artifacts") or {}).get("dir")
            for d in kept
            if isinstance(d, dict)
        }
        for d in evicted:
            if not isinstance(d, dict):
                continue
            adir = (d.get("artifacts") or {}).get("dir")
            if not adir or adir in live_dirs:
                continue
            if not os.path.isfile(os.path.join(adir, AOT_MANIFEST_NAME)):
                continue
            shutil.rmtree(adir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Endpoint registry (cross-host replica discovery; ISSUE 17)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EndpointRecord:
    """One replica's self-reported address + lease."""

    replica_id: str
    host: str
    port: int
    generation: int = 0
    #: wall-clock epoch seconds the lease expires at (wall clock, not
    #: monotonic: the whole point is cross-process, cross-host validity)
    lease_expires: float = 0.0
    announced_at: float = 0.0
    meta: dict | None = None

    def to_json(self) -> dict:
        out: dict[str, Any] = {
            "replicaId": self.replica_id,
            "host": self.host,
            "port": self.port,
            "generation": self.generation,
            "leaseExpires": self.lease_expires,
            "announcedAt": self.announced_at,
        }
        if self.meta:
            out["meta"] = dict(self.meta)
        return out

    @staticmethod
    def from_json(d: dict) -> "EndpointRecord":
        return EndpointRecord(
            replica_id=str(d["replicaId"]),
            host=str(d["host"]),
            port=int(d["port"]),
            generation=int(d.get("generation", 0)),
            lease_expires=float(d.get("leaseExpires", 0.0)),
            announced_at=float(d.get("announcedAt", 0.0)),
            meta=d.get("meta"),
        )

    def lease_age_s(self, now: float | None = None) -> float:
        """Seconds since the entry was last (re)announced."""
        return max(0.0, (time.time() if now is None else now) - self.announced_at)

    def live(self, now: float | None = None) -> bool:
        return (time.time() if now is None else now) < self.lease_expires


class EndpointRegistry:
    """Directory of lease-stamped endpoint entries, one file per replica.

    Every write is atomic (tmp + fsync + rename in the same directory),
    so a reader sees either the previous whole entry or the next whole
    entry — two writers racing on the same ``replica_id`` converge on
    whichever rename lands last, never on a torn file. Filenames are
    derived from the replica id through a character allow-list, so an
    adversarial id cannot escape the directory.
    """

    #: entry filename suffix — anything else in the directory is ignored
    SUFFIX = ".endpoint.json"
    #: suffix an eviction claim renames the losing entry to before unlink
    _EVICT_SUFFIX = ".evicting"

    def __init__(self, directory: str, lease_ttl_s: float = 5.0):
        if lease_ttl_s <= 0:
            raise ValueError("lease_ttl_s must be > 0")
        self.directory = directory
        self.lease_ttl_s = float(lease_ttl_s)
        self._claim_seq = 0

    # --------------------------------------------------------------- paths
    def _entry_path(self, replica_id: str) -> str:
        safe = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in replica_id
        )[:128]
        if not safe:
            raise ValueError(f"unusable replica id {replica_id!r}")
        return os.path.join(self.directory, safe + self.SUFFIX)

    # --------------------------------------------------------------- write
    def announce(
        self,
        replica_id: str,
        host: str,
        port: int,
        generation: int = 0,
        meta: dict | None = None,
        now: float | None = None,
    ) -> EndpointRecord:
        """Publish (or renew — a heartbeat IS a re-announce) one
        replica's bound address with a fresh lease."""
        now = time.time() if now is None else now
        record = EndpointRecord(
            replica_id=replica_id,
            host=host,
            port=int(port),
            generation=int(generation),
            lease_expires=now + self.lease_ttl_s,
            announced_at=now,
            meta=meta,
        )
        os.makedirs(self.directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            prefix=".endpoint.", suffix=".tmp", dir=self.directory
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(record.to_json(), f)
                f.flush()
                os.fsync(f.fileno())
            # piolint: waive=PIO502 -- leases are ephemeral by contract: a crash-forgotten rename is indistinguishable from lease expiry, which every reader tolerates, and announce/heartbeat is the TTL/3 hot path where a per-beat dir fsync would tax the whole fleet
            os.replace(tmp, self._entry_path(replica_id))
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
        return record

    def heartbeat(
        self,
        replica_id: str,
        host: str,
        port: int,
        generation: int = 0,
        meta: dict | None = None,
    ) -> EndpointRecord:
        """Lease renewal — an atomic whole-entry rewrite, so a heartbeat
        racing an eviction claim simply re-creates the entry (the replica
        is alive; the claim evicted a lease that was genuinely stale when
        claimed)."""
        return self.announce(
            replica_id, host, port, generation=generation, meta=meta
        )

    def withdraw(self, replica_id: str) -> bool:
        """Clean retirement: remove the entry now instead of letting the
        lease run out. Returns whether an entry was actually removed."""
        try:
            os.unlink(self._entry_path(replica_id))
            return True
        except FileNotFoundError:
            return False

    # ---------------------------------------------------------------- read
    def snapshot(
        self, now: float | None = None
    ) -> tuple[list[EndpointRecord], list[EndpointRecord], list[dict]]:
        """Read every entry: ``(live, expired, problems)``.

        ``problems`` carries one dict per torn/unparsable entry file —
        loud, never silently dropped: ``pio status`` prints them and the
        router surfaces them on ``/fleet/endpoints.json``. Expired
        entries are returned separately so callers can distinguish "gone"
        from "lease ran out but not yet evicted"."""
        now = time.time() if now is None else now
        live: list[EndpointRecord] = []
        expired: list[EndpointRecord] = []
        problems: list[dict] = []
        try:
            names = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return [], [], []
        for name in names:
            if not name.endswith(self.SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path) as f:
                    record = EndpointRecord.from_json(json.load(f))
            except FileNotFoundError:
                continue  # lost a race with withdraw/evict — fine
            except (json.JSONDecodeError, KeyError, TypeError, ValueError,
                    OSError) as e:
                problems.append(
                    {"file": name, "error": f"{type(e).__name__}: {e}"[:200]}
                )
                continue
            (live if record.live(now) else expired).append(record)
        return live, expired, problems

    def live(self, now: float | None = None) -> list[EndpointRecord]:
        return self.snapshot(now)[0]

    # ---------------------------------------------------------------- evict
    def evict_expired(self, now: float | None = None) -> list[str]:
        """Remove entries whose lease expired (and torn entry files older
        than one lease), returning the replica ids THIS caller evicted.

        Exactly-once across concurrent callers: each eviction first
        claims the entry with an atomic ``os.rename`` to a caller-unique
        name — of N racing routers exactly one rename succeeds, and only
        the winner counts (and unlinks) the eviction. The losers see
        ``FileNotFoundError`` and report nothing, so an HA router pair
        never double-counts one membership change."""
        now = time.time() if now is None else now
        evicted: list[str] = []
        try:
            names = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return []
        for name in names:
            if not name.endswith(self.SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            stale_unparsable = False
            try:
                with open(path) as f:
                    record = EndpointRecord.from_json(json.load(f))
            except FileNotFoundError:
                continue
            except (json.JSONDecodeError, KeyError, TypeError, ValueError,
                    OSError):
                record = None
                try:
                    stale_unparsable = (
                        now - os.path.getmtime(path) > self.lease_ttl_s
                    )
                except OSError:
                    continue
            if record is not None and record.live(now):
                continue
            if record is None and not stale_unparsable:
                continue  # torn but fresh: give its writer a lease to fix it
            self._claim_seq += 1
            claim = (
                f"{path}{self._EVICT_SUFFIX}.{os.getpid()}.{self._claim_seq}"
            )
            try:
                os.rename(path, claim)  # the atomic exactly-once gate
            except FileNotFoundError:
                continue  # another router (or a heartbeat) won this entry
            except OSError:
                continue
            try:
                os.unlink(claim)
            except OSError:
                pass
            evicted.append(
                record.replica_id
                if record is not None
                else name[: -len(self.SUFFIX)]
            )
        return evicted
