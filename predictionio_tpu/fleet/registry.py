"""Generation-stamped model registry over shared-filesystem storage.

A fleet needs one answer to "which model should every replica be
serving?". Each replica's in-process reload counter says where *that
process* is; the registry says where the *fleet* should converge. It is
a single JSON document on a filesystem every replica host mounts (the
same sharedfs idiom the storage layer's ``TYPE=sharedfs`` driver uses):

* ``publish(instance_id)`` — stamp a new fleet generation pointing at a
  trained engine instance. Atomic (tmp + fsync + rename) so a reader
  never sees a torn record; the generation counter is monotonic even
  across concurrent publishers (last writer wins the pointer, but never
  reuses a generation number).
* ``current()`` — the record replicas/routers/operators gate on.
* ``history()`` — recent generations, newest first (bounded), so a
  rollback target is always one read away.

The router's rolling ``/reload`` stamps the registry before rotating
replicas, then verifies every replica reports the fleet generation on
``/readyz`` — "rollout complete" is a registry⇄fleet convergence check,
not a hope (docs/operations.md, fleet runbook).

Stdlib-only by contract: the registry must be readable from the router,
``pio status``, and CI hosts with nothing installed.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import os
import tempfile
from typing import Any

__all__ = ["ModelRegistry", "RegistryRecord"]

_HISTORY_LIMIT = 50


@dataclasses.dataclass(frozen=True)
class RegistryRecord:
    """One published fleet generation."""

    generation: int
    engine_instance_id: str
    published_at: str  # ISO-8601 UTC
    meta: dict | None = None

    def to_json(self) -> dict:
        out: dict[str, Any] = {
            "generation": self.generation,
            "engineInstanceId": self.engine_instance_id,
            "publishedAt": self.published_at,
        }
        if self.meta:
            out["meta"] = dict(self.meta)
        return out

    @staticmethod
    def from_json(d: dict) -> "RegistryRecord":
        return RegistryRecord(
            generation=int(d["generation"]),
            engine_instance_id=str(d["engineInstanceId"]),
            published_at=str(d.get("publishedAt", "")),
            meta=d.get("meta"),
        )


class ModelRegistry:
    """The fleet's model-generation ledger at ``<dir>/model-registry.json``."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, "model-registry.json")

    # --------------------------------------------------------------- read
    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return {"current": None, "history": []}
        except (json.JSONDecodeError, OSError):
            # a torn read can only happen if rename atomicity was violated
            # (non-POSIX mount): treat as empty rather than wedging the
            # fleet on a parse error; the next publish rewrites it whole
            return {"current": None, "history": []}
        if not isinstance(doc, dict):
            return {"current": None, "history": []}
        return doc

    def current(self) -> RegistryRecord | None:
        cur = self._load().get("current")
        if not isinstance(cur, dict):
            return None
        try:
            return RegistryRecord.from_json(cur)
        except (KeyError, ValueError, TypeError):
            return None

    def history(self) -> list[RegistryRecord]:
        out = []
        for d in self._load().get("history", []):
            try:
                out.append(RegistryRecord.from_json(d))
            except (KeyError, ValueError, TypeError):
                continue
        return out

    # -------------------------------------------------------------- write
    def publish(
        self, engine_instance_id: str, meta: dict | None = None
    ) -> RegistryRecord:
        """Stamp the next fleet generation. Atomic rename; fsync'd so an
        acked publish survives a host crash (same durability contract as
        the model blobs it points at)."""
        doc = self._load()
        prev = doc.get("current") or {}
        generation = int(prev.get("generation", 0)) + 1
        record = RegistryRecord(
            generation=generation,
            engine_instance_id=engine_instance_id,
            published_at=_dt.datetime.now(_dt.timezone.utc).isoformat(),
            meta=meta,
        )
        history = [record.to_json()] + list(doc.get("history", []))
        new_doc = {
            "current": record.to_json(),
            "history": history[:_HISTORY_LIMIT],
        }
        os.makedirs(self.directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            prefix=".model-registry.", suffix=".tmp", dir=self.directory
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(new_doc, f, indent=2)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
        return record
