"""Consistent-hash ring for scope→replica affinity.

Why consistent hashing and not round-robin: the query server's result
cache (PR 4) is per-process. Behind a round-robin balancer every replica
ends up caching the same hot scopes — R copies of one working set, and a
cache hit rate divided by R for the long tail. Hashing the *cache scope*
(the query's ``user`` field; see ``serving.cache.affinity_key``) pins
each scope to one replica, so the fleet's aggregate cache is the UNION
of the replicas' caches, and event-driven invalidations for a scope only
need to reach the replica that owns it (the router still broadcasts —
delivery is cheap and the broadcast is idempotent — but correctness only
depends on the owner).

Why a *ring* and not ``hash(key) % R``: modulo remaps ~every key when R
changes; the ring with virtual nodes remaps only ~1/R of keys when one
replica joins or leaves (asserted in tests/test_fleet_router.py), so a
replica kill or a rolling restart doesn't flush the whole fleet's cache
affinity.

Stdlib-only, deterministic (``blake2b``), no randomness: the same
member set always builds the same ring, so a restarted router routes
identically.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Sequence

__all__ = ["HashRing"]


def _point(data: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(data.encode(), digest_size=8).digest(), "big"
    )


class HashRing:
    """Immutable consistent-hash ring over replica ids.

    ``vnodes`` virtual points per member smooth the load split (64 keeps
    the max/min scope share within ~20% for small fleets). Build cost is
    O(R·vnodes·log); lookups are a binary search.
    """

    def __init__(self, members: Iterable[str], vnodes: int = 64):
        self.members: tuple[str, ...] = tuple(dict.fromkeys(members))
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        points: list[tuple[int, str]] = []
        for member in self.members:
            for v in range(vnodes):
                points.append((_point(f"{member}#{v}"), member))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [m for _, m in points]

    def owner(self, key: str) -> str | None:
        """The member owning ``key``, or None for an empty ring."""
        seq = self.sequence(key, limit=1)
        return seq[0] if seq else None

    def sequence(self, key: str, limit: int | None = None) -> Sequence[str]:
        """Distinct members in ring order starting at ``key``'s point —
        the failover preference order: element 0 is the owner, element 1
        the first fallback, and so on. Every member appears exactly once."""
        if not self.members:
            return []
        limit = len(self.members) if limit is None else min(limit, len(self.members))
        idx = bisect.bisect_left(self._points, _point(key))
        seen: dict[str, None] = {}
        n = len(self._owners)
        for step in range(n):
            m = self._owners[(idx + step) % n]
            if m not in seen:
                seen[m] = None
                if len(seen) >= limit:
                    break
        return list(seen)
