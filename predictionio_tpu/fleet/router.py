"""The fleet router: health-gated load balancing, failover, rolling swap.

The process ``pio deploy --replicas N`` binds to the public port. It
owns no model — it owns the *availability contract*:

* **Routing** — ``POST /queries.json`` routes by consistent hash of the
  query's cache scope (``serving.cache.affinity_key`` →
  :class:`~predictionio_tpu.fleet.ring.HashRing`), so each scope's
  cached results live on exactly one replica; scope-less bodies route
  least-loaded. Unhealthy, draining, rolling, or breaker-open replicas
  are skipped at selection time.
* **Health gating** — a monitor thread probes every replica's
  ``/readyz`` each ``probe_interval_s`` (active), and every forwarded
  request's outcome feeds the same state (passive), with one
  :class:`~predictionio_tpu.resilience.CircuitBreaker` per backend. A
  SIGKILLed replica is routed around within one probe interval — and
  usually sooner, because the first failed forward marks it down.
* **Failover** — a transport failure mid-request re-dispatches the SAME
  query to the next replica in ring order, at most
  ``failover_retries`` times (default 1), and only for idempotent
  requests (GETs and ``/queries.json``; any other proxied POST is
  forwarded exactly once). Caveat under ``--feedback``: a replica that
  died *after* scoring may already have enqueued its prediction event,
  so a failover (or a hedge) can record the same query's prediction
  twice — feedback is best-effort telemetry by contract
  (``FeedbackConfig``), and that contract is what makes queries safe to
  re-dispatch. A ``503`` carrying ``Retry-After`` is a
  *routing signal*, not a client problem: the replica is marked
  draining for that long and the request re-dispatches to a peer
  immediately without consuming the failover budget — behind a router,
  PR 5's drain contract produces zero client-visible 503s.
* **Hedging** (opt-in, ``--hedge-ms``) — when the primary has not
  answered within ``max(hedge_ms, observed p95)``, a hedge goes to the
  next candidate and the first answer wins; bounds the tail a single
  slow replica can impose.
* **Rolling swap** — ``POST /reload`` rotates one replica at a time:
  mark it rolling (drain semantics: new work routes around it, in-flight
  work completes), reload it, wait for ``/readyz`` to report the new
  generation, move on. A bounded key→generation LRU tags every routed
  cache key with the generation that served it, and selection prefers
  replicas at or past that generation — so one cache key is never served
  by two model generations mid-rollout (``generationRegressions`` on
  ``/stats.json`` counts the availability-over-affinity escapes; the
  chaos drill asserts it stays 0).
* **Invalidation fan-out** — ``POST /cache/invalidate.json`` broadcasts
  to every replica (one retry per replica; invalidation is idempotent,
  and event-shaped bodies carry PR 5's deterministic ``eventId`` so any
  upstream redelivery is absorbed too).
* **Fast fleet-down answer** — with every replica down the router
  answers ``503`` immediately with a ``taxonomy`` field
  (``breaker_open`` vs ``no_healthy_replicas``) and a ``Retry-After``
  derived from the breaker reset — no retry storm, no stacked timeouts.
* **Elastic membership** (opt-in, ``endpoint_registry=``) — the monitor
  thread reconciles the ring from the shared
  :class:`~predictionio_tpu.fleet.registry.EndpointRegistry` each probe
  interval: replicas that announced join, replicas whose lease expired
  are evicted (exactly once across an HA router pair — the registry's
  rename-claim guarantees it) and leave the ring. ``GET
  /fleet/endpoints.json`` is the registry's HTTP read API.
* **Stale-while-down cache** (opt-in, ``--stale-cache-ttl-s``) — the
  last good answer per scope is kept for a bounded TTL and served with
  an explicit ``X-PIO-Stale: true`` marker ONLY when no replica can
  serve at all; a scope any live replica could answer is always served
  fresh.

Stdlib-only by contract (piolint manifest): replicas are opaque HTTP
backends; the router must never import jax, storage, or the workflow.
"""

from __future__ import annotations

import collections
import dataclasses
import http.client
import json
import logging
import queue
import threading
import time
import urllib.parse
from typing import Any, Callable, Iterable, Mapping, Sequence

from predictionio_tpu.fleet.registry import EndpointRegistry, ModelRegistry
from predictionio_tpu.fleet.ring import HashRing
from predictionio_tpu.resilience import CircuitBreaker
from predictionio_tpu.serving.cache import affinity_key

__all__ = ["ReplicaState", "RouterConfig", "RouterService", "TransportError"]

logger = logging.getLogger(__name__)


class TransportError(Exception):
    """The replica could not be reached or died mid-request (connection
    refused/reset, timeout, torn response) — distinct from any HTTP
    status it answered."""


def _token_ok(presented: str, expected: str) -> bool:
    import hmac

    return hmac.compare_digest(str(presented), expected)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Knobs of the router (CLI: ``pio deploy --replicas N ...``)."""

    #: seconds between active /readyz probes of each replica
    probe_interval_s: float = 0.25
    #: socket timeout of one probe
    probe_timeout_s: float = 2.0
    #: socket timeout of one forwarded request
    request_timeout_s: float = 30.0
    #: most times one idempotent request is re-dispatched after a
    #: transport failure (draining re-dispatches are not counted here)
    failover_retries: int = 1
    #: >0 enables hedged queries: a hedge fires after
    #: ``max(hedge_ms, observed p95 latency)`` — p95-triggered with a
    #: floor, so a cold histogram cannot hedge every request. 0 = off.
    hedge_ms: float = 0.0
    #: consecutive transport failures that open a replica's breaker
    breaker_threshold: int = 2
    #: seconds an open replica breaker waits before the next probe
    breaker_reset_s: float = 1.0
    #: query field naming the cache scope (must match the replicas'
    #: ``--cache-scope-field``); None hashes whole bodies only
    scope_field: str | None = "user"
    #: bounded key→generation affinity map (the never-two-generations
    #: guard); oldest tags are forgotten first
    key_gen_entries: int = 65536
    #: virtual nodes per replica on the hash ring
    vnodes: int = 64
    #: per-replica budget of one rolling-reload rotation (model load +
    #: jit warm-up)
    reload_timeout_s: float = 300.0
    #: longest the rotation waits for a replica's in-flight requests
    drain_wait_s: float = 10.0
    #: >0 enables the stale-while-down cache: the last good
    #: ``/queries.json`` answer per scope is kept this many seconds and
    #: served (marked ``X-PIO-Stale: true``) ONLY when no replica can
    #: serve — never for a scope a live replica could answer fresh
    stale_cache_ttl_s: float = 0.0
    #: bounded entry count of the stale-while-down cache
    stale_cache_entries: int = 1024

    def __post_init__(self) -> None:
        if self.probe_interval_s <= 0:
            raise ValueError("probe_interval_s must be > 0")
        if self.failover_retries < 0:
            raise ValueError("failover_retries must be >= 0")


class _ConnPool:
    """Tiny keep-alive pool of ``http.client`` connections to one
    replica. Handler threads check out/in; any error discards the
    connection (the next checkout dials fresh)."""

    def __init__(self, host: str, port: int, timeout_s: float):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._idle: list[http.client.HTTPConnection] = []

    def get(self) -> http.client.HTTPConnection:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout_s
        )

    def put(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._idle) < 32:
                self._idle.append(conn)
                return
        conn.close()

    def close_all(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            conn.close()


class ReplicaState:
    """Everything the router knows about one backend replica."""

    def __init__(self, replica_id: str, host: str, port: int, config: RouterConfig):
        self.id = replica_id
        self.host = host
        self.port = port
        self.url = f"http://{host}:{port}"
        self.pool = _ConnPool(host, port, config.request_timeout_s)
        self.breaker = CircuitBreaker(
            failure_threshold=config.breaker_threshold,
            reset_timeout_s=config.breaker_reset_s,
            name=f"replica:{replica_id}",
        )
        self._lock = threading.Lock()
        # health (monitor-written, selection-read)
        self.healthy = False
        self.degraded = False
        self.draining = False
        self.draining_until = 0.0  # monotonic; passive Retry-After signal
        self.rolling = False  # excluded while its rolling-reload rotation runs
        self.generation = 0  # last generation the replica reported
        self.reported_id: str | None = None
        self.last_probe_at = 0.0
        self.last_error: str | None = None
        # load / counters
        self.inflight = 0
        self.forwarded = 0
        self.failures = 0

    # ------------------------------------------------------------- signals
    def note_success(self, generation: int | None = None) -> None:
        self.breaker.record_success()
        with self._lock:
            self.healthy = True
            self.forwarded += 1
            if generation is not None and generation > 0:
                self.generation = generation

    def note_transport_failure(self, error: str) -> None:
        self.breaker.record_failure()
        with self._lock:
            self.failures += 1
            # passive detection: don't wait for the next probe to stop
            # routing at a dead socket
            self.healthy = False
            self.last_error = error[:200]

    def note_draining(self, retry_after_s: float) -> None:
        with self._lock:
            self.draining_until = time.monotonic() + max(0.1, retry_after_s)

    def available(self, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            return (
                self.healthy
                and not self.rolling
                and not self.draining
                and now >= self.draining_until
            )

    def begin(self) -> None:
        with self._lock:
            self.inflight += 1

    def end(self) -> None:
        with self._lock:
            self.inflight -= 1

    def to_json(self) -> dict:
        with self._lock:
            return {
                "id": self.id,
                "url": self.url,
                "healthy": self.healthy,
                "degraded": self.degraded,
                "draining": self.draining
                or time.monotonic() < self.draining_until,
                "rolling": self.rolling,
                "generation": self.generation,
                "reportedId": self.reported_id,
                "inflight": self.inflight,
                "forwarded": self.forwarded,
                "failures": self.failures,
                "lastError": self.last_error,
                "breaker": self.breaker.to_json(),
            }


class _RouterStats:
    """Thread-safe router counters for ``GET /stats.json``."""

    _FIELDS = (
        "routed",
        "failovers",
        "redispatch_draining",
        "hedges",
        "hedge_wins",
        "fast_503s",
        "broadcasts",
        "reloads",
        "generation_regressions",
        "passthrough",
        "membership_changes",
        "lease_evictions",
        "stale_served",
    )

    def __init__(self) -> None:
        self._lock = threading.Lock()
        for f in self._FIELDS:
            setattr(self, f, 0)

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + by)

    def to_json(self) -> dict:
        with self._lock:
            camel = {
                "routed": "routed",
                "failovers": "failovers",
                "redispatch_draining": "redispatchDraining",
                "hedges": "hedges",
                "hedge_wins": "hedgeWins",
                "fast_503s": "fast503s",
                "broadcasts": "broadcasts",
                "reloads": "reloads",
                "generation_regressions": "generationRegressions",
                "passthrough": "passthrough",
                "membership_changes": "membershipChanges",
                "lease_evictions": "leaseEvictions",
                "stale_served": "staleServed",
            }
            return {camel[f]: getattr(self, f) for f in self._FIELDS}


class _Wire:
    """Transport-shape response (duck-typed like ``api.service.Response``
    — the fleet package must not import the storage-coupled api.service
    module). ``raw`` carries an already-encoded replica body through
    unchanged; ``body`` is JSON-encoded at send time."""

    __slots__ = ("status", "body", "raw", "headers", "content_type")

    def __init__(
        self,
        status: int,
        body: Any = None,
        raw: bytes | None = None,
        headers: Mapping[str, str] | None = None,
        content_type: str = "application/json; charset=UTF-8",
    ):
        self.status = status
        self.body = body
        self.raw = raw
        self.headers = dict(headers) if headers else None
        self.content_type = content_type

    def json_bytes(self) -> bytes:
        if self.raw is not None:
            return self.raw
        return json.dumps(self.body, default=str).encode()


#: response headers the router forwards back to the client verbatim
_FORWARDED_HEADERS = ("x-pio-replica", "x-pio-generation", "retry-after")


class RouterService:
    """Transport-agnostic router core; served by ``api.http.serve`` like
    every other framework service (``dispatch`` / ``readiness``)."""

    def __init__(
        self,
        replicas: Sequence[tuple[str, str, int]],  # (id, host, port)
        config: RouterConfig | None = None,
        registry: ModelRegistry | None = None,
        split=None,
        endpoint_registry: EndpointRegistry | None = None,
    ):
        self.config = config or RouterConfig()
        self.registry = registry
        #: optional shared EndpointRegistry — when set, it is the single
        #: source of truth for ring membership (reconciled each probe
        #: interval); the ``replicas`` argument is only the initial view
        self.endpoint_registry = endpoint_registry
        #: optional experiments.split.TrafficSplit — A/B assignment is a
        #: pure function of (salt, weights, affinity key), so stickiness
        #: survives router restarts and replica failover by construction
        self.split = split
        self.replicas: list[ReplicaState] = [
            ReplicaState(rid, host, port, self.config)
            for rid, host, port in replicas
        ]
        self._by_id = {r.id: r for r in self.replicas}
        self._ring = HashRing(
            [r.id for r in self.replicas], vnodes=self.config.vnodes
        )
        self._membership_lock = threading.Lock()
        self.stats = _RouterStats()
        # stale-while-down: gen_key → (expires_monotonic, raw, headers)
        self._stale_cache: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict()
        )
        self._stale_lock = threading.Lock()
        # query arrival timestamps for the autoscaler's q/s window
        self._query_times: "collections.deque[float]" = collections.deque(
            maxlen=4096
        )
        self.start_time = time.time()
        # bounded key→generation tags (the never-two-generations guard)
        self._key_gens: "collections.OrderedDict[str, int]" = (
            collections.OrderedDict()
        )
        self._key_gens_lock = threading.Lock()
        # last 256 successful query latencies, for the p95 hedge trigger
        self._latencies: "collections.deque[float]" = collections.deque(
            maxlen=256
        )
        self._latencies_lock = threading.Lock()
        self._reload_lock = threading.Lock()
        self._stop_event = threading.Event()
        self._monitor_lock = threading.Lock()
        self._monitor: threading.Thread | None = None
        # wired by the console like QueryService's (GET /stop)
        self.stop_server: Callable[[], Any] | None = None
        self.stop_token: str | None = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """Launch the health-monitor thread (idempotent)."""
        with self._monitor_lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            self._stop_event.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="fleet-health", daemon=True
            )
            self._monitor.start()

    def close(self) -> None:
        self._stop_event.set()
        for rep in self.replicas:
            rep.pool.close_all()

    def drain(self) -> None:
        """Drain hook discovered by the HTTP wrapper."""
        self.close()

    # -------------------------------------------------------------- probing
    def probe_replica(self, rep: ReplicaState) -> bool:
        """One active /readyz probe; updates the replica's health, drain,
        degraded, and generation state. Returns readiness."""
        try:
            status, raw, _ = self._forward(
                rep,
                "GET",
                "/readyz",
                None,
                timeout_s=self.config.probe_timeout_s,
                count_load=False,
            )
        except TransportError as e:
            rep.breaker.record_failure()
            with rep._lock:
                rep.healthy = False
                rep.last_probe_at = time.monotonic()
                rep.last_error = str(e)[:200]
            return False
        try:
            report = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            report = {}
        ready = status == 200 and bool(report.get("ready"))
        if ready:
            rep.breaker.record_success()
        with rep._lock:
            rep.healthy = ready
            rep.draining = bool(report.get("draining"))
            rep.degraded = bool(report.get("degraded"))
            gen = report.get("generation")
            if isinstance(gen, int) and gen > 0:
                rep.generation = gen
            rid = report.get("replicaId")
            if isinstance(rid, str):
                rep.reported_id = rid
            rep.last_probe_at = time.monotonic()
            if ready:
                rep.last_error = None
        return ready

    def probe_all(self) -> None:
        for rep in self.replicas:
            self.probe_replica(rep)

    def reconcile_endpoints(self) -> dict:
        """Fold the shared endpoint registry into ring membership:
        announced replicas join, withdrawn/expired ones leave. Expired
        leases are evicted through the registry's rename-claim, so of N
        routers sharing the directory exactly one counts each eviction
        (``leaseEvictions``); every router counts its own local ring
        rebuilds (``membershipChanges``). No-op without a registry."""
        reg = self.endpoint_registry
        if reg is None:
            return {"joined": [], "left": [], "evicted": []}
        evicted = reg.evict_expired()
        if evicted:
            self.stats.incr("lease_evictions", len(evicted))
        live, _expired, problems = reg.snapshot()
        with self._membership_lock:
            current = self._by_id
            live_by_id = {e.replica_id: e for e in live}
            joined = [e for e in live if e.replica_id not in current]
            left = [rid for rid in current if rid not in live_by_id]
            # same id, new address = a respawned replica that re-bound
            # port 0 — must be re-pointed, not just added/removed
            moved = [
                e.replica_id
                for e in live
                if e.replica_id in current
                and (current[e.replica_id].host, current[e.replica_id].port)
                != (e.host, e.port)
            ]
            if not joined and not left and not moved:
                return {"joined": [], "left": [], "evicted": evicted,
                        "problems": problems}
            new_replicas: list[ReplicaState] = []
            for entry in live:
                rep = current.get(entry.replica_id)
                if rep is None or (rep.host, rep.port) != (
                    entry.host, entry.port
                ):
                    rep = ReplicaState(
                        entry.replica_id, entry.host, entry.port, self.config
                    )
                    if entry.generation > 0:
                        rep.generation = entry.generation
                new_replicas.append(rep)
            new_by_id = {r.id: r for r in new_replicas}
            new_ring = HashRing(
                sorted(new_by_id), vnodes=self.config.vnodes
            )
            leavers = [current[rid] for rid in left]
            leavers += [current[rid] for rid in moved]  # stale-address pools
            # readers capture these attributes per access and tolerate
            # by_id/ring skew (missing members are dropped in selection),
            # so plain assignment is the atomic publish
            self._by_id = new_by_id
            self._ring = new_ring
            self.replicas = new_replicas
            self.stats.incr(
                "membership_changes", len(joined) + len(left) + len(moved)
            )
        for rep in leavers:
            rep.pool.close_all()
        if joined or left or moved:
            logger.info(
                "ring membership reconciled: +%s -%s ~%s (evicted %s)",
                [e.replica_id for e in joined], left, moved, evicted,
            )
        return {
            "joined": [e.replica_id for e in joined],
            "left": left,
            "moved": moved,
            "evicted": evicted,
            "problems": problems,
        }

    def _monitor_loop(self) -> None:
        while not self._stop_event.is_set():
            t0 = time.monotonic()
            try:
                self.reconcile_endpoints()
            except OSError as e:  # sharedfs hiccup: keep probing
                logger.warning("endpoint reconcile failed: %s", e)
            self.probe_all()
            elapsed = time.monotonic() - t0
            self._stop_event.wait(
                max(0.01, self.config.probe_interval_s - elapsed)
            )

    # ------------------------------------------------------------ transport
    def _forward(
        self,
        rep: ReplicaState,
        method: str,
        path: str,
        body_bytes: bytes | None,
        timeout_s: float | None = None,
        count_load: bool = True,
        extra_headers: Mapping[str, str] | None = None,
    ) -> tuple[int, bytes, dict]:
        """One HTTP round trip to ``rep``; raises :class:`TransportError`
        on anything below the HTTP layer. Returns
        ``(status, raw body, lowercased headers)``."""
        if timeout_s is not None:
            # custom-deadline calls (probes, reloads) dial fresh: a pooled
            # connection's socket keeps the timeout it connected with, so
            # reusing one here would silently ignore the tighter deadline
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=timeout_s
            )
        else:
            conn = rep.pool.get()
        headers = {"Content-Type": "application/json"}
        if extra_headers:
            headers.update(extra_headers)
        if body_bytes is not None:
            headers["Content-Length"] = str(len(body_bytes))
        if count_load:
            rep.begin()
        try:
            try:
                conn.request(method, path, body=body_bytes, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                status = resp.status
                rhdrs = {k.lower(): v for k, v in resp.getheaders()}
                reuse = not resp.will_close
            except (http.client.HTTPException, OSError, ValueError) as e:
                conn.close()
                raise TransportError(f"{type(e).__name__}: {e}") from e
        finally:
            if count_load:
                rep.end()
        if reuse and timeout_s is None:
            rep.pool.put(conn)
        else:
            conn.close()
        return status, raw, rhdrs

    # ------------------------------------------------------------ selection
    def _key_gen_get(self, key: str | None) -> int:
        if key is None:
            return 0
        with self._key_gens_lock:
            return self._key_gens.get(key, 0)

    def _key_gen_put(self, key: str | None, generation: int) -> None:
        if key is None or generation <= 0:
            return
        with self._key_gens_lock:
            prev = self._key_gens.get(key, 0)
            self._key_gens[key] = max(prev, generation)
            self._key_gens.move_to_end(key)
            while len(self._key_gens) > self.config.key_gen_entries:
                self._key_gens.popitem(last=False)

    def _candidates(self, key: str | None, min_gen: int) -> list[ReplicaState]:
        """Selection order: ring order for keyed queries (owner first),
        least-loaded otherwise; unavailable replicas are dropped, and
        replicas whose known generation is behind the key's recorded
        generation sort last (availability still beats affinity — a
        served-below-tag escape is counted, never a refused query)."""
        now = time.monotonic()
        if key is not None:
            ring, by_id = self._ring, self._by_id
            # a reconcile may land between the two attribute reads: a
            # ring member missing from by_id is simply dropped this pass
            order = [
                r
                for r in (by_id.get(m) for m in ring.sequence(key))
                if r is not None
            ]
        else:
            order = sorted(
                self.replicas, key=lambda r: (r.inflight, r.forwarded)
            )
        avail = [r for r in order if r.available(now)]
        if min_gen > 0:
            preferred = [r for r in avail if r.generation >= min_gen]
            behind = [r for r in avail if r.generation < min_gen]
            return preferred + behind
        return avail

    def _all_down_response(self) -> _Wire:
        """Every replica unavailable: answer fast with the failure
        taxonomy — no forwards, no stacked timeouts."""
        self.stats.incr("fast_503s")
        open_breakers = [
            r for r in self.replicas if r.breaker.state != "closed"
        ]
        taxonomy = (
            "breaker_open"
            if len(open_breakers) == len(self.replicas) and self.replicas
            else "no_healthy_replicas"
        )
        retry_after = max(
            [r.breaker.retry_after_s() for r in self.replicas] or [0.0]
        )
        retry_after = max(1, int(retry_after or self.config.probe_interval_s) + 1)
        return _Wire(
            503,
            {
                "message": "No healthy replica available.",
                "taxonomy": taxonomy,
                "replicas": len(self.replicas),
                "retryAfterSeconds": retry_after,
            },
            headers={"Retry-After": str(retry_after)},
        )

    # ----------------------------------------------------------- query path
    def _record_latency(self, seconds: float) -> None:
        with self._latencies_lock:
            self._latencies.append(seconds)
            self._query_times.append(time.monotonic())

    def _p95_s(self) -> float:
        with self._latencies_lock:
            lat = sorted(self._latencies)
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(0.95 * len(lat)))]

    def _hedge_delay_s(self) -> float:
        # p95-triggered with the configured floor: a cold histogram (or a
        # uniformly fast one) never hedges earlier than hedge_ms
        return max(self.config.hedge_ms / 1000.0, self._p95_s())

    def load_snapshot(self, window_s: float = 5.0) -> dict:
        """Router-side load over the trailing window — the autoscaler's
        watermark inputs: queries/second and p99 latency."""
        now = time.monotonic()
        with self._latencies_lock:
            recent = sum(1 for t in self._query_times if now - t <= window_s)
            lat = sorted(self._latencies)
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))] if lat else 0.0
        return {
            "windowSeconds": window_s,
            "qps": recent / window_s if window_s > 0 else 0.0,
            "p99Seconds": p99,
            "replicas": len(self.replicas),
        }

    # ----------------------------------------------------- stale-while-down
    def _stale_put(self, gen_key: str | None, raw: bytes, headers: dict) -> None:
        if gen_key is None or self.config.stale_cache_ttl_s <= 0:
            return
        expires = time.monotonic() + self.config.stale_cache_ttl_s
        with self._stale_lock:
            self._stale_cache[gen_key] = (expires, raw, dict(headers))
            self._stale_cache.move_to_end(gen_key)
            while len(self._stale_cache) > self.config.stale_cache_entries:
                self._stale_cache.popitem(last=False)

    def _stale_response(self, gen_key: str | None) -> _Wire | None:
        """The bounded-TTL last-good answer for this scope, explicitly
        marked ``X-PIO-Stale`` — called ONLY from the no-candidate-can-
        serve paths, so a fresh-capable scope never sees it."""
        if gen_key is None or self.config.stale_cache_ttl_s <= 0:
            return None
        with self._stale_lock:
            entry = self._stale_cache.get(gen_key)
            if entry is None:
                return None
            expires, raw, headers = entry
            if time.monotonic() >= expires:
                del self._stale_cache[gen_key]
                return None
        self.stats.incr("stale_served")
        out = dict(headers)
        out["X-PIO-Stale"] = "true"
        return _Wire(200, raw=raw, headers=out)

    def _forward_query(
        self,
        rep: ReplicaState,
        body_bytes: bytes,
        extra_headers: Mapping[str, str] | None = None,
    ) -> tuple[int, bytes, dict]:
        t0 = time.monotonic()
        result = self._forward(
            rep, "POST", "/queries.json", body_bytes,
            extra_headers=extra_headers,
        )
        self._record_latency(time.monotonic() - t0)
        return result

    def _forward_hedged(
        self,
        rep: ReplicaState,
        backup: ReplicaState | None,
        body_bytes: bytes,
        extra_headers: Mapping[str, str] | None = None,
    ) -> tuple[ReplicaState, int, bytes, dict]:
        """Primary forward with one optional hedge: first answer wins.
        Raises TransportError only when every launched attempt failed."""
        results: "queue.Queue" = queue.Queue()

        def attempt(r: ReplicaState) -> None:
            try:
                results.put(
                    (r, self._forward_query(r, body_bytes, extra_headers))
                )
            except TransportError as e:
                r.note_transport_failure(str(e))
                results.put((r, e))

        threading.Thread(
            target=attempt, args=(rep,), name="fleet-fwd", daemon=True
        ).start()
        launched = 1
        try:
            winner, outcome = results.get(timeout=self._hedge_delay_s())
        except queue.Empty:
            winner, outcome = None, None
        if winner is None and backup is not None:
            self.stats.incr("hedges")
            threading.Thread(
                target=attempt, args=(backup,), name="fleet-hedge", daemon=True
            ).start()
            launched += 1
        failures: list[TransportError] = []
        while True:
            if winner is None:
                try:
                    winner, outcome = results.get(
                        timeout=self.config.request_timeout_s + 5.0
                    )
                except queue.Empty:
                    # every launched attempt outlived the total budget
                    # (per-read socket timeouts never fired on a
                    # slow-drip response): surface a routed transport
                    # failure, not a naked exception — the abandoned
                    # threads' eventual results are discarded
                    raise TransportError(
                        "hedged request exceeded the request deadline "
                        "on every attempt"
                    ) from None
            if isinstance(outcome, TransportError):
                failures.append(outcome)
                if len(failures) >= launched:
                    raise failures[0]
                winner, outcome = None, None
                continue
            if launched > 1 and winner is backup:
                self.stats.incr("hedge_wins")
            return winner, outcome[0], outcome[1], outcome[2]

    def route_query(self, body: Any, params: Mapping[str, str]) -> _Wire:
        """The /queries.json path: hash-affine selection, breaker gating,
        draining re-dispatch, bounded failover, optional hedging."""
        try:
            body_bytes = json.dumps(body, default=str).encode()
        except (TypeError, ValueError):
            return _Wire(400, {"message": "Query body is required (JSON)."})
        key = affinity_key(body, self.config.scope_field)
        variant = self.split.assign(key) if self.split is not None else None
        # per-variant generation streams: during a promotion rollout two
        # variants may legitimately serve the same scope from different
        # generations, so the never-two-generations guard tracks
        # (variant, key) — variant names cannot contain "|" (validated in
        # experiments.split), so the tag cannot collide with a raw key
        gen_key = (
            f"{variant}|{key}"
            if variant is not None and key is not None
            else key
        )
        variant_headers = (
            {"X-PIO-Variant": variant} if variant is not None else None
        )
        min_gen = self._key_gen_get(gen_key)
        candidates = self._candidates(key, min_gen)
        if not candidates:
            stale = self._stale_response(gen_key)
            if stale is not None:
                return stale
            return self._all_down_response()
        failovers = 0
        last_503: _Wire | None = None
        tried: set[str] = set()
        while True:
            rep = next(
                (
                    r
                    for r in candidates
                    if r.id not in tried and r.available()
                ),
                None,
            )
            if rep is None:
                break
            tried.add(rep.id)
            if not rep.breaker.acquire():
                continue  # open circuit: skip without touching the socket
            hedge_backup = None
            if self.config.hedge_ms > 0:
                hedge_backup = next(
                    (
                        r
                        for r in candidates
                        if r.id not in tried
                        and r.id != rep.id
                        and r.available()
                    ),
                    None,
                )
            t_fwd = time.monotonic()
            try:
                if hedge_backup is not None:
                    rep, status, raw, rhdrs = self._forward_hedged(
                        rep, hedge_backup, body_bytes, variant_headers
                    )
                    tried.add(rep.id)
                else:
                    status, raw, rhdrs = self._forward_query(
                        rep, body_bytes, variant_headers
                    )
            except TransportError as e:
                if hedge_backup is None:
                    # the hedged path already recorded each failed
                    # attempt inside _forward_hedged — recording again
                    # here would open the primary's breaker at half the
                    # configured threshold
                    rep.note_transport_failure(str(e))
                if failovers < self.config.failover_retries:
                    failovers += 1
                    self.stats.incr("failovers")
                    continue
                if variant is not None:
                    self.split.note_routed(
                        variant, time.monotonic() - t_fwd, ok=False
                    )
                return _Wire(
                    502,
                    {
                        "message": "Replica failed mid-request and the "
                        "failover budget is exhausted.",
                        "replica": rep.id,
                        "failovers": failovers,
                        "error": str(e)[:200],
                    },
                )
            if status == 503 and "retry-after" in rhdrs:
                # draining replica (PR 5's drain contract): routing
                # signal, not a client answer — mark and re-dispatch,
                # without consuming the failover budget
                try:
                    retry_after = float(rhdrs["retry-after"])
                except ValueError:
                    retry_after = 1.0
                rep.note_draining(retry_after)
                self.stats.incr("redispatch_draining")
                last_503 = _Wire(
                    status, raw=raw,
                    headers={"Retry-After": rhdrs["retry-after"]},
                )
                continue
            gen = 0
            try:
                gen = int(rhdrs.get("x-pio-generation", "0"))
            except ValueError:
                pass
            rep.note_success(gen or None)
            served_gen = gen or rep.generation
            if min_gen > 0 and 0 < served_gen < min_gen:
                # availability beat affinity: an older generation served a
                # key the newer one already answered — surfaced, counted,
                # and asserted zero during orderly rollouts
                self.stats.incr("generation_regressions")
            self._key_gen_put(gen_key, served_gen)
            self.stats.incr("routed")
            if variant is not None:
                self.split.note_routed(
                    variant, time.monotonic() - t_fwd, ok=status == 200
                )
            out_headers = {
                k.title(): v
                for k, v in rhdrs.items()
                if k in _FORWARDED_HEADERS
            }
            out_headers["X-PIO-Routed-Replica"] = rep.id
            if variant is not None:
                out_headers["X-PIO-Variant"] = variant
            if status == 200:
                self._stale_put(gen_key, raw, out_headers)
            return _Wire(status, raw=raw, headers=out_headers)
        if last_503 is not None:
            # every peer was also draining/down: the drain 503 (with its
            # Retry-After) is the truthful answer
            return last_503
        # every candidate was tried and is down: the last good answer
        # (explicitly marked stale) beats a 503 for a read-shaped query
        stale = self._stale_response(gen_key)
        if stale is not None:
            return stale
        return self._all_down_response()

    # ------------------------------------------------------------ broadcast
    def broadcast(
        self, method: str, path: str, body: Any, retries: int = 1
    ) -> dict:
        """Deliver one request to EVERY replica (invalidations must reach
        all R caches). Per-replica transport failures retry ``retries``
        times; results are reported per replica. Safe to retry because
        the broadcast routes are idempotent (cache invalidation; event-
        shaped bodies additionally carry deterministic eventIds)."""
        try:
            body_bytes = (
                json.dumps(body, default=str).encode()
                if body is not None
                else None
            )
        except (TypeError, ValueError):
            return {"ok": False, "error": "unserializable body"}
        results: dict[str, dict] = {}
        lock = threading.Lock()

        def deliver(rep: ReplicaState) -> None:
            # a replica that is DOWN before we even try cannot be holding
            # cache entries the invalidation needs to kill: whenever it
            # comes back (respawn, reload) its result cache starts cold,
            # so failed delivery to it is a safe skip, not a lost
            # invalidation. Delivery failure to a replica that WAS
            # serving stays loudly partial (502).
            was_available = rep.available()
            outcome: dict = {}
            for _ in range(retries + 1):
                try:
                    status, raw, _h = self._forward(rep, method, path, body_bytes)
                except TransportError as e:
                    rep.note_transport_failure(str(e))
                    outcome = {"ok": False, "error": str(e)[:200]}
                    continue
                try:
                    payload = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    payload = None
                outcome = {"ok": 200 <= status < 300, "status": status,
                           "body": payload}
                break
            if not outcome.get("ok") and not was_available:
                outcome = dict(
                    outcome,
                    ok=True,
                    skipped="replica down before delivery — its cache "
                    "is cold when it returns",
                )
            with lock:
                results[rep.id] = outcome

        threads = [
            threading.Thread(
                target=deliver, args=(rep,), name=f"fleet-bcast-{rep.id}",
                daemon=True,
            )
            for rep in self.replicas
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.config.request_timeout_s + 5.0)
        self.stats.incr("broadcasts")
        return {
            "ok": all(r.get("ok") for r in results.values()) and bool(results),
            "replicas": results,
        }

    # --------------------------------------------------------- rolling swap
    def rolling_reload(self) -> tuple[int, dict]:
        """Rotate ``/reload`` through the fleet one replica at a time,
        reusing the drain semantics: the rotating replica stops receiving
        new work, finishes what it has, reloads, and must come back ready
        at a NEWER generation before the next rotation starts. Returns
        ``(http status, report)``."""
        if not self._reload_lock.acquire(blocking=False):
            return 409, {"message": "A rolling reload is already running."}
        try:
            self.stats.incr("reloads")
            target = self.registry.current() if self.registry else None
            report: dict[str, Any] = {
                "replicas": {},
                "registryGeneration": target.generation if target else None,
                "registryInstanceId": (
                    target.engine_instance_id if target else None
                ),
            }
            if target is not None and target.artifacts:
                # artifact-readiness gate (pio train --aot): the target
                # generation declares an AOT artifact set, so verify it —
                # stdlib manifest parse + blob size/sha256 (registry.py)
                # — BEFORE rotating a single replica. Rotating onto a
                # missing/torn artifact dir would demote the whole fleet
                # to JIT fallback at once, the exact cold-start spike AOT
                # exists to remove; failing the rotation here keeps every
                # replica serving warm while the operator re-exports.
                # Fingerprint matching stays the replicas' job — they
                # have jax, this router does not.
                from predictionio_tpu.fleet.registry import (
                    verify_aot_artifacts,
                )

                adir = target.artifacts.get("dir", "")
                check = verify_aot_artifacts(adir) if adir else {
                    "ok": False,
                    "problems": ["artifact stamp carries no dir"],
                }
                report["artifactCheck"] = {
                    "dir": adir,
                    "ok": check["ok"],
                    "problems": check.get("problems", []),
                }
                if not check["ok"]:
                    report["ok"] = False
                    report["error"] = (
                        "registry generation declares AOT artifacts but "
                        "the artifact set failed verification; rotation "
                        "aborted before touching any replica"
                    )
                    return 500, report
            ok = True
            for rep in self.replicas:
                entry: dict[str, Any] = {"generationBefore": rep.generation}
                old_gen = rep.generation
                with rep._lock:
                    rep.rolling = True
                try:
                    # drain semantics: new work already routes around the
                    # rolling replica; wait (bounded) for in-flight work
                    deadline = time.monotonic() + self.config.drain_wait_s
                    while rep.inflight > 0 and time.monotonic() < deadline:
                        time.sleep(0.02)
                    try:
                        status, raw, _h = self._forward(
                            rep, "POST", "/reload", b"{}",
                            timeout_s=self.config.reload_timeout_s,
                        )
                    except TransportError as e:
                        rep.note_transport_failure(str(e))
                        entry["error"] = str(e)[:200]
                        ok = False
                        break
                    if status != 200:
                        entry["error"] = f"/reload answered {status}"
                        entry["body"] = raw[:300].decode("utf-8", "replace")
                        ok = False
                        break
                    # gate the rotation on the replica converging: ready
                    # AND generation advanced past the pre-reload one
                    deadline = time.monotonic() + self.config.reload_timeout_s
                    converged = False
                    while time.monotonic() < deadline:
                        if (
                            self.probe_replica(rep)
                            and rep.generation > old_gen
                        ):
                            converged = True
                            break
                        time.sleep(
                            min(0.05, self.config.probe_interval_s)
                        )
                    if not converged:
                        entry["error"] = (
                            "replica did not report a newer generation "
                            "after /reload"
                        )
                        ok = False
                        break
                finally:
                    with rep._lock:
                        rep.rolling = False
                    entry["generationAfter"] = rep.generation
                    report["replicas"][rep.id] = entry
            generations = {r.generation for r in self.replicas}
            report["converged"] = len(generations) == 1
            report["generations"] = sorted(generations)
            report["ok"] = ok and report["converged"]
            if report["ok"] and self.registry is not None and self.replicas:
                # stamp what the fleet actually converged to: the served
                # instance id comes from a replica's own status, so the
                # registry records rollout truth, not intent
                try:
                    _s, raw, _h = self._forward(
                        self.replicas[0], "GET", "/", None
                    )
                    inst = (json.loads(raw) or {}).get("engineInstanceId")
                except (TransportError, json.JSONDecodeError):
                    inst = None
                if inst and (
                    target is None or target.engine_instance_id != inst
                ):
                    record = self.registry.publish(  # piolint: waive=PIO211 -- reload lock is try-acquire: contenders bail with 409 instead of convoying, and publishing the new generation durably is part of the rotation by design
                        inst, meta={"source": "rolling_reload"}
                    )
                    report["registryGeneration"] = record.generation
                    report["registryInstanceId"] = inst
            return (200 if report["ok"] else 500), report
        finally:
            self._reload_lock.release()

    # ---------------------------------------------------------- passthrough
    def _passthrough(
        self, method: str, path: str, params: Mapping[str, str], body: Any
    ) -> _Wire:
        """Any other route: forward to one healthy replica. Only
        idempotent requests (GETs) may fail over after a transport error;
        a non-idempotent POST body is never re-sent — the client gets the
        502 and decides."""
        try:
            body_bytes = (
                json.dumps(body, default=str).encode()
                if body is not None
                else None
            )
        except (TypeError, ValueError):
            return _Wire(400, {"message": "Malformed body."})
        qs = urllib.parse.urlencode(dict(params))
        target = path + (f"?{qs}" if qs else "")
        idempotent = method == "GET"
        attempts = (self.config.failover_retries + 1) if idempotent else 1
        candidates = self._candidates(None, 0)
        if not candidates:
            return self._all_down_response()
        last_error = "no candidate attempted"
        for rep in candidates[:attempts]:
            if not rep.breaker.acquire():
                continue
            try:
                status, raw, rhdrs = self._forward(
                    rep, method, target, body_bytes
                )
            except TransportError as e:
                rep.note_transport_failure(str(e))
                last_error = str(e)[:200]
                if not idempotent:
                    return _Wire(
                        502,
                        {
                            "message": "Replica failed mid-request; this "
                            "route is not idempotent, so the request was "
                            "not retried.",
                            "replica": rep.id,
                            "error": last_error,
                        },
                    )
                continue
            rep.note_success()
            self.stats.incr("passthrough")
            out_headers = {
                k.title(): v
                for k, v in rhdrs.items()
                if k in _FORWARDED_HEADERS
            }
            out_headers["X-PIO-Routed-Replica"] = rep.id
            return _Wire(status, raw=raw, headers=out_headers)
        return _Wire(
            502,
            {"message": "Every candidate replica failed.", "error": last_error},
        )

    # -------------------------------------------------------------- status
    def generation_converged(self) -> int | None:
        gens = {r.generation for r in self.replicas}
        if len(gens) == 1:
            return next(iter(gens))
        return None

    def status_json(self) -> dict:
        return {
            "status": "alive",
            "role": "router",
            "replicas": [r.to_json() for r in self.replicas],
            "generation": self.generation_converged(),
            "generationConverged": self.generation_converged() is not None,
            "registry": (
                self.registry.current().to_json()
                if self.registry and self.registry.current()
                else None
            ),
            "stats": self.stats.to_json(),
        }

    def stats_json(self, fanout: bool = False) -> dict:
        out: dict[str, Any] = {
            "role": "router",
            "router": self.stats.to_json(),
            "replicas": [r.to_json() for r in self.replicas],
            "generation": self.generation_converged(),
            "p95Seconds": round(self._p95_s(), 6),
        }
        if self.split is not None:
            out["experiments"] = self.split.stats_json()
        if fanout:
            details: dict[str, Any] = {}
            for rep in self.replicas:
                try:
                    _s, raw, _h = self._forward(rep, "GET", "/stats.json", None)
                    details[rep.id] = json.loads(raw)
                except (TransportError, json.JSONDecodeError) as e:
                    details[rep.id] = {"error": str(e)[:200]}
            out["replicaStats"] = details
        return out

    def endpoints_json(self) -> dict:
        """``GET /fleet/endpoints.json``: the registry's HTTP read API —
        live entries (with lease ages), expired-but-unevicted entries,
        torn-file problems, and this router's current ring view."""
        now = time.time()
        reg = self.endpoint_registry
        doc: dict[str, Any] = {
            "registry": None,
            "ring": sorted(self._by_id),
            "replicas": [r.to_json() for r in self.replicas],
            "membershipChanges": self.stats.membership_changes,
            "leaseEvictions": self.stats.lease_evictions,
        }
        if reg is None:
            return doc
        live, expired, problems = reg.snapshot(now)
        doc["registry"] = {
            "directory": reg.directory,
            "leaseTtlSeconds": reg.lease_ttl_s,
            "live": [
                dict(e.to_json(), leaseAgeSeconds=round(e.lease_age_s(now), 3))
                for e in live
            ],
            "expired": [e.to_json() for e in expired],
            "problems": problems,
        }
        return doc

    def readiness(self) -> dict:
        """Router /readyz: ready while at least one replica can serve."""
        now = time.monotonic()
        healthy = sum(1 for r in self.replicas if r.available(now))
        return {
            "ready": healthy > 0,
            "checks": {
                "replicas": {
                    "ok": healthy > 0,
                    "healthy": healthy,
                    "total": len(self.replicas),
                }
            },
            "role": "router",
            "generation": self.generation_converged(),
        }

    # ---------------------------------------------------------- experiments
    def experiments_json(self) -> dict:
        """``GET /experiments.json``: the live experiment — config,
        per-variant counters, and the promotion stamp (plus the registry
        record a promotion published, when one exists)."""
        out: dict[str, Any] = self.split.stats_json()
        out["scopeField"] = self.config.scope_field
        if self.registry is not None:
            current = self.registry.current()
            meta = getattr(current, "meta", None) if current else None
            if isinstance(meta, dict) and meta.get("source") == (
                "experiment_promotion"
            ):
                out["registryPromotion"] = {
                    "generation": current.generation,
                    "engineInstanceId": current.engine_instance_id,
                    "variant": meta.get("variant"),
                }
        return out

    def promote_experiment(self, body: Any) -> tuple[int, dict]:
        """``POST /experiments/promote.json`` ``{"variant": name}``:
        collapse traffic onto the winner, stamp the outcome into the
        model registry, and rotate the fleet through a rolling reload so
        every replica converges on one generation with zero failed
        queries (PR 15's drain semantics)."""
        name = (body or {}).get("variant") if isinstance(body, dict) else None
        if not isinstance(name, str) or not name:
            return 400, {
                "message": 'Promotion body must be {"variant": "<name>"}.'
            }
        try:
            promotion = self.split.promote(name)
        except ValueError as e:
            return 404, {"message": str(e)}
        report: dict[str, Any] = {"promotion": promotion}
        if self.registry is not None and self.replicas:
            # stamp rollout truth: the instance id the fleet is actually
            # serving, read from a replica, not deployment intent
            inst = None
            for rep in self.replicas:
                try:
                    _s, raw, _h = self._forward(rep, "GET", "/", None)
                    inst = (json.loads(raw) or {}).get("engineInstanceId")
                except (TransportError, json.JSONDecodeError):
                    continue
                if inst:
                    break
            if inst:
                record = self.registry.publish(
                    inst,
                    meta={
                        "source": "experiment_promotion",
                        "variant": name,
                        "weightsBefore": promotion.get("weightsBefore"),
                    },
                )
                report["registry"] = {
                    "generation": record.generation,
                    "engineInstanceId": inst,
                }
        status, reload_report = self.rolling_reload()
        report["reload"] = reload_report
        report["ok"] = status == 200
        return (200 if status == 200 else 500), report

    def reward_experiment(self, body: Any) -> tuple[int, dict]:
        """``POST /experiments/reward.json``: fold reward observations
        into the per-variant counters. Each item names its variant
        explicitly, or carries the original query body's scope fields so
        the router re-derives the assignment (same pure function that
        routed it)."""
        items = body if isinstance(body, list) else [body]
        matched = 0
        for item in items:
            if not isinstance(item, dict):
                continue
            variant = item.get("variant")
            if not isinstance(variant, str) or not variant:
                key = affinity_key(item, self.config.scope_field)
                if key is None:
                    continue
                variant = self.split.assign(key)
            value = item.get("value", 1.0)
            if variant in self.split.variant_names():
                self.split.note_reward(variant, value)
                matched += 1
        return 200, {
            "matched": matched,
            "experiments": self.split.stats_json(),
        }

    # ------------------------------------------------------------- dispatch
    def dispatch(
        self,
        method: str,
        path: str,
        params: Mapping[str, str],
        body: Any = None,
        headers: Mapping[str, str] | None = None,
        form: Mapping[str, str] | None = None,
    ) -> _Wire:
        method = method.upper()
        if path == "/" and method == "GET":
            return _Wire(200, self.status_json())
        if path == "/queries.json" and method == "POST":
            return self.route_query(body, params)
        if path == "/cache/invalidate.json" and method == "POST":
            result = self.broadcast(method, path, body)
            return _Wire(200 if result.get("ok") else 502, result)
        if path == "/stats.json" and method == "GET":
            return _Wire(
                200, self.stats_json(fanout=params.get("fanout") == "1")
            )
        if path == "/fleet/endpoints.json" and method == "GET":
            return _Wire(200, self.endpoints_json())
        if path == "/reload" and method == "POST":
            status, report = self.rolling_reload()
            return _Wire(status, report)
        if path.startswith("/experiments") and self.split is None:
            return _Wire(
                404,
                {
                    "message": "No experiment is configured on this fleet "
                    "(deploy with --variants name:weight,...)."
                },
            )
        if path == "/experiments.json" and method == "GET":
            return _Wire(200, self.experiments_json())
        if path == "/experiments/promote.json" and method == "POST":
            status, report = self.promote_experiment(body)
            return _Wire(status, report)
        if path == "/experiments/reward.json" and method == "POST":
            status, report = self.reward_experiment(body)
            return _Wire(status, report)
        if path == "/stop" and method == "GET":
            presented = ""
            if headers:
                presented = next(
                    (
                        v
                        for k, v in headers.items()
                        if k.lower() == "x-pio-stop-token"
                    ),
                    "",
                )
            presented = presented or params.get("token", "")
            if self.stop_token and not _token_ok(presented, self.stop_token):
                return _Wire(403, {"message": "Missing or invalid stop token."})
            if self.stop_server is None:
                return _Wire(501, {"message": "This router has no stop hook."})
            self.stop_server()
            return _Wire(200, {"message": "Shutting down fleet."})
        return self._passthrough(method, path, params, body)
