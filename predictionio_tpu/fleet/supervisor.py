"""Replica process supervision for ``pio deploy --replicas N``.

The supervisor owns the replica *processes* the way the router owns the
replica *traffic*: it spawns N query-server subprocesses (each a full
``pio deploy`` with the operator's flags, so ``--shard-factors`` /
``--quantize`` / ``--ann`` compose per replica), respawns any replica
that dies (rate-limited, so a crash-looping model cannot fork-bomb the
host), and records the live topology in a **fleet state file** under the
deployments directory — the single source of truth ``pio status``, the
chaos drill, and operators use to find replica ports and PIDs.

Self-healing is what turns the router's route-around into recovery: the
router hides a SIGKILLed replica within one probe interval, and the
supervisor brings a replacement up on the same port so capacity (and the
hash ring's affinity — the ring is keyed by replica id, which the
replacement inherits) returns without operator action. Under k8s the
Deployment controller plays this role instead (docs/operations.md maps
the pieces); this supervisor is the single-host story.

Stdlib-only by contract: process control and JSON state only.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Sequence

__all__ = [
    "FleetSupervisor",
    "ReplicaSpec",
    "fleet_state_path",
    "read_fleet_state",
]

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ReplicaSpec:
    """One replica's identity and launch recipe."""

    replica_id: str
    port: int
    #: full argv AFTER the interpreter (e.g. ``["-m",
    #: "predictionio_tpu.tools.console", "deploy", ...]``)
    argv: tuple[str, ...]


def fleet_state_path(base_dir: str, router_port: int) -> str:
    return os.path.join(
        base_dir, "deployments", f"fleet-{router_port}.json"
    )


def read_fleet_state(path: str) -> dict | None:
    """The fleet topology document, or None when absent/torn."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None
    return doc if isinstance(doc, dict) else None


class FleetSupervisor:
    """Spawns, watches, respawns, and stops the replica subprocesses."""

    #: respawn rate limit per replica: more than this many deaths inside
    #: the window means the replica is crash-looping (bad model, bad
    #: flags) — stop respawning it and mark it failed in the state file
    MAX_RESPAWNS = 5
    RESPAWN_WINDOW_S = 60.0

    def __init__(
        self,
        specs: Sequence[ReplicaSpec],
        state_path: str,
        router_port: int,
        env: dict | None = None,
        poll_interval_s: float = 0.5,
    ):
        self.specs = list(specs)
        self.state_path = state_path
        self.router_port = router_port
        self.env = dict(env) if env is not None else None
        self.poll_interval_s = poll_interval_s
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}
        #: retired replicas still draining — stop() escalates on them too
        self._retiring: list[subprocess.Popen] = []
        self._respawn_times: dict[str, list[float]] = {}
        self._failed: set[str] = set()
        self._stopping = threading.Event()
        self._monitor: threading.Thread | None = None

    # -------------------------------------------------------------- spawn
    def _spawn(self, spec: ReplicaSpec) -> subprocess.Popen:
        proc = subprocess.Popen(
            [sys.executable, *spec.argv],
            env=self.env,
            stdin=subprocess.DEVNULL,
        )
        logger.info(
            "spawned replica %s (port %d, pid %d)",
            spec.replica_id, spec.port, proc.pid,
        )
        return proc

    def start(self) -> None:
        # spawn OUTSIDE the lock (Popen blocks); publish under it
        spawned = {spec.replica_id: self._spawn(spec) for spec in self.specs}
        monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-supervisor", daemon=True
        )
        with self._lock:
            self._procs.update(spawned)
            self._monitor = monitor
        self.write_state()
        monitor.start()

    # ------------------------------------------------------------- monitor
    def _monitor_loop(self) -> None:
        while not self._stopping.wait(self.poll_interval_s):
            changed = False
            with self._lock:
                specs = list(self.specs)  # autoscaler mutates the fleet
            for spec in specs:
                with self._lock:
                    proc = self._procs.get(spec.replica_id)
                    failed = spec.replica_id in self._failed
                if failed or proc is None or proc.poll() is None:
                    continue
                rc = proc.returncode
                now = time.monotonic()
                times = self._respawn_times.setdefault(spec.replica_id, [])
                times[:] = [
                    t for t in times if now - t < self.RESPAWN_WINDOW_S
                ]
                if len(times) >= self.MAX_RESPAWNS:
                    logger.error(
                        "replica %s crash-looping (rc=%s, %d respawns in "
                        "%.0fs) — giving up on it",
                        spec.replica_id, rc, len(times), self.RESPAWN_WINDOW_S,
                    )
                    with self._lock:
                        self._failed.add(spec.replica_id)
                    changed = True
                    continue
                times.append(now)
                if self._stopping.is_set():
                    # stop() raced this iteration: it has already
                    # snapshotted the process list, so a respawn here
                    # would orphan the replacement past the shutdown
                    return
                logger.warning(
                    "replica %s (port %d) exited rc=%s — respawning",
                    spec.replica_id, spec.port, rc,
                )
                try:
                    replacement = self._spawn(spec)  # outside the lock
                except OSError as e:
                    # transient fork/exec failure (EAGAIN, ENOMEM): the
                    # monitor thread must survive it — this attempt
                    # counted toward the rate limit above, and the next
                    # poll retries. An unhandled raise here would kill
                    # the supervisor thread and silently disable
                    # self-healing for the whole fleet.
                    logger.error(
                        "respawn of replica %s failed: %s", spec.replica_id, e
                    )
                    continue
                with self._lock:
                    if self._stopping.is_set():
                        # stop() won the race mid-spawn: the snapshot
                        # missed the replacement, so terminate it here
                        replacement.terminate()
                        return
                    self._procs[spec.replica_id] = replacement
                changed = True
            if changed and not self._stopping.is_set():
                self.write_state()

    # ------------------------------------------------------------- elastic
    def add_replica(self, spec: ReplicaSpec) -> None:
        """Scale-up: spawn one more replica and start watching it."""
        if self._stopping.is_set():
            return
        proc = self._spawn(spec)  # outside the lock (Popen blocks)
        with self._lock:
            if self._stopping.is_set():
                proc.terminate()
                return
            self.specs.append(spec)
            self._procs[spec.replica_id] = proc
        self.write_state()

    def retire_replica(self, replica_id: str) -> bool:
        """Scale-down, drain-aware: remove the spec FIRST (so the monitor
        never respawns it), then SIGTERM — the replica drains in-flight
        queries per its ``--drain-deadline-s`` and withdraws its own
        registry entry on clean exit. Returns whether a replica was
        actually retired."""
        with self._lock:
            spec = next(
                (s for s in self.specs if s.replica_id == replica_id), None
            )
            if spec is None:
                return False
            self.specs.remove(spec)
            proc = self._procs.pop(replica_id, None)
            if proc is not None:
                self._retiring.append(proc)
            self._failed.discard(replica_id)
            self._respawn_times.pop(replica_id, None)
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        logger.info("retiring replica %s (drain via SIGTERM)", replica_id)
        self.write_state()
        return True

    def retiring_count(self) -> int:
        """Retired replicas still draining (their process has not exited
        yet) — the autoscaler holds further scale-downs while > 0."""
        with self._lock:
            self._retiring = [p for p in self._retiring if p.poll() is None]
            return len(self._retiring)

    # --------------------------------------------------------------- state
    def state(self) -> dict:
        with self._lock:
            replicas = []
            for spec in self.specs:
                proc = self._procs.get(spec.replica_id)
                replicas.append(
                    {
                        "id": spec.replica_id,
                        "port": spec.port,
                        "pid": proc.pid if proc is not None else None,
                        "alive": proc is not None and proc.poll() is None,
                        "failed": spec.replica_id in self._failed,
                    }
                )
        return {
            "routerPort": self.router_port,
            "supervisorPid": os.getpid(),
            "replicas": replicas,
        }

    def write_state(self) -> None:
        doc = self.state()
        directory = os.path.dirname(self.state_path)
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            prefix=".fleet.", suffix=".tmp", dir=directory
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=2)
                # full durability protocol (PIO501/PIO502): the state
                # file is how a post-crash `pio status` finds orphaned
                # replica PIDs to clean up — a torn or forgotten file
                # after a host reset would leak the whole fleet
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.state_path)
            dfd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass

    # ---------------------------------------------------------------- stop
    def stop(self, grace_s: float = 10.0) -> None:
        """SIGTERM every replica, escalate to SIGKILL after ``grace_s``,
        and remove the state file. Idempotent."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        with self._lock:
            procs = list(self._procs.values()) + list(self._retiring)
        for proc in procs:
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + grace_s
        for proc in procs:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        try:
            os.unlink(self.state_path)
        except FileNotFoundError:
            pass
