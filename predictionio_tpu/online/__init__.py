"""Online learning — incremental fold-in from the event tail to the
live serving model, in seconds instead of retrains (ROADMAP item 3).

The closed loop the PredictionIO blueprint promises — events in, fresh
predictions out — used to close only through a full ``pio train``. This
package closes it online:

* :mod:`~predictionio_tpu.online.follower` — a durable tail follower
  with a persisted watermark cursor over the columnar event store
  (survives segment roll, compaction, and process restart exactly-once);
* :mod:`~predictionio_tpu.online.foldin` — a jitted batched ALS re-solve
  of ONLY the touched user/item rows against fixed opposite-side factors
  (the classic MLlib-era fold-in), plus cold-start row injection for
  never-seen entities;
* :mod:`~predictionio_tpu.online.trainer` — a streaming mini-batch
  trainer for two-tower embeddings consuming the same delta stream in a
  background daemon thread;
* :mod:`~predictionio_tpu.online.runner` — the orchestration daemon:
  poll the follower, group deltas, dispatch to each deployed algorithm's
  online hooks, hot-swap the touched rows through
  ``QueryService.apply_online_update`` (per-scope cache invalidation,
  device re-pin of delta rows, incremental IVF index update), commit the
  watermark.

Layering (piolint manifest): this package may import ``ops``, ``data``,
``workflow`` and ``serving`` — never templates or tools; algorithms
participate through duck-typed hooks (see
:mod:`~predictionio_tpu.online.types`). Strictly opt-in behind ``pio
deploy --online``: with the flag off nothing here is imported at all
(CI-guarded), and this ``__init__`` plus ``types`` stay jax-free so
merely constructing an :class:`OnlineConfig` costs nothing.
"""

from predictionio_tpu.online.types import EventDelta, OnlineConfig, OnlineUpdate

__all__ = ["EventDelta", "OnlineConfig", "OnlineUpdate"]
