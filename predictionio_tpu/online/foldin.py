"""Batched ALS fold-in — re-solve only the touched rows.

The classic MLlib-era incremental update (PAPERS.md, "MLlib: Machine
Learning in Apache Spark"): with the opposite-side factors ``Y`` held
FIXED, the least-squares optimum for one row is independent of every
other row, so fresh events only require re-solving the rows they
touched::

    x_u = argmin_x  ||r_u - Y_u x||^2  +  reg * n_u * ||x||^2
                    +  prior_weight * ||x - x_old||^2

The ``prior_weight`` anchor keeps a row near its trained optimum while
its *online-observed* history is still thin (the follower only sees
events since deploy, not the training set); as online ratings
accumulate the data term dominates and the solve converges to the pure
fold-in. Cold-start rows (entities the model has never seen) use
``x_old = 0`` with no anchor — exactly the textbook fold-in of a new
user/item from its first events.

The kernel is one jitted program per (batch, width) bucket: gather the
rated opposite rows, form the normal equations with masked einsums, add
the ALS-WR ridge (``reg * max(n,1)`` — the same scaling ``ops.als``
trains with, so fold-in and retrain agree on the objective), and solve
with the shared SPD solver. Batch and width pad to powers of two so
live traffic compiles a handful of programs, then re-traces nothing —
the same bucketing discipline as the serving top-K. Implicit-feedback
models add the ``YtY`` Gramian and confidence weights (MLlib
``implicitPrefs`` fold-in); the caller supplies ``yty`` once per model
generation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["foldin_rows", "gram_yty"]

#: floor for the padded per-row rating width buckets
_MIN_BUCKET = 8
#: widest per-row rating window the kernel solves; heavier histories
#: keep their most recent entries (a bounded window is also what keeps
#: one fold's latency flat as an entity's online history grows)
MAX_WIDTH = 512
#: FIXED solve-batch shape: every call chunks its rows into batches of
#: exactly this many (padded), so the kernel compiles ONCE per width
#: bucket instead of once per distinct touched-row count — per-fold
#: retraces were measured to dominate fold latency (and bleed into
#: serving p99 through CPU contention) when the batch dimension floated
B_CHUNK = 128


def _bucket(n: int, floor: int = _MIN_BUCKET) -> int:
    return max(floor, 1 << (max(1, n) - 1).bit_length())


@functools.partial(jax.jit, static_argnames=("implicit",))
def _foldin_kernel(
    Yg: jax.Array,  # [B, L, K] PRE-GATHERED opposite rows (see below)
    val: jax.Array,  # [B, L] f32 ratings
    mask: jax.Array,  # [B, L] f32 1=real
    prior: jax.Array,  # [B, K] f32 anchor rows (0 for cold starts)
    prior_w: jax.Array,  # [B] f32 per-row anchor strength
    reg: jax.Array,  # scalar f32
    alpha: jax.Array,  # scalar f32 (implicit confidence slope)
    yty: jax.Array,  # [K, K] (zeros when explicit)
    implicit: bool,
) -> jax.Array:
    """Solve the anchored normal equations for ``B`` rows at once.

    The gather happens OUTSIDE this jit on purpose: cold-start
    injections grow the factor tables every few folds, and a kernel
    traced against the table would re-compile on every growth — the
    pre-gathered ``[B, L, K]`` operand keeps the trace shape-stable
    regardless of catalog size."""
    Yg = Yg * mask[..., None]  # masked rows zero out
    n = mask.sum(axis=-1)  # [B]
    K = Yg.shape[-1]
    eye = jnp.eye(K, dtype=Yg.dtype)
    if implicit:
        # MLlib implicit fold-in: A = YtY + alpha * sum r y y^T,
        # b = sum (1 + alpha r) y  (preference 1 for every observed pair)
        A = jnp.einsum("blk,blj,bl->bkj", Yg, Yg, alpha * val)
        A = A + yty[None]
        b = jnp.einsum("blk,bl->bk", Yg, (1.0 + alpha * val) * mask)
    else:
        A = jnp.einsum("blk,blj->bkj", Yg, Yg)
        b = jnp.einsum("blk,bl->bk", Yg, val * mask)
    ridge = reg * jnp.maximum(n, 1.0) + prior_w  # ALS-WR + anchor
    A = A + ridge[:, None, None] * eye
    b = b + prior_w[:, None] * prior
    from predictionio_tpu.ops.solve import cholesky_solve

    return cholesky_solve(A, b)


def gram_yty(opposite) -> np.ndarray:
    """``Y^T Y`` of the opposite factors — computed once per model
    generation by implicit-model callers."""
    Y = np.asarray(opposite, dtype=np.float32)
    return Y.T @ Y


def foldin_rows(
    opposite,
    entries: list[tuple[list[int], list[float]]],
    reg: float,
    priors: np.ndarray | None = None,
    prior_weights: np.ndarray | None = None,
    implicit: bool = False,
    alpha: float = 1.0,
    yty: np.ndarray | None = None,
) -> np.ndarray:
    """Re-solve a batch of rows against fixed ``opposite`` factors.

    ``entries[i] = (opposite row indices, ratings)`` is row ``i``'s full
    online-observed history (rows beyond :data:`MAX_WIDTH` keep their
    most recent entries — callers append chronologically). ``priors``
    [B, K] / ``prior_weights`` [B] anchor each solve to its previous row
    (omit or pass weight 0 for pure fold-in / cold starts). Returns the
    solved rows ``[B, K]`` float32.

    The batch dimension is FIXED at :data:`B_CHUNK` (larger batches run
    several chunks) and the width pads to a power-of-two bucket, so the
    jitted kernel compiles once per width bucket and steady-state folds
    re-trace nothing; padding rows solve a trivial identity system and
    are dropped before returning."""
    Y = opposite
    on_host = isinstance(Y, np.ndarray)
    B = len(entries)
    K = int(Y.shape[1])
    if B == 0:
        return np.zeros((0, K), np.float32)
    width = min(MAX_WIDTH, max(len(ix) for ix, _ in entries))
    L = _bucket(width)
    yty_arr = jnp.asarray(
        np.zeros((K, K), np.float32)
        if yty is None
        else np.asarray(yty, np.float32)
    )
    out_parts = []
    for lo in range(0, B, B_CHUNK):
        part = entries[lo : lo + B_CHUNK]
        n = len(part)
        idx = np.zeros((B_CHUNK, L), np.int32)
        val = np.zeros((B_CHUNK, L), np.float32)
        mask = np.zeros((B_CHUNK, L), np.float32)
        for i, (ix, vs) in enumerate(part):
            if len(ix) > L:  # keep the most recent window
                ix, vs = ix[-L:], vs[-L:]
            m = len(ix)
            if m == 0:
                continue
            idx[i, :m] = ix
            val[i, :m] = vs
            mask[i, :m] = 1.0
        pr = np.zeros((B_CHUNK, K), np.float32)
        pw = np.zeros(B_CHUNK, np.float32)
        if priors is not None:
            pr[:n] = np.asarray(priors, np.float32)[lo : lo + B_CHUNK]
        if prior_weights is not None:
            pw[:n] = np.asarray(prior_weights, np.float32)[lo : lo + B_CHUNK]
        # gather OUTSIDE the jit (host fancy-index, or an eager device
        # gather for pinned tables): the kernel's trace must not depend
        # on the catalog size, which cold-start injections keep growing
        if on_host:
            Yg = jnp.asarray(
                np.asarray(Y, np.float32)[idx.reshape(-1)].reshape(
                    B_CHUNK, L, K
                )
            )
        else:
            Yg = Y[jnp.asarray(idx.reshape(-1))].reshape(B_CHUNK, L, K)
            Yg = Yg.astype(jnp.float32)
        out = _foldin_kernel(
            Yg,
            jnp.asarray(val),
            jnp.asarray(mask),
            jnp.asarray(pr),
            jnp.asarray(pw),
            jnp.float32(reg),
            jnp.float32(alpha),
            yty_arr,
            implicit,
        )
        out_parts.append(np.asarray(out)[:n])
    return np.concatenate(out_parts, axis=0)
