"""Durable tail follower — the watermark cursor over the event store.

Wraps the columnar driver's ``tail_follow`` delta-read API
(:meth:`predictionio_tpu.data.storage.columnar._ColumnarEvents
.tail_follow`) with crash-safe cursor persistence:

* :meth:`TailFollower.poll` reads everything appended since the cursor
  (decoded events) and advances the cursor **in memory only**;
* :meth:`TailFollower.commit` persists the advanced cursor atomically
  (tmp + rename) — callers commit AFTER the batch is applied to the
  model, so a crash between poll and commit re-delivers the batch
  (at-least-once) instead of skipping it; the fold-in consumers are
  re-solve-idempotent, so re-delivery converges to the same factors.

Across a clean stop/start the persisted cursor resumes exactly once: no
event delivered twice, none skipped — including across segment roll and
compaction (the storage layer re-anchors the consumed prefix inside
compacted segments via the cursor's recent-id chain). A dropped and
recreated stream resets the cursor via the ``stream_id`` marker.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import zlib

from predictionio_tpu.online.types import EventDelta

__all__ = ["TailFollower", "FollowerUnsupportedError"]

logger = logging.getLogger(__name__)


class FollowerUnsupportedError(RuntimeError):
    """The configured event store has no tail-follow API (only the
    columnar driver streams deltas; see docs/operations.md)."""


def _state_path(
    state_dir: str, app_name: str, channel: str | None,
    partition: int | None = None,
) -> str:
    if not state_dir:
        from predictionio_tpu.data.storage import Storage

        state_dir = os.path.join(Storage.base_dir(), "online")
    # readable prefix + crc so distinct app names never share a cursor;
    # partitioned stores get one cursor file PER partition follower
    # (partition=None keeps the pre-partitioning name, so existing
    # single-stream cursors survive an upgrade)
    name = f"{app_name}\x00{channel or ''}"
    safe = re.sub(r"[^A-Za-z0-9_-]", "_", app_name)
    if partition is not None:
        name += f"\x00p{partition}"
        safe += f"-p{partition}"
    return os.path.join(
        state_dir, f"{safe}-{zlib.crc32(name.encode()):08x}.cursor.json"
    )


class TailFollower:
    """Follow one app's event stream from a persisted watermark."""

    def __init__(
        self,
        app_name: str,
        channel: str | None = None,
        state_dir: str = "",
        from_start: bool = False,
        partition: int | None = None,
    ):
        from predictionio_tpu.data.store import resolve_app
        from predictionio_tpu.data.storage import Storage

        self.app_name = app_name
        self.partition = partition
        self._app_id, self._channel_id = resolve_app(app_name, channel)
        self._pe = Storage.get_p_events()
        if not hasattr(self._pe, "tail_follow"):
            raise FollowerUnsupportedError(
                "the configured EVENTDATA store does not support tail "
                "following (pio deploy --online needs the columnar "
                "driver; docs/operations.md)"
            )
        self._from_start = from_start
        self._path = _state_path(state_dir, app_name, channel, partition)
        self._lock = threading.Lock()
        self._cursor: dict | None = self._load()
        self._pending: dict | None = None  # advanced but uncommitted
        if self._cursor is None and not from_start:
            # anchor the watermark NOW, not at the first poll: anything
            # ingested between deploy and the daemon's first cycle is
            # new data and must fold — a first-poll anchor would swallow
            # it into the "history" the watermark skips
            _, self._cursor = self._follow(None)
            self._pending = self._cursor
            self.commit()

    def _follow(self, cursor: dict | None):
        """tail_follow with the partition routed only when set — plain
        (non-partitioned) stores never see the kwarg."""
        kw = {} if self.partition is None else {"partition": self.partition}
        return self._pe.tail_follow(
            self._app_id, self._channel_id, cursor=cursor,
            from_start=self._from_start, **kw,
        )

    # ------------------------------------------------------------ persistence
    def _load(self) -> dict | None:
        try:
            with open(self._path) as f:
                cursor = json.load(f)
        except (FileNotFoundError, ValueError):
            return None
        return cursor if isinstance(cursor, dict) else None

    def commit(self) -> None:
        """Persist the last poll's cursor atomically. Called by the
        runner AFTER the batch was folded into the serving model — the
        watermark never runs ahead of what serving reflects."""
        with self._lock:
            pending = self._pending
            if pending is None:
                return
            self._pending = None
            self._cursor = pending
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(pending, f)
            # durability protocol (PIO501/PIO502): the watermark IS the
            # exactly-once contract — a torn cursor file after a crash
            # would re-deliver (or worse, skip) the whole tail
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)
        dfd = os.open(os.path.dirname(self._path), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def rollback(self) -> None:
        """Drop the un-committed poll advance: the next :meth:`poll`
        re-delivers everything since the last committed watermark. The
        runner calls this when a batch could NOT be fully applied (fold
        deadline hit, or a concurrent ``/reload`` superseded the model
        generation) — advancing the watermark past unapplied events
        would lose them until the next retrain."""
        with self._lock:
            self._pending = None

    # ------------------------------------------------------------------ poll
    def poll(self, limit: int | None = None) -> list:
        """Events appended since the watermark, oldest first (decoded
        :class:`~predictionio_tpu.data.event.Event` objects). Advances
        the in-memory cursor; call :meth:`commit` once the batch is
        applied. ``limit`` is advisory only — a poll always consumes
        whole storage deltas; the runner slices oversized batches into
        consecutive folds itself."""
        with self._lock:
            cursor = self._pending if self._pending is not None else self._cursor
            # piolint: waive=PIO211 -- tail_follow can reach os.replace only on first-touch stream creation; every later poll is a pure delta read, and poll/commit must stay serialized under this lock regardless
            events, new_cursor = self._follow(cursor)
            # only the PENDING cursor advances; the committed cursor
            # moves in commit() so rollback() can re-deliver in-process
            self._pending = new_cursor
        return events

    def lag(self) -> dict:
        """Watermark position for /stats.json: consumed segments/lines
        vs the store's current state."""
        with self._lock:
            cursor = dict(self._cursor or {})
        kw = {} if self.partition is None else {"partition": self.partition}
        state = (
            self._pe.scan_state(self._app_id, self._channel_id, **kw)
            if hasattr(self._pe, "scan_state")
            else {}
        )
        out = {
            "tailLinesConsumed": int(cursor.get("tail_lines", 0)),
            "tailLinesStore": int(state.get("tail_lines", 0)),
            "segmentsConsumed": len(cursor.get("segments", ())),
            "segmentsStore": len(state.get("segments", ())),
            "compactions": int(cursor.get("compactions", 0)),
            # byte offset of the cleanly-consumed tail prefix: polls are
            # O(delta) while present; absent means the next poll takes
            # the (line-count) fallback scan (docs/operations.md)
            "tailBytesConsumed": cursor.get("tail_bytes"),
        }
        if self.partition is not None:
            out["partition"] = self.partition
        return out


def to_deltas(events, rating_prop: str = "rating") -> list[EventDelta]:
    """Decoded events -> the reduced per-event view fold-in consumes.
    Property extraction mirrors the training read: a numeric
    ``rating_prop`` lands as the rating, everything else is NaN."""
    out: list[EventDelta] = []
    for e in events:
        v = e.properties.opt(rating_prop) if e.properties is not None else None
        rating = (
            float(v)
            if isinstance(v, (int, float)) and not isinstance(v, bool)
            else float("nan")
        )
        t = e.event_time
        if t.tzinfo is None:
            import datetime as _dt

            t = t.replace(tzinfo=_dt.timezone.utc)
        out.append(
            EventDelta(
                event=e.event,
                user=e.entity_id,
                item=e.target_entity_id,
                t_us=int(t.timestamp() * 1e6),
                rating=rating,
            )
        )
    return out
