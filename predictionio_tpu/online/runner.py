"""Online-learning orchestration — the ``pio deploy --online`` daemon.

One background thread closes the loop the paper's blueprint promises:

    event store tail --> per-entity deltas --> fold-in / streaming SGD
        --> QueryService.apply_online_update (touched rows only)
        --> watermark commit

Per poll: the durable :class:`~predictionio_tpu.online.follower
.TailFollower` returns everything appended since the watermark; deltas
are dispatched to each deployed algorithm's online hooks (fold-in for
matrix-factorization models, the :class:`~predictionio_tpu.online
.trainer.StreamingTrainer` for towers); the computed rows hot-swap into
serving under the generation lock with per-scope cache invalidation and
incremental IVF index maintenance; only then does the watermark commit
— a crash re-delivers (and re-solves, idempotently) rather than skips.

A full ``/reload`` supersedes everything here: the runner detects the
generation bump, rebinds to the fresh pairs, and drops in-flight
updates computed against the old generation (``apply_online_update``
validates the generation token under the lock).
"""

from __future__ import annotations

import json
import logging
import threading
import time

from predictionio_tpu.online.follower import TailFollower, to_deltas
from predictionio_tpu.online.types import OnlineConfig

__all__ = ["OnlineRunner"]

logger = logging.getLogger(__name__)

#: fold-latency / freshness sample ring size for /stats.json percentiles
_SAMPLES = 256


def _percentile(samples: list[float], q: float) -> float | None:
    if not samples:
        return None
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * len(s)))]


class OnlineRunner:
    """Owns the follower thread and the per-pair online adapters for one
    deployed :class:`~predictionio_tpu.workflow.serving.QueryService`."""

    def __init__(self, service, config: OnlineConfig):
        self.service = service
        self.config = config
        ds_params: dict = {}
        inst = service.instance
        if inst is not None and getattr(inst, "datasource_params", None):
            try:
                ds_params = json.loads(inst.datasource_params) or {}
            except ValueError:
                ds_params = {}
        if not ds_params:
            # fall back to the variant's raw engine.json
            ds_params = (
                (service.variant.raw.get("datasource") or {}).get("params")
                or {}
            )
        self.ds_params = ds_params
        app_name = ds_params.get("appName") or ds_params.get("app_name") or ""
        if not app_name:
            raise ValueError(
                "--online requires the engine's datasource params to name "
                "an appName (the stream to follow)"
            )
        # one follower per event-store partition: each keeps its own
        # durable byte-offset cursor and folds concurrently (owner-shard
        # scatters keep concurrent fold-ins shard-local); a plain store
        # gets the single partition=None follower with the legacy cursor
        # filename
        from predictionio_tpu.data.storage import Storage

        pe = Storage.get_p_events()
        part_count = int(
            getattr(getattr(pe, "_e", None), "partition_count", 0)
            or getattr(pe, "partition_count", 0)
            or 1
        )
        self.followers: list[TailFollower] = [
            TailFollower(
                app_name,
                channel=ds_params.get("channelName"),
                state_dir=config.state_dir,
                from_start=config.from_start,
                partition=p,
            )
            for p in ([None] if part_count <= 1 else range(part_count))
        ]
        self.follower: TailFollower = self.followers[0]
        self._lock = threading.Lock()
        #: serializes whole fold cycles: the daemon cadence and a manual
        #: POST /online/fold.json must not interleave poll/apply/commit
        self._cycle_lock = threading.Lock()
        self.folds = 0
        self.events_seen = 0
        self.events_folded = 0
        self.last_error: str | None = None
        self._fold_ms: list[float] = []
        self._visible_s: list[float] = []
        self._bound_generation = -1
        self._trainers: dict[int, object] = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="pio-online-follower"
        )
        self._thread.start()

    # -------------------------------------------------------------- control
    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5.0)
        with self._lock:
            trainers = list(self._trainers.values())
            self._trainers = {}
        for t in trainers:
            t.stop()

    def fold_now(self, timeout_s: float = 30.0) -> dict:
        """Synchronous poll+fold — the ``POST /online/fold.json`` manual
        trigger (and the test hook). Runs one cycle on the caller's
        thread; the daemon keeps its own cadence. A deadline abort
        rolls the watermark back (``requeued: true`` in the response) —
        nothing is lost, the daemon (which runs without a deadline)
        drains the backlog on its next cycle."""
        return self._cycle(deadline=time.monotonic() + timeout_s)

    # ----------------------------------------------------------------- loop
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._cycle()
            except Exception as e:  # keep following; surface on /stats.json
                with self._lock:
                    self.last_error = str(e)[:300]
                logger.exception("online fold cycle failed; continuing")
            self._wake.wait(self.config.interval_s)
            self._wake.clear()

    def _algo_enabled(self, algo) -> bool:
        allow = self.config.algorithms
        if not allow:
            return True
        name = type(algo).__name__.lower()
        return any(tok and tok.lower() in name for tok in allow)

    def _rebind(self, pairs, generation: int) -> None:
        """(Re)build per-pair streaming trainers when the model
        generation moved (a /reload swapped the models out from under
        the previous binding)."""
        with self._lock:
            if generation == self._bound_generation:
                return
            stale = list(self._trainers.values())
            self._trainers = {}
            self._bound_generation = generation
        for t in stale:
            t.stop()
        from predictionio_tpu.online.trainer import StreamingTrainer

        for pi, (algo, model) in enumerate(pairs):
            spec_fn = getattr(algo, "online_trainer_spec", None)
            if spec_fn is None or not self._algo_enabled(algo):
                continue
            spec = spec_fn(model)
            if not spec:
                continue
            cfg = self.config

            def apply(update, _pi=pi, _gen=generation):
                res = self.service.apply_online_update(
                    [(_pi, update)], generation=_gen
                )
                if res.get("applied"):
                    # the trainer applies asynchronously, outside the
                    # fold cycle — freshness for streamed updates is
                    # recorded here, stamped with the batch's newest
                    # event time the trainer threads through
                    self._record_visible(
                        int(update.info.get("newestUs") or 0)
                    )
                return res

            trainer = StreamingTrainer(
                model,
                apply,
                batch_size=cfg.trainer_batch,
                lr=spec.get("learning_rate", cfg.trainer_lr),
                temperature=spec.get("temperature", 0.1),
                seed=int(spec.get("seed", 0)),
            )
            with self._lock:
                # stop() may have drained _trainers while this cycle was
                # mid-rebind; registering now would leak a live daemon
                # past close() — stop it instead (outside the lock: stop
                # joins the trainer thread)
                doomed = trainer if self._stop.is_set() else None
                if doomed is None:
                    self._trainers[pi] = trainer
            if doomed is not None:
                doomed.stop()

    def _record_visible(self, newest_us: int) -> None:
        """One event->serving-visible latency sample: the wall-clock gap
        between a batch's newest event and its hot-swap completing."""
        if not newest_us:
            return
        with self._lock:
            self._visible_s.append(max(0.0, time.time() - newest_us / 1e6))
            del self._visible_s[:-_SAMPLES]

    def _cycle(self, deadline: float | None = None) -> dict:
        with self._cycle_lock:
            try:
                # piolint: waive=PIO211 -- the cycle lock exists to serialize fold cycles end to end; the watermark fsync MUST land before the next poll, and no request path ever contends on this lock
                return self._cycle_locked(deadline)
            except Exception:
                # the watermark must never advance past a batch that
                # failed mid-fold (a transient hook/apply error would
                # otherwise silently skip those events until the next
                # retrain): drop the pending cursors so the next cycle
                # re-delivers the whole batch
                for f in self.followers:
                    f.rollback()
                raise

    def _fold_batches(
        self, pairs, generation: int, deltas, deadline: float | None
    ) -> tuple[bool, int, str | None]:
        """Fold one follower's polled deltas in config-sized batches.
        Returns ``(applied_any, folded, aborted_reason)``. Safe to run
        concurrently for different partitions: ``apply_online_update``
        validates the generation under the service lock and the fold-in
        scatters are shard-local (owner-shard layout, PR 9)."""
        svc = self.service
        applied_any = False
        folded = 0
        aborted: str | None = None
        batch = self.config.batch_size
        for lo in range(0, len(deltas), batch):
            if deadline is not None and time.monotonic() > deadline:
                aborted = "deadline"
                break
            chunk = deltas[lo : lo + batch]
            t0 = time.perf_counter()
            updates = []
            for pi, (algo, model) in enumerate(pairs):
                if not self._algo_enabled(algo):
                    continue
                with self._lock:
                    trainer = self._trainers.get(pi)
                if trainer is not None:
                    names = self.ds_params.get("eventNames") or (
                        "view", "rate", "buy", "like",
                    )
                    trainer.submit(
                        [
                            (d.user, d.item)
                            for d in chunk
                            if d.item is not None and d.event in names
                        ],
                        newest_us=max((d.t_us for d in chunk), default=0),
                    )
                    continue
                hook = getattr(algo, "online_foldin", None)
                if hook is None:
                    continue
                upd = hook(model, chunk, self.ds_params, self.config)
                if upd is not None and not upd.empty:
                    updates.append((pi, upd))
            if updates:
                res = svc.apply_online_update(updates, generation=generation)
                if not res.get("applied") and res.get("reason"):
                    # a concurrent /reload superseded the generation the
                    # rows were solved against
                    aborted = str(res["reason"])
                    break
                applied_any = applied_any or res.get("applied", False)
            folded += len(chunk)
            with self._lock:
                self._fold_ms.append((time.perf_counter() - t0) * 1e3)
                del self._fold_ms[:-_SAMPLES]
        return applied_any, folded, aborted

    def _cycle_locked(self, deadline: float | None = None) -> dict:
        svc = self.service
        pairs, generation = svc.snapshot_pairs()
        self._rebind(pairs, generation)
        followers = self.followers
        if len(followers) == 1:
            polled = [followers[0].poll()]
        else:
            # concurrent polls: each partition's delta read is
            # independent I/O; a slow partition doesn't delay the rest
            polled = [None] * len(followers)

            def _poll(i: int) -> None:
                polled[i] = followers[i].poll()

            threads = [
                threading.Thread(
                    target=_poll, args=(i,), name=f"pio-online-poll-p{i}",
                    daemon=True,
                )
                for i in range(len(followers))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            polled = [p if p is not None else [] for p in polled]
        total = sum(len(ev) for ev in polled)
        if not total:
            return {"events": 0, "applied": False}
        # exploration reward fold-back (ISSUE 16): the same polled batch
        # feeds the explorer's posterior — reward events are telemetry
        # for the bandit, not ratings, so they ride beside the fold
        # pipeline (which ignores non-rating events) rather than in it
        explorer = getattr(svc, "explorer", None)
        if explorer is not None:
            try:
                for ev in polled:
                    if ev:
                        explorer.note_reward_events(ev)
            except Exception:
                logger.exception("explorer reward fold-back failed")
        all_deltas = [to_deltas(ev) for ev in polled]
        newest_us = max(
            (d.t_us for ds in all_deltas for d in ds), default=0
        )
        outcomes: list[tuple[bool, int, str | None]] = [None] * len(followers)
        failures: list[BaseException] = []

        def _fold(i: int) -> None:
            if not all_deltas[i]:
                outcomes[i] = (False, 0, None)
                return
            try:
                outcomes[i] = self._fold_batches(
                    pairs, generation, all_deltas[i], deadline
                )
            except BaseException as e:
                # a partition whose fold died rolls back below and the
                # exception re-raises after the healthy partitions have
                # committed — partition isolation without weakening the
                # "a failed fold never advances the watermark" contract
                logger.exception("fold failed; requeueing partition batch")
                outcomes[i] = (False, 0, f"error: {str(e)[:200]}")
                failures.append(e)

        if len(followers) == 1:
            _fold(0)
        else:
            # one fold worker per partition follower — the concurrency
            # the owner-shard scatter layout exists to make safe
            threads = [
                threading.Thread(
                    target=_fold, args=(i,), name=f"pio-online-fold-p{i}",
                    daemon=True,
                )
                for i in range(len(followers))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        applied_any = False
        folded = 0
        committed_events = 0
        requeued = 0
        reason: str | None = None
        for f, ev, (applied, n, aborted) in zip(followers, polled, outcomes):
            applied_any = applied_any or applied
            folded += n
            if aborted is not None:
                # the watermark must never advance past events that were
                # not applied: drop THIS partition's poll advance so its
                # next cycle re-delivers the batch. Fold-in re-solves
                # idempotently; the streaming trainer may re-see an
                # already-trained chunk — its drop-oldest sampling queue
                # absorbs the repeat. Other partitions commit normally.
                f.rollback()
                requeued += 1
                reason = reason or aborted
            else:
                f.commit()
                committed_events += len(ev)
        if committed_events or not requeued:
            with self._lock:
                self.folds += 1
                self.events_seen += committed_events
                self.events_folded += folded
                self.last_error = None
        if applied_any and not requeued:
            # wall-clock event->serving-visible latency: the batch's
            # newest event was just swapped into the live model
            self._record_visible(newest_us)
        if failures:
            # propagate the fold failure to the caller (fold_now() raises;
            # the daemon cycle records it in lastError) — the failed
            # partitions were rolled back above, the healthy ones already
            # committed, so re-delivery is scoped to what actually failed
            raise failures[0]
        out = {"events": total, "applied": applied_any}
        if requeued:
            out["requeued"] = True
            out["reason"] = reason
        return out

    # ---------------------------------------------------------------- stats
    def stats_json(self) -> dict:
        with self._lock:
            fold_ms = list(self._fold_ms)
            visible = list(self._visible_s)
            trainers = {
                str(pi): t.stats_json() for pi, t in self._trainers.items()
            }
            out = {
                "folds": self.folds,
                "eventsSeen": self.events_seen,
                "eventsFolded": self.events_folded,
                "intervalSeconds": self.config.interval_s,
                "lastError": self.last_error,
            }
        out["foldMs"] = {
            "p50": _percentile(fold_ms, 0.50),
            "p95": _percentile(fold_ms, 0.95),
            "last": fold_ms[-1] if fold_ms else None,
        }
        # measured event->reflected-in-recs latency (newest event of each
        # applied batch to its hot-swap completing)
        out["eventToVisibleSeconds"] = {
            "p50": _percentile(visible, 0.50),
            "p95": _percentile(visible, 0.95),
            "last": visible[-1] if visible else None,
        }
        out["watermark"] = self.follower.lag()
        if len(self.followers) > 1:
            out["watermarks"] = [f.lag() for f in self.followers]
        if trainers:
            out["trainers"] = trainers
        return out
