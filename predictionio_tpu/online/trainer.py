"""Streaming mini-batch trainer — the two-tower half of online learning.

Matrix-factorization rows admit a closed-form fold-in
(:mod:`~predictionio_tpu.online.foldin`); embedding towers do not, so
their online path is the streaming analog of training: small SGD steps
on the fresh (user, item) pairs with in-batch sampled-softmax — the
same objective ``ops.twotower`` trains with — touching ONLY the rows
the batch names. A :class:`StreamingTrainer` runs in its own background
daemon thread consuming the runner's delta stream from a bounded queue
(a burst drops oldest batches rather than stalling the follower), and
pushes each step's updated rows through the same
``apply_online_update`` hot-swap path the fold-in side uses.

The jitted step computes gradients w.r.t. the GATHERED rows only (the
rest of the tables are fixed for the step), so its cost scales with the
mini-batch, not the catalog; per-id gradient accumulation and the SGD
update run host-side on the handful of touched rows. Rows re-normalize
after each step — the serving contract is L2-normalized towers.
"""

from __future__ import annotations

import functools
import logging
import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.online.types import OnlineUpdate

__all__ = ["StreamingTrainer", "sgd_step"]

logger = logging.getLogger(__name__)

#: padded mini-batch bucket floor (one compiled program per bucket)
_MIN_BUCKET = 8


def _bucket(n: int) -> int:
    return max(_MIN_BUCKET, 1 << (max(1, n) - 1).bit_length())


@jax.jit
def _grad_kernel(ue, ie, mask, inv_temp):
    """Masked symmetric in-batch softmax-CE over gathered rows
    ``[B, D]``; returns (loss, grad_ue, grad_ie). Padding rows (mask 0)
    contribute no loss and are excluded from every negative set."""

    def loss_fn(u_raw, i_raw):
        un = u_raw / (jnp.linalg.norm(u_raw, axis=-1, keepdims=True) + 1e-8)
        inn = i_raw / (jnp.linalg.norm(i_raw, axis=-1, keepdims=True) + 1e-8)
        real = mask > 0
        n_real = jnp.maximum(mask.sum(), 1.0)
        B = u_raw.shape[0]
        labels = jnp.arange(B)
        # padding columns leave every negative set; the diagonal stays
        # unmasked so a padding ROW's own label is finite (its loss is
        # then select-dropped — a -inf diagonal would make it +inf and
        # poison the mean with inf*0)
        allow = real[None, :] | jnp.eye(B, dtype=bool)

        def ce(a, b):
            logits = (a @ b.T) * inv_temp
            logits = jnp.where(allow, logits, -jnp.inf)
            logp = jax.nn.log_softmax(logits, axis=1)
            return -logp[labels, labels]

        per = jnp.where(real, 0.5 * (ce(un, inn) + ce(inn, un)), 0.0)
        return per.sum() / n_real

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(ue, ie)
    return loss, grads[0], grads[1]


def sgd_step(
    user_vecs,
    item_vecs,
    u_idx: np.ndarray,
    i_idx: np.ndarray,
    lr: float,
    temperature: float,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, float]:
    """One streaming step over pairs ``(u_idx[j], i_idx[j])``.

    Gathers the touched rows (device gather when the tables are pinned),
    runs the jitted masked-CE gradient kernel, accumulates per-id
    gradients host-side (one id may appear in several pairs), applies
    SGD, and re-normalizes. Returns ``(unique user rows idx, updated
    rows, unique item rows idx, updated rows, loss)``."""
    B = len(u_idx)
    Bp = _bucket(B)
    up = np.zeros(Bp, np.int64)
    ip = np.zeros(Bp, np.int64)
    up[:B] = u_idx
    ip[:B] = i_idx
    mask = np.zeros(Bp, np.float32)
    mask[:B] = 1.0
    ue = np.asarray(user_vecs[up], np.float32)
    ie = np.asarray(item_vecs[ip], np.float32)
    loss, gu, gi = _grad_kernel(
        jnp.asarray(ue), jnp.asarray(ie), jnp.asarray(mask),
        jnp.float32(1.0 / max(temperature, 1e-6)),
    )
    gu = np.asarray(gu)[:B]
    gi = np.asarray(gi)[:B]

    def fold(idx: np.ndarray, rows: np.ndarray, grad: np.ndarray):
        uniq, inv = np.unique(idx, return_inverse=True)
        acc = np.zeros((uniq.size, rows.shape[1]), np.float32)
        np.add.at(acc, inv, grad)
        first = np.zeros(uniq.size, np.int64)
        first[inv[::-1]] = np.arange(idx.size - 1, -1, -1)
        new = rows[first] - lr * acc
        new /= np.linalg.norm(new, axis=1, keepdims=True) + 1e-8
        return uniq, new

    uu, new_u = fold(up[:B], ue[:B], gu)
    ui, new_i = fold(ip[:B], ie[:B], gi)
    return uu, new_u, ui, new_i, float(loss)


class StreamingTrainer:
    """Background daemon consuming delta pair batches for ONE deployed
    two-tower pair. The runner enqueues ``(pairs, new_users, new_items)``
    work items; the thread turns each into one or more SGD steps and
    hands the updated rows to ``apply`` (the runner's hot-swap bridge
    into ``QueryService.apply_online_update``)."""

    def __init__(
        self,
        model,
        apply,
        batch_size: int = 256,
        lr: float = 0.05,
        temperature: float = 0.1,
        seed: int = 0,
        queue_size: int = 64,
    ):
        self._model = model
        self._apply = apply
        self._batch = max(1, int(batch_size))
        self._lr = float(lr)
        self._temp = float(temperature)
        self._rng = np.random.default_rng(seed)
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._lock = threading.Lock()
        self.steps = 0
        self.pairs_trained = 0
        self.dropped_batches = 0
        self.last_loss: float | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="pio-online-trainer"
        )
        self._thread.start()

    # --------------------------------------------------------------- intake
    def submit(
        self, pairs: list[tuple[str, str]], newest_us: int = 0
    ) -> None:
        """Enqueue fresh (user id, item id) pairs; drops the OLDEST
        queued batch on overflow so a burst degrades to sampling recent
        data instead of stalling the follower thread. ``newest_us``
        (the batch's newest event time) rides along so the runner can
        measure event->serving-visible freshness when the async apply
        lands."""
        if not pairs:
            return
        while True:
            try:
                self._queue.put_nowait((pairs, newest_us))
                return
            except queue.Full:
                try:
                    self._queue.get_nowait()
                    with self._lock:
                        self.dropped_batches += 1
                except queue.Empty:
                    continue

    def stop(self) -> None:
        self._stop.set()
        self._queue.put(None)  # wake the consumer
        self._thread.join(timeout=5.0)

    # ----------------------------------------------------------------- loop
    def _cold_rows(self, n: int, dim: int) -> np.ndarray:
        rows = self._rng.standard_normal((n, dim)).astype(np.float32)
        rows /= np.linalg.norm(rows, axis=1, keepdims=True) + 1e-8
        return rows

    def _run(self) -> None:
        while not self._stop.is_set():
            item = self._queue.get()
            if item is None or self._stop.is_set():
                break
            try:
                self._train_one(*item)
            except Exception:
                logger.exception("streaming trainer step failed; continuing")

    def _train_one(
        self, pairs: list[tuple[str, str]], newest_us: int = 0
    ) -> None:
        model = self._model
        dim = int(np.asarray(model.item_vecs).shape[1]) if len(
            model.item_index
        ) else 0
        # cold-start injection first: unseen entities get a normalized
        # random row so the SGD step (and serving) can address them
        new_users = sorted(
            {u for u, _ in pairs if model.user_index.get(u) is None}
        )
        new_items = sorted(
            {i for _, i in pairs if model.item_index.get(i) is None}
        )
        if new_users or new_items:
            res = self._apply(
                OnlineUpdate(
                    user_ids=new_users,
                    user_rows=self._cold_rows(len(new_users), dim),
                    item_ids=new_items,
                    item_rows=self._cold_rows(len(new_items), dim),
                    seen_pairs=(),
                    info={"coldStart": True, "newestUs": newest_us},
                )
            ) or {}
            if not res.get("applied"):
                # a concurrent /reload superseded the generation this
                # trainer was bound to — the cold rows were NOT injected
                # (model.user_index[u] below would KeyError) and the
                # runner's rebind is about to replace this trainer;
                # abandon the work item instead of crashing on it
                return
        for lo in range(0, len(pairs), self._batch):
            chunk = pairs[lo : lo + self._batch]
            u_idx = np.asarray(
                [model.user_index[u] for u, _ in chunk], np.int64
            )
            i_idx = np.asarray(
                [model.item_index[i] for _, i in chunk], np.int64
            )
            uu, new_u, ui, new_i, loss = sgd_step(
                model.user_vecs, model.item_vecs, u_idx, i_idx,
                self._lr, self._temp,
            )
            inv_u = model.user_index.inverse
            inv_i = model.item_index.inverse
            res = self._apply(
                OnlineUpdate(
                    user_ids=[inv_u(int(r)) for r in uu],
                    user_rows=new_u,
                    item_ids=[inv_i(int(r)) for r in ui],
                    item_rows=new_i,
                    seen_pairs=chunk,
                    info={"loss": round(loss, 5), "newestUs": newest_us},
                )
            ) or {}
            with self._lock:
                self.steps += 1
                self.pairs_trained += len(chunk)
                self.last_loss = loss
            if not res.get("applied") and res.get("reason"):
                # superseded mid-item: later chunks would be dropped too
                return

    def stats_json(self) -> dict:
        with self._lock:
            return {
                "steps": self.steps,
                "pairsTrained": self.pairs_trained,
                "droppedBatches": self.dropped_batches,
                "lastLoss": self.last_loss,
                "queued": self._queue.qsize(),
            }
