"""Shared types of the online-learning subsystem (jax-free).

Engine templates implement the online hooks against these types without
importing the heavy halves of the package (`foldin`/`trainer` pull in
jax; this module is numpy + stdlib so a hook's *signature* costs
nothing on the default path — with ``--online`` off, nothing under
``predictionio_tpu.online`` is imported at all, CI-guarded).

The hook protocol (duck-typed — ``online/`` never imports templates, by
the layering manifest):

* ``algo.online_foldin(model, deltas, ds_params, config) ->
  OnlineUpdate | None`` — compute new factor rows for the entities an
  event batch touched, against the FIXED opposite-side factors (the
  classic MLlib-era fold-in). Read-only; runs outside the serving lock.
* ``algo.apply_online_update(model, update) -> dict`` — swap the touched
  rows into the live model (and inject cold-start rows). Runs UNDER the
  query service's generation lock; must be fast (row scatters, no
  solves).
* ``algo.online_trainer_spec(model) -> dict | None`` — opt into the
  streaming mini-batch trainer (two-tower) instead of fold-in; returns
  the hyperparameters ``online.trainer`` needs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

__all__ = ["OnlineConfig", "EventDelta", "OnlineUpdate"]


@dataclasses.dataclass(frozen=True)
class OnlineConfig:
    """Knobs of ``pio deploy --online`` (docs/operations.md has the
    runbook). Strictly opt-in: ``enabled`` False (the default) starts no
    follower thread and leaves serving byte-identical — CI-guarded like
    batching, caching, ANN, and resilience."""

    #: tail the event store and fold fresh events into the live model
    enabled: bool = False
    #: seconds between watermark polls of the columnar tail
    interval_s: float = 1.0
    #: most events folded per batch; a burst larger than this folds over
    #: several consecutive batches (bounds per-fold solve latency)
    batch_size: int = 4096
    #: comma-derived template/algorithm allowlist; empty = every deployed
    #: algorithm that implements the online hooks participates
    algorithms: tuple[str, ...] = ()
    #: strength of the anchor to the entity's pre-fold row in the ALS
    #: re-solve (``mu`` in ``min ||r - Y x||^2 + lambda n ||x||^2 +
    #: mu ||x - x_old||^2``). 0 = pure fold-in from online-observed
    #: ratings only; higher keeps rows closer to the trained optimum
    #: while their online history is still thin.
    prior_weight: float = 1.0
    #: most entities the per-entity online rating accumulator retains
    #: (LRU per side) — bounds follower memory on unbounded id spaces
    max_entities: int = 100_000
    #: mini-batch size of the streaming two-tower trainer
    trainer_batch: int = 256
    #: learning rate of the streaming two-tower trainer
    trainer_lr: float = 0.05
    #: fold events already in the store at deploy time too (default:
    #: start at the watermark's end — history is the trained model's job)
    from_start: bool = False
    #: override for the watermark file ("" = <basedir>/online/)
    state_dir: str = ""

    def __post_init__(self) -> None:
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if self.prior_weight < 0:
            raise ValueError("prior_weight must be >= 0")


@dataclasses.dataclass(frozen=True)
class EventDelta:
    """One followed event, reduced to what fold-in consumes."""

    event: str
    user: str
    item: str | None
    t_us: int
    #: numeric ``rating`` property when present (NaN = absent)
    rating: float = float("nan")


@dataclasses.dataclass
class OnlineUpdate:
    """New factor rows for one (algorithm, model) pair, computed by
    ``online_foldin`` (or the streaming trainer) and applied by
    ``apply_online_update`` under the serving lock.

    ``user_ids``/``item_ids`` may name entities absent from the model's
    index — those are cold-start injections: ``apply_online_update``
    extends the id maps and appends their rows. ``seen_pairs`` (two-tower
    only) grows the serving-time seen-item filter coherently with the
    folded events."""

    user_ids: Sequence[str] = ()
    user_rows: Any = None  # np.ndarray [len(user_ids), K]
    item_ids: Sequence[str] = ()
    item_rows: Any = None  # np.ndarray [len(item_ids), K]
    seen_pairs: Sequence[tuple[str, str]] = ()
    #: additional invalidation scopes beyond ``user_ids`` — e.g. the
    #: raters of a touched ITEM, whose own row did not move but whose
    #: ranked results just changed
    extra_scopes: Sequence[str] = ()
    #: loss/diagnostic info for /stats.json (free-form per algorithm)
    info: dict = dataclasses.field(default_factory=dict)

    @property
    def empty(self) -> bool:
        return not self.user_ids and not self.item_ids

    def touched_scopes(self) -> list[str]:
        """Per-scope cache invalidation targets: the users whose ranked
        results this update changes directly (their own row moved, a
        pair they appear in was folded, or an item they rated moved)."""
        scopes = {str(u) for u in self.user_ids}
        scopes.update(str(u) for u, _ in self.seen_pairs)
        scopes.update(str(s) for s in self.extra_scopes)
        return sorted(scopes)


def latest_wins(
    deltas: Sequence[EventDelta],
) -> dict[tuple[str, str], tuple[int, float]]:
    """Collapse a delta batch to one rating per (user, item): latest
    event wins, equal timestamps break toward the higher rating — the
    SAME rule the training read uses, so a fold followed by a retrain
    converges to the same data."""
    out: dict[tuple[str, str], tuple[int, float]] = {}
    for d in deltas:
        if d.item is None or not np.isfinite(d.rating):
            continue
        key = (d.user, d.item)
        cand = (d.t_us, float(d.rating))
        prev = out.get(key)
        if prev is None or cand >= prev:
            out[key] = cand
    return out
