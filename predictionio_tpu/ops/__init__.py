"""TPU compute ops — the replacement for the reference's MLlib dependency.

The reference delegates all ML math to Spark MLlib (ALS for the
Recommendation/Similar-Product/E-Commerce templates, NaiveBayes for
Classification — reached via the template repos, SURVEY.md section 3.8).
Here those kernels are first-class, implemented as jit/pjit-compiled JAX
programs designed for the MXU: batched einsums + batched Cholesky solves,
static shapes via bucketed padding, factors sharded over the device mesh.
"""
