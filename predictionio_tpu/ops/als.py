"""Alternating Least Squares, TPU-native.

Replaces Spark MLlib's ``org.apache.spark.ml.recommendation.ALS``, the
training kernel behind the reference's Recommendation / Similar-Product /
E-Commerce templates (reached from ``PAlgorithm.train`` — see SURVEY.md
sections 3.9, 8.1). Nothing here is a port: MLlib's block-partitioned
shuffle becomes sharded dense compute + XLA collectives, following the
ALX recipe (PAPERS.md — "ALX: Large Scale Matrix Factorization on TPUs").

Memory-bounded solver design (v2):

* **Segmented bucketing** — every row's ragged rating list is split into
  fixed-width segments (powers of two, 8..512 by default). Rows hotter
  than the max width span multiple max-width segments ("hot" rows), so
  no tensor ever scales with the hottest row.
* **Chunked scans** — each bucket is processed in bounded row-chunks via
  ``lax.scan``: peak HBM is O(chunk_entries · rank), *independent of
  bucket size*. This is what lets a 20M-rating sweep fit in one chip's
  HBM (round-1 materialized whole buckets and OOM'd: VERDICT.md weak #1).
* **Two solve paths** — rows that fit one segment are solved in-chunk
  (batched normal equations + Cholesky) and scattered straight into the
  factor table. Hot rows accumulate partial Gramians ``A += QᵀWQ``,
  ``b += Qᵀr`` across their segments (scatter-add into ``[H, K, K]``,
  where H ≤ nnz / max_width by construction) and are solved once at the
  end of the half-sweep.
* **Mesh sharding** — bucket rows/segments are sharded over the ``data``
  axis; the persistent factor tables are sharded over the ``model`` axis
  (ALX-style — NOT replicated, so catalog size scales with the mesh).
  The opposite table never materializes replicated: under ``shard_map``
  each device gathers only from its LOCAL table shard (out-of-shard
  entries masked to zero) and the partial Gramians ``[C,K,K]`` are
  psum'd over ``model`` — the small normal-equation blocks move over
  ICI instead of the catalog-sized table, so peak per-device HBM is
  O(catalog / model_axis) + O(chunk). Solved rows scatter back to
  their ``model`` shard (GSPMD emits the exchange).
* **Hot-slot grouping** — the hot-row Gramian accumulator is built per
  group of at most ``hot_group_slots`` rows, so its ``[H,K,K]`` buffer
  is bounded by a config knob instead of growing with nnz/max_width.

Supports MLlib's two objectives:

* **explicit** — squared error with ALS-WR regularization (λ scaled by
  each row's rating count, MLlib default).
* **implicit** (Hu-Koren-Volinsky) — confidence ``c = 1 + α·|r|``,
  preference ``p = [r > 0]``, shared ``YᵀY`` Gramian once per half-sweep,
  and λ scaled by the row's positive-rating count (MLlib's
  ``numExplicits`` scaling, so reference ``lambda`` values transfer).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import types
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from predictionio_tpu.ops.compat import (
    reshard,
    shard_map,
    sharded_gather,
    sharded_matmul,
    sharded_scatter_add,
    sharded_scatter_set,
)
from predictionio_tpu.ops.topk import top_k_scores

__all__ = [
    "ALSConfig",
    "ALSFactors",
    "BucketedRatings",
    "build_buckets",
    "build_buckets_device",
    "train_als",
    "als_sweep",
    "predict_scores",
    "top_k_items",
    "top_k_items_batch",
]

#: Segment widths: multiples of 8 at ~1.33-1.5x steps, so within-bucket
#: padding is < 1.5x (measured padding efficiency 0.787 vs 0.625 for the
#: former powers-of-two set at the 20M bench; sweep ~1.09x faster).
#: Rows with more ratings than the max width split into hot segments.
_DEFAULT_BUCKET_WIDTHS = (8, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512)

#: Max padded entries (rows × width) processed per scan step. Bounds the
#: per-chunk gather at chunk_entries·rank·4 bytes (1 GB at rank 64).
#: Measured on v5e at 20M nnz rank 64: 2^22 is ~20% faster per sweep
#: than 2^20 (fewer scan steps amortize better); 2^23 adds only ~2%.
_DEFAULT_CHUNK_ENTRIES = 1 << 22

#: Max rows per scan step, independent of width: bounds the batched
#: normal-equation buffers at chunk_rows·K²·4 bytes (512 MB at rank 64)
#: — without it a narrow bucket at large chunk_entries would build a
#: [chunk_entries/width, K, K] solve buffer far bigger than the gather.
_DEFAULT_CHUNK_ROWS = 1 << 15

# read-only: als_sweep (jit) closes over this table, so a mutable dict
# here would be frozen into the compiled program at trace time (piolint
# PIO302) — the proxy makes the immutability the trace assumes explicit
_PRECISIONS = types.MappingProxyType({
    "default": jax.lax.Precision.DEFAULT,
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
})


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    """Hyperparameters (parity: MLlib ``ALS`` params ``rank``, ``maxIter``,
    ``regParam``, ``implicitPrefs``, ``alpha``, ``seed``)."""

    rank: int = 10
    iterations: int = 10
    reg: float = 0.1
    implicit: bool = False
    alpha: float = 1.0
    seed: int = 0
    #: pad rank up to a multiple of this for MXU-friendly K (0 = exact rank)
    rank_pad_multiple: int = 0
    #: orbax step-checkpoint directory ("" = off); training resumes from
    #: the latest step found there (resume-on-preemption, SURVEY.md 6.4)
    checkpoint_dir: str = ""
    checkpoint_interval: int = 5
    #: segment widths for bucketing (see build_buckets)
    bucket_widths: tuple = _DEFAULT_BUCKET_WIDTHS
    #: max padded entries per scan chunk — the HBM knob
    chunk_entries: int = _DEFAULT_CHUNK_ENTRIES
    #: max hot rows per Gramian-accumulator group: bounds the [H,K,K]
    #: hot accumulator at hot_group_slots·K² floats per group (extra
    #: groups only cost one more batched solve + scatter each)
    hot_group_slots: int = 2048
    #: where the O(nnz) bucketing work runs: "auto" sorts/fills on the
    #: accelerator when training single-device on TPU/GPU (the host sort
    #: alone costs ~3 s/side at 20M nnz on one core), "host"/"device"
    #: force a path. Mesh and multi-host layouts always bucket on host.
    bucketing: str = "auto"
    #: matmul precision for the normal equations: "highest" (full f32,
    #: MLlib-parity accuracy), "high", or "default" (bf16 passes).
    #: "highest" is the recommended default: the sweep is gather-bound,
    #: so bf16 measured only ~0.6% faster at 20M nnz rank 64 on v5e
    #: while costing ~6% top-10 overlap churn (bench precision_compare).
    precision: str = "highest"
    #: SPD solver for the normal equations: "auto" picks the Pallas
    #: blocked-Gauss-Jordan kernel on a single-device TPU backend (~3x
    #: faster than XLA Cholesky at bench shapes) and Cholesky elsewhere;
    #: explicit "cholesky" / "pallas" / "pallas_interpret" override.
    solver: str = "auto"


class ALSFactors(NamedTuple):
    """The model: dense factor matrices. Row ``num_rows`` of each is a
    zero sentinel used as the scatter target for padding (stripped by
    :func:`train_als` before returning)."""

    user: jax.Array  # [num_users(+1), K]
    item: jax.Array  # [num_items(+1), K]


class _Chunked(NamedTuple):
    """One bucket in scan layout: ``n_chunks`` steps of ``C`` rows of a
    fixed segment width ``L`` (all shapes static for XLA)."""

    row_id: Any  # [n_chunks, C] int32 — row index (normal) or hot slot (hot);
    #              padding rows carry the sentinel (num_rows / num_hot)
    idx: Any  # [n_chunks, C, L] int32 — column indices into the other side
    val: Any  # [n_chunks, C, L] f32 — ratings (0 where masked)
    mask: Any  # [n_chunks, C, L] f32 — 1 for real entries


class BucketedRatings(NamedTuple):
    """One side of the ratings matrix in solver layout.

    Registered as a custom pytree below: the array fields (``normal``,
    ``hot``, ``hot_rows``) are children; the int metadata travels in the
    treedef so it stays STATIC under jit (a multi-process jit must not
    receive per-host scalar leaves, and the sentinel row index wants to
    be a compile-time constant).

    Hot rows are split into GROUPS of at most ``hot_group_slots`` rows:
    ``hot[g]`` holds group g's segments with group-local slot ids and
    ``hot_rows[g]`` maps those slots back to row ids — so the sweep's
    Gramian accumulator is [H_g, K, K], never [num_hot, K, K]."""

    normal: tuple  # tuple[_Chunked, ...] — rows fitting one segment
    hot: tuple  # tuple[_Chunked, ...] — one per group (row_id = local slot)
    hot_rows: tuple  # tuple of [H_g + 1] int32 — slot -> row id; last = sentinel
    num_rows: int
    num_cols: int
    nnz: int  # real entries
    padded_nnz: int  # entries incl. padding (MXU work actually done)


jax.tree_util.register_pytree_node(
    BucketedRatings,
    lambda b: ((b.normal, b.hot, b.hot_rows),
               (b.num_rows, b.num_cols, b.nnz, b.padded_nnz)),
    lambda aux, ch: BucketedRatings(ch[0], ch[1], ch[2], *aux),
)


def _chunk(arrs: list, n: int, c: int, l: int) -> _Chunked:
    """Reshape flat [B(,L)] bucket arrays into scan layout [n, C(, L)]."""
    row_id, idx, val, mask = arrs
    return _Chunked(
        row_id.reshape(n, c),
        idx.reshape(n, c, l),
        val.reshape(n, c, l),
        mask.reshape(n, c, l),
    )


def _fill_bucket(
    n_seg: int,
    n_pad: int,
    width: int,
    seg_row: np.ndarray,
    seg_start: np.ndarray,
    seg_len: np.ndarray,
    cols_s: np.ndarray,
    vals_s: np.ndarray,
    sentinel: int,
) -> list:
    """Vectorized ragged fill of one bucket's [n_pad, width] arrays from
    sorted COO slices (no per-row Python loop — this runs at full-catalog
    scale before the first TPU step)."""
    row_id = np.full(n_pad, sentinel, dtype=np.int32)
    idx = np.zeros((n_pad, width), dtype=np.int32)
    val = np.zeros((n_pad, width), dtype=np.float32)
    mask = np.zeros((n_pad, width), dtype=np.float32)
    row_id[:n_seg] = seg_row
    if n_seg:
        dst_row = np.repeat(np.arange(n_seg), seg_len)
        lane_end = np.cumsum(seg_len)
        dst_lane = np.arange(int(lane_end[-1])) - np.repeat(lane_end - seg_len, seg_len)
        src = np.repeat(seg_start, seg_len) + dst_lane
        idx[dst_row, dst_lane] = cols_s[src]
        val[dst_row, dst_lane] = vals_s[src]
        mask[dst_row, dst_lane] = 1.0
    return [row_id, idx, val, mask]


class _Segments(NamedTuple):
    """Host-side segmentation of one COO shard (pre-padding layout)."""

    per_width: dict  # width -> (seg_row int32, seg_start, seg_len)
    hot_slot: np.ndarray  # local hot-slot id per hot segment
    hot_start: np.ndarray
    hot_len: np.ndarray
    hot_rows: np.ndarray  # [H_local] row ids of hot rows
    w_max: int
    cols_s: np.ndarray  # row-sorted column ids
    vals_s: np.ndarray  # row-sorted values
    rated: np.ndarray  # bool [num_rows] — rows present in this shard


def _segment(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    num_cols: int,
    widths: Sequence[int],
) -> _Segments:
    """Validate + sort one COO shard and split every row into fixed-width
    segments: rows with <= max(widths) ratings get one segment in the
    smallest fitting width; hotter rows get ceil(count/w_max) segments."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    if not (rows.shape == cols.shape == vals.shape):
        raise ValueError("rows/cols/vals must be 1-D arrays of equal length")
    if rows.size and (rows.min() < 0 or rows.max() >= num_rows):
        raise ValueError("row index out of range")
    if cols.size and (cols.min() < 0 or cols.max() >= num_cols):
        raise ValueError("column index out of range")

    usable = _usable_widths(widths)
    w_max = usable[-1]

    order = np.argsort(rows, kind="stable")
    cols_s, vals_s = cols[order], vals[order]
    # counts via bincount instead of np.unique: unique re-sorts the 20M+
    # array a second time (2.5 s/side at ML-20M scale) where bincount is a
    # single O(nnz) pass (VERDICT r2 item 2)
    counts_all = np.bincount(rows, minlength=num_rows)
    uniq, starts, counts = _row_offsets(counts_all)
    rated = counts_all > 0

    plan = _plan_segments(uniq, starts, counts, usable)
    return _Segments(
        plan["per_width"], plan["hot_slot"], plan["hot_start"], plan["hot_len"],
        plan["hot_rows"], w_max, cols_s, vals_s, rated,
    )


def _usable_widths(widths: Sequence[int]) -> list:
    usable = sorted({int(w) for w in widths if w >= 1})
    if not usable:
        raise ValueError("widths must contain at least one positive width")
    return usable


def _row_offsets(counts_all: np.ndarray) -> tuple:
    """(uniq row ids, their start offset in the row-sorted layout, their
    counts) from a dense per-row count vector — O(num_rows)."""
    uniq = np.nonzero(counts_all)[0]
    counts = counts_all[uniq]
    starts = (np.cumsum(counts_all) - counts_all)[uniq]
    return uniq, starts, counts


def _plan_segments(
    uniq: np.ndarray, starts: np.ndarray, counts: np.ndarray, usable: list
) -> dict:
    """Split rows into fixed-width segments given per-row counts — the
    O(num_rows) planning shared by the host and device bucketing paths."""
    w_max = usable[-1]
    is_hot = counts > w_max
    per_width: dict = {}
    lo = 0
    for w in usable:
        sel = np.nonzero(~is_hot & (counts > lo) & (counts <= w))[0]
        lo = w
        if sel.size:
            per_width[w] = (uniq[sel].astype(np.int32), starts[sel], counts[sel])

    hot_sel = np.nonzero(is_hot)[0]
    num_hot = int(hot_sel.size)
    if num_hot:
        h_counts = counts[hot_sel]
        n_segs = -(-h_counts // w_max)  # per hot row
        hot_slot = np.repeat(np.arange(num_hot, dtype=np.int32), n_segs)
        # segment k of a row starts at row_start + k*w_max
        seg_k = np.arange(int(n_segs.sum())) - np.repeat(
            np.cumsum(n_segs) - n_segs, n_segs
        )
        hot_start = np.repeat(starts[hot_sel], n_segs) + seg_k * w_max
        hot_len = np.minimum(
            np.repeat(h_counts, n_segs) - seg_k * w_max, w_max
        ).astype(np.int64)
        hot_rows = uniq[hot_sel].astype(np.int32)
    else:
        hot_slot = np.zeros(0, np.int32)
        hot_start = np.zeros(0, np.int64)
        hot_len = np.zeros(0, np.int64)
        hot_rows = np.zeros(0, np.int32)
    return {
        "per_width": per_width, "hot_slot": hot_slot, "hot_start": hot_start,
        "hot_len": hot_len, "hot_rows": hot_rows,
    }


def build_buckets(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    num_cols: int,
    widths: Sequence[int] = _DEFAULT_BUCKET_WIDTHS,
    row_multiple: int = 8,
    chunk_entries: int = _DEFAULT_CHUNK_ENTRIES,
    hot_group_slots: int = 2048,
) -> BucketedRatings:
    """Host-side: COO ratings -> chunked, segmented, padded buckets.

    Rows with at most ``max(widths)`` ratings go to the smallest width
    that fits (normal path). Hotter rows are split into ``max(widths)``-
    wide segments (hot path) so no shape depends on the hottest row.
    Every bucket is laid out as ``[n_chunks, C, L]`` with
    ``C·L ≤ chunk_entries`` and ``C`` a multiple of ``row_multiple``
    (keep that a multiple of the mesh's data-axis size so chunk rows
    shard evenly). Rows with zero ratings are absent — ``train_als``
    zeroes their factors via the rated-row mask.
    """
    seg = _segment(rows, cols, vals, num_rows, num_cols, widths)
    nnz = int(np.asarray(rows).size)
    padded_nnz = 0
    normal_chunks: list = []
    hot_chunks: list = []

    def pack(seg_row, seg_start, seg_len, width, sentinel):
        """Pad segments to chunked layout and append a _Chunked."""
        nonlocal padded_nnz
        n_seg = int(seg_row.size)
        c, n_chunks, n_pad = _chunk_plan(n_seg, width, row_multiple, chunk_entries)
        padded_nnz += n_pad * width
        arrs = _fill_bucket(
            n_seg, n_pad, width, seg_row, seg_start, seg_len,
            seg.cols_s, seg.vals_s, sentinel,
        )
        return _chunk(arrs, n_chunks, c, width)

    plan = {
        "per_width": seg.per_width, "hot_slot": seg.hot_slot,
        "hot_start": seg.hot_start, "hot_len": seg.hot_len,
        "hot_rows": seg.hot_rows,
    }
    hot_rows_groups: list = []
    for seg_row, seg_start, seg_len, width, sentinel, hr in _bucket_defs(
        plan, num_rows, seg.w_max, hot_group_slots
    ):
        chunked = pack(seg_row, seg_start, seg_len, width, sentinel)
        if hr is None:
            normal_chunks.append(chunked)
        else:
            hot_chunks.append(chunked)
            hot_rows_groups.append(hr)

    return BucketedRatings(
        tuple(normal_chunks),
        tuple(hot_chunks),
        tuple(hot_rows_groups),
        num_rows,
        num_cols,
        nnz,
        padded_nnz,
    )


def _chunk_plan(
    n_seg: int, width: int, row_multiple: int, chunk_entries: int
) -> tuple[int, int, int]:
    """(rows per chunk, n_chunks, padded rows) for one bucket. Rows are
    bounded both by entries (the gather buffer) and by _DEFAULT_CHUNK_ROWS
    (the [C, K, K] normal-equation buffers)."""
    c = max(row_multiple, (chunk_entries // width) // row_multiple * row_multiple)
    cap = max(row_multiple, _DEFAULT_CHUNK_ROWS // row_multiple * row_multiple)
    c = min(c, cap, -(-max(n_seg, 1) // row_multiple) * row_multiple)
    n_chunks = -(-max(n_seg, 1) // c)
    return c, n_chunks, n_chunks * c


@functools.partial(jax.jit, static_argnames=("n_max",))
def _sort_coo(
    rows: jax.Array, cols: jax.Array, vals: jax.Array, n_max: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Device-side row-sort of the COO + per-row counts. One fused XLA
    program: the 20M-entry sort that costs ~3 s/side single-threaded on
    host runs in well under a second on the chip. ``n_max`` is padded to
    ``max(num_rows, num_cols)`` by the caller so the user- and item-side
    sorts share one compiled program."""
    _, cols_s, vals_s = jax.lax.sort((rows, cols, vals), num_keys=1)
    counts = jnp.zeros(n_max, jnp.int32).at[rows].add(1)
    return cols_s, vals_s, counts


@jax.jit
def _coo_stats(rows: jax.Array, cols: jax.Array) -> jax.Array:
    """[min(rows), min(cols), max(cols)] — one fused validation readback."""
    return jnp.stack([jnp.min(rows), jnp.min(cols), jnp.max(cols)])


@functools.partial(jax.jit, static_argnames=("shapes",))
def _fill_buckets(cs: jax.Array, vs: jax.Array, meta: jax.Array, shapes: tuple):
    """Gather-based ragged fill: idx[r, l] = cols_s[start[r] + l] for
    l < len[r] — one fused gather per bucket, no host scatter. All bucket
    metadata travels in ONE concatenated operand (remote backends pay a
    round-trip per transfer, not per byte); ``shapes`` is the static
    (width, rows_per_chunk, n_chunks) tuple per bucket. Module-level jit:
    a per-call closure would recompile on every train."""
    out = []
    off = 0
    for width, c, n_chunks in shapes:
        n_pad = c * n_chunks
        row_id = meta[off : off + n_pad]
        st = meta[off + n_pad : off + 2 * n_pad]
        ln = meta[off + 2 * n_pad : off + 3 * n_pad]
        off += 3 * n_pad
        lane = jnp.arange(width, dtype=jnp.int32)[None, :]
        lm = lane < ln[:, None]
        src = jnp.where(lm, st[:, None] + lane, 0)
        out.append(
            _Chunked(
                row_id.reshape(n_chunks, c),
                jnp.where(lm, cs[src], 0).reshape(n_chunks, c, width),
                jnp.where(lm, vs[src], 0.0).reshape(n_chunks, c, width),
                lm.astype(jnp.float32).reshape(n_chunks, c, width),
            )
        )
    return tuple(out)


def _bucket_defs(plan: dict, num_rows: int, w_max: int, hot_group_slots: int):
    """Yield ``(seg_row, seg_start, seg_len, width, sentinel, hot_rows_g)``
    per bucket — normal-width buckets first (hot_rows_g None), then hot
    groups of <= hot_group_slots slots. The single source of truth for
    bucket/group structure, shared by the host and device fill paths."""
    for w in sorted(plan["per_width"]):
        seg_row, seg_start, seg_len = plan["per_width"][w]
        yield seg_row, seg_start, seg_len, w, num_rows, None
    num_hot = int(plan["hot_rows"].size)
    if num_hot:
        H = hot_group_slots
        g_of_seg = plan["hot_slot"] // H
        for g in range(-(-num_hot // H)):
            sel = g_of_seg == g
            h_g = min(H, num_hot - g * H)
            hr = np.full(h_g + 1, num_rows, dtype=np.int32)
            hr[:h_g] = plan["hot_rows"][g * H : g * H + h_g]
            yield (
                (plan["hot_slot"][sel] - g * H).astype(np.int32),
                plan["hot_start"][sel], plan["hot_len"][sel],
                w_max, h_g, hr,
            )


def build_buckets_device(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    num_cols: int,
    widths: Sequence[int] = _DEFAULT_BUCKET_WIDTHS,
    row_multiple: int = 8,
    chunk_entries: int = _DEFAULT_CHUNK_ENTRIES,
    hot_group_slots: int = 2048,
) -> tuple[BucketedRatings, np.ndarray]:
    """Device-side bucketing: COO ratings -> chunked, segmented, padded
    buckets, with every O(nnz) step on the accelerator.

    The host transfers the raw COO once, reads back only the O(num_rows)
    per-row counts, and plans segment/chunk shapes from them; the sort
    and the padded gather-fills run on device (VERDICT r2 item 2 — the
    20 s single-threaded host bucketing at 20M nnz drops to the device
    sort + a metadata pass). Single-device layout: the mesh path shards
    host-built buckets; the multi-host path has its own assembler.

    Accepts numpy COO arrays, or ``jax.Array``s already on device (int32
    indices) — the latter skips the host round-trip and validates on
    device instead (explicit min/max reductions plus the bincount sum:
    jax scatters WRAP negative indices, so a sum check alone is not
    enough).

    Returns ``(bucketed ratings with device arrays, rated-row mask)``.
    """
    on_device = all(
        isinstance(a, jax.Array) and not isinstance(a, np.ndarray)
        for a in (rows, cols, vals)
    )
    if not on_device:
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        vals = np.asarray(vals, dtype=np.float32)
    if not (rows.shape == cols.shape == vals.shape):
        raise ValueError("rows/cols/vals must be 1-D arrays of equal length")
    if not on_device:
        if rows.size and (rows.min() < 0 or rows.max() >= num_rows):
            raise ValueError("row index out of range")
        if cols.size and (cols.min() < 0 or cols.max() >= num_cols):
            raise ValueError("column index out of range")
    usable = _usable_widths(widths)
    w_max = usable[-1]
    nnz = int(rows.size)
    if nnz == 0 or nnz >= 2**31 or max(num_rows, num_cols) >= 2**31:
        # int32 device indices would overflow — use the host path
        b = build_buckets(
            np.asarray(rows), np.asarray(cols), np.asarray(vals),
            num_rows, num_cols, widths,
            row_multiple, chunk_entries, hot_group_slots,
        )
        return _device_buckets(b, None), rated_row_mask(b)

    if on_device:
        rows_d, cols_d, vals_d = rows, cols, vals
        if jnp.issubdtype(rows_d.dtype, jnp.int64):
            rows_d = rows_d.astype(jnp.int32)
            cols_d = cols_d.astype(jnp.int32)
    else:
        rows_d = jnp.asarray(rows.astype(np.int32))
        cols_d = jnp.asarray(cols.astype(np.int32))
        vals_d = jnp.asarray(vals)
    # pad the count vector to max(rows, cols) so both transposed sides
    # share one compiled sort (XLA compile is expensive on remote backends)
    n_max = max(num_rows, num_cols)
    cols_s, vals_s, counts_d = _sort_coo(rows_d, cols_d, vals_d, n_max)
    counts_full = np.asarray(counts_d).astype(np.int64)
    counts_all = counts_full[:num_rows]
    if on_device:
        # device-side validation, one readback: negative indices WRAP in
        # jax scatters/gathers (they are not dropped), so min() checks are
        # mandatory; rows >= num_rows land in the padding region of the
        # count vector and make the in-range sum fall short
        stats = np.asarray(_coo_stats(rows_d, cols_d))
        if stats[0] < 0 or int(counts_all.sum()) != nnz:
            raise ValueError("row index out of range")
        if stats[1] < 0 or stats[2] >= num_cols:
            raise ValueError("column index out of range")
    uniq, starts, counts = _row_offsets(counts_all)
    plan = _plan_segments(uniq, starts, counts, usable)

    metas: list = []  # (row_id[n_pad], start[n_pad], len[n_pad], width, c, n_chunks)
    padded_nnz = 0
    n_normal = 0
    hot_rows_groups: list = []
    for seg_row, seg_start, seg_len, width, sentinel, hr in _bucket_defs(
        plan, num_rows, w_max, hot_group_slots
    ):
        n_seg = int(seg_row.size)
        c, n_chunks, n_pad = _chunk_plan(n_seg, width, row_multiple, chunk_entries)
        padded_nnz += n_pad * width
        row_id = np.full(n_pad, sentinel, np.int32)
        row_id[:n_seg] = seg_row
        st = np.zeros(n_pad, np.int32)
        st[:n_seg] = seg_start
        ln = np.zeros(n_pad, np.int32)
        ln[:n_seg] = seg_len
        metas.append((row_id, st, ln, width, c, n_chunks))
        if hr is None:
            n_normal += 1
        else:
            hot_rows_groups.append(hr)

    shapes = tuple((m[3], m[4], m[5]) for m in metas)
    meta_concat = (
        np.concatenate([np.concatenate([m[0], m[1], m[2]]) for m in metas])
        if metas
        else np.zeros(0, np.int32)
    )
    chunks = (
        _fill_buckets(cols_s, vals_s, jnp.asarray(meta_concat), shapes)
        if metas
        else ()
    )
    bucketed = BucketedRatings(
        tuple(chunks[:n_normal]),
        tuple(chunks[n_normal:]),
        tuple(hot_rows_groups),
        num_rows,
        num_cols,
        nnz,
        padded_nnz,
    )
    return bucketed, counts_all > 0


def rated_row_mask(b: BucketedRatings) -> np.ndarray:
    """Bool [num_rows]: which rows appear in the ratings. Rows outside get
    zero factors (parity: the reference only emits factors for trained
    entities — VERDICT round-1 advisor finding on random unrated scores)."""
    mask = np.zeros(b.num_rows + 1, dtype=bool)
    for ch in b.normal:
        mask[np.asarray(ch.row_id).ravel()] = True
    for hr in b.hot_rows:
        mask[np.asarray(hr)] = True
    mask[b.num_rows] = False
    return mask[: b.num_rows]


# ---------------------------------------------------------------------------
# Solver kernels (pure, jit-compiled)
# ---------------------------------------------------------------------------


def _partials(
    Q: jax.Array,  # [C, L, K] masked gathered factors
    chunk_val: jax.Array,  # [C, L]
    meff: jax.Array,  # [C, L] effective mask (0 where padded / out of shard)
    implicit: bool,
    alpha: float,
    hi: jax.lax.Precision,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-chunk partial normal equations (no λ/YᵀY yet). All heavy ops
    are [C,L,K]-shaped einsums -> MXU."""
    if implicit:
        conf_minus_1 = alpha * jnp.abs(chunk_val) * meff  # c - 1
        pref = (chunk_val > 0).astype(Q.dtype) * meff
        A = jnp.einsum("clk,cl,clj->ckj", Q, conf_minus_1, Q, precision=hi)
        b = jnp.einsum("clk,cl->ck", Q, (1.0 + conf_minus_1) * pref, precision=hi)
        n = pref.sum(axis=-1)  # MLlib numExplicits: positive ratings
    else:
        A = jnp.einsum("clk,clj->ckj", Q, Q, precision=hi)
        b = jnp.einsum("clk,cl->ck", Q, chunk_val * meff, precision=hi)
        n = meff.sum(axis=-1)
    return A, b, n


def _gram_chunk(
    other: jax.Array,  # [num_cols+1(+pad), K] — model-sharded on a 2-axis mesh
    chunk_idx: jax.Array,  # [C, L]
    chunk_val: jax.Array,  # [C, L]
    chunk_mask: jax.Array,  # [C, L]
    implicit: bool,
    alpha: float,
    hi: jax.lax.Precision,
    mesh: Mesh | None,
    data_axis: str | None,
    model_axis: str | None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Partial normal equations for one chunk of segments.

    Returns (A [C,K,K], b [C,K], n [C]) WITHOUT the λ/YᵀY terms, so the
    same kernel serves both the in-chunk solve (normal rows) and the
    Gramian accumulation (hot-row segments).

    With a model axis the opposite table stays SHARDED: under shard_map
    each device gathers only from its local [N/S, K] shard (entries
    owned by other shards masked to zero) and the partial Gramians are
    psum'd over ``model``. The catalog-sized table never moves or
    replicates — only O(C·K²) Gramian blocks cross ICI (VERDICT r2
    item 1; the chunk-Gramians-move-not-the-table half of the ALX
    recipe, PAPERS.md).
    """
    if mesh is not None and model_axis is not None:
        S = int(mesh.shape[model_axis])
        rps = other.shape[0] // S  # train_als pads the table to a multiple

        def local(tbl, idx, val, mask):
            me = jax.lax.axis_index(model_axis)
            lidx = idx - me * rps
            inr = (lidx >= 0) & (lidx < rps)
            meff = mask * inr.astype(mask.dtype)
            Q = tbl[jnp.where(inr, lidx, 0)] * meff[..., None]
            A, b, n = _partials(Q, val, meff, implicit, alpha, hi)
            return (
                jax.lax.psum(A, model_axis),
                jax.lax.psum(b, model_axis),
                jax.lax.psum(n, model_axis),
            )

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(
                PartitionSpec(model_axis, None),
                PartitionSpec(data_axis, None),
                PartitionSpec(data_axis, None),
                PartitionSpec(data_axis, None),
            ),
            out_specs=(
                PartitionSpec(data_axis, None, None),
                PartitionSpec(data_axis, None),
                PartitionSpec(data_axis),
            ),
        )(other, chunk_idx, chunk_val, chunk_mask)

    if mesh is not None:
        # data-parallel mesh (tables replicated by construction):
        # segment-sharded gather — each device touches only its rows
        gathered = sharded_gather(
            other, chunk_idx,
            NamedSharding(mesh, PartitionSpec(data_axis, None, None)),
        )
    else:
        gathered = other[chunk_idx]
    Q = gathered * chunk_mask[..., None]  # [C, L, K]
    return _partials(Q, chunk_val, chunk_mask, implicit, alpha, hi)


def _finish_solve(
    A: jax.Array,  # [.., K, K] accumulated Gramian (no reg / yty yet)
    b: jax.Array,  # [.., K]
    n: jax.Array,  # [..] per-row rating count
    reg: float,
    yty: jax.Array | None,
    solver: str,
) -> jax.Array:
    """Add ALS-WR regularization (λ·max(n,1)·I — MLlib scales λ by the
    rating count in both objectives) and the implicit YᵀY, then solve
    (Pallas blocked-GJ on TPU, Cholesky elsewhere — see ops/solve.py)."""
    from predictionio_tpu.ops.solve import spd_solve

    K = A.shape[-1]
    eye = jnp.eye(K, dtype=A.dtype)
    A = A + (reg * jnp.maximum(n, 1.0))[..., None, None] * eye
    if yty is not None:
        A = A + yty
    return spd_solve(A, b, method=solver)


def _half_sweep(
    factors: jax.Array,  # [num_rows+1, K] — side being updated (model-sharded)
    other_factors: jax.Array,  # [num_cols+1, K] (model-sharded)
    bucketed: BucketedRatings,
    reg: float,
    implicit: bool,
    alpha: float,
    hi: jax.lax.Precision,
    solver: str,
    mesh: Mesh | None,
    data_axis: str | None,
    model_axis: str | None,
) -> jax.Array:
    model_sharding = None
    if mesh is not None:
        # model_axis=None (axis absent from the mesh): replicated tables —
        # the pure-data-parallel layout of e.g. `pio train --mesh data=8`
        spec = PartitionSpec(model_axis, None) if model_axis else PartitionSpec(None, None)
        model_sharding = NamedSharding(mesh, spec)
    # The opposite table is consumed AS SHARDED: _gram_chunk's shard-map
    # path gathers from each device's local shard and psums the Gramians,
    # so the full table never materializes replicated (VERDICT r2 item 1).
    other = other_factors

    yty = None
    if implicit:
        # Gramian over the other side; sentinel row is zero so it is a
        # no-op term. From the model-sharded table this is a sharded
        # matmul whose contraction psums over the model axis (ICI).
        if mesh is not None:
            yty = sharded_matmul(
                other_factors.T, other_factors, precision=hi,
                sharding=NamedSharding(mesh, PartitionSpec(None, None)),
            )
        else:
            yty = jnp.matmul(other_factors.T, other_factors, precision=hi)

    # --- normal rows: solve in-chunk, scatter into the factor table ------
    for ch in bucketed.normal:

        def step(fac, xs):
            row_id, idx, val, mask = xs
            A, b, n = _gram_chunk(
                other, idx, val, mask, implicit, alpha, hi,
                mesh, data_axis, model_axis,
            )
            x = _finish_solve(A, b, n, reg, yty, solver)  # [C, K]
            # scatter data-sharded solved rows to their model shard —
            # GSPMD lowers to the ICI exchange replacing MLlib's
            # factor-block shuffle
            fac = sharded_scatter_set(fac, row_id, x, model_sharding)
            return fac, None

        factors, _ = jax.lax.scan(step, factors, tuple(ch))

    # --- hot rows: accumulate Gramians across segments, solve per group --
    # groups of <= hot_group_slots rows bound the accumulator at
    # [H_g, K, K] regardless of how many rows are hot (VERDICT r2 weak #2)
    K = factors.shape[-1]
    replicated = None if mesh is None else NamedSharding(mesh, PartitionSpec())
    for ch, hot_rows_g in zip(bucketed.hot, bucketed.hot_rows):
        num_slots = int(hot_rows_g.shape[0])  # H_g + sentinel
        acc = (
            jnp.zeros((num_slots, K, K), factors.dtype, device=replicated),
            jnp.zeros((num_slots, K), factors.dtype, device=replicated),
            jnp.zeros((num_slots,), factors.dtype, device=replicated),
        )

        def hot_step(carry, xs):
            A_acc, b_acc, n_acc = carry
            slot, idx, val, mask = xs
            A, b, n = _gram_chunk(
                other, idx, val, mask, implicit, alpha, hi,
                mesh, data_axis, model_axis,
            )
            # scatter-add partial Gramians: segments of one row combine
            # here — the hot-row splitting that bounds memory by
            # nnz/max_width instead of the hottest row's count. The
            # accumulators are replicated (H_g is config-bounded), so
            # on a mesh the adds psum across the data axis.
            A_acc = sharded_scatter_add(A_acc, slot, A, replicated)
            b_acc = sharded_scatter_add(b_acc, slot, b, replicated)
            n_acc = sharded_scatter_add(n_acc, slot, n, replicated)
            return (A_acc, b_acc, n_acc), None

        acc, _ = jax.lax.scan(hot_step, acc, tuple(ch))
        x_hot = _finish_solve(*acc, reg, yty, solver)  # [num_slots, K]
        hr = jnp.asarray(hot_rows_g)
        factors = sharded_scatter_set(factors, hr, x_hot, model_sharding)

    # padding rows scattered into the sentinel; re-zero it (array index:
    # the scalar-index path rejects/breaks on out_sharding). The sentinel
    # is row ``num_rows`` — the table may carry extra zero rows beyond it
    # so its length divides the model axis.
    sentinel = jnp.reshape(jnp.asarray(bucketed.num_rows, jnp.int32), (1,))
    zero = jnp.zeros((1, factors.shape[1]), factors.dtype)
    return sharded_scatter_set(factors, sentinel, zero, model_sharding)


@functools.partial(
    jax.jit,
    static_argnames=(
        "reg", "implicit", "alpha", "precision", "solver",
        "mesh", "data_axis", "model_axis",
    ),
    donate_argnums=(0, 1),
)
def als_sweep(
    user_factors: jax.Array,
    item_factors: jax.Array,
    user_bucketed: BucketedRatings,
    item_bucketed: BucketedRatings,
    reg: float,
    implicit: bool,
    alpha: float,
    precision: str = "highest",
    solver: str = "cholesky",
    mesh: Mesh | None = None,
    data_axis: str | None = None,
    model_axis: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """One full ALS iteration: solve users given items, then items given
    users. Compiled once; buffers donated so factors update in place."""
    hi = _PRECISIONS[precision]
    user_factors = _half_sweep(
        user_factors, item_factors, user_bucketed,
        reg, implicit, alpha, hi, solver, mesh, data_axis, model_axis,
    )
    item_factors = _half_sweep(
        item_factors, user_factors, item_bucketed,
        reg, implicit, alpha, hi, solver, mesh, data_axis, model_axis,
    )
    return user_factors, item_factors


def _device_buckets(
    b: BucketedRatings, mesh: Mesh | None, data_axis: str = "data"
) -> BucketedRatings:
    """Place bucket arrays on device — chunk rows sharded over the mesh's
    data axis when a mesh is given (replaces Spark's RDD partitioning).
    ``hot_rows`` stays a host numpy array (its size is static metadata)."""

    def put(ch: _Chunked) -> _Chunked:
        if mesh is not None:
            s1 = NamedSharding(mesh, PartitionSpec(None, data_axis))
            s2 = NamedSharding(mesh, PartitionSpec(None, data_axis, None))
            return _Chunked(
                jax.device_put(ch.row_id, s1),
                jax.device_put(ch.idx, s2),
                jax.device_put(ch.val, s2),
                jax.device_put(ch.mask, s2),
            )
        return _Chunked(
            jnp.asarray(ch.row_id),
            jnp.asarray(ch.idx),
            jnp.asarray(ch.val),
            jnp.asarray(ch.mask),
        )

    return BucketedRatings(
        tuple(put(ch) for ch in b.normal),
        tuple(put(ch) for ch in b.hot),
        tuple(np.asarray(hr) for hr in b.hot_rows),
        b.num_rows,
        b.num_cols,
        b.nnz,
        b.padded_nnz,
    )


def _multihost_bucketed(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    num_cols: int,
    mesh: Mesh,
    data_axis: str,
    widths: Sequence[int],
    chunk_entries: int,
    hot_group_slots: int = 2048,
) -> tuple[BucketedRatings, np.ndarray]:
    """Multi-host: per-host COO shards -> GLOBAL sharded bucket arrays
    without ever materializing the global rating set on one host
    (VERDICT round-1 missing #3 — replaces :func:`_allgather_coo`).

    1. All-to-all the shard so host ``p`` owns every rating of rows with
       ``row % P == p`` (bounded-memory exchange, O(nnz/P) steady state).
    2. Each host segments its rows locally (complete rows -> correct
       counts), then all hosts agree on per-width block shapes (a tiny
       metadata all-gather) so every host packs an identically-shaped
       block per bucket.
    3. ``jax.make_array_from_process_local_data`` assembles the global
       [n_chunks, P*c_local, L] arrays with the chunk-row axis sharded
       over ``data_axis`` (process-contiguous blocks — the mesh must be
       built over ``jax.devices()`` in process order, which
       ``mesh_context()`` does).

    Returns (bucketed ratings with global device arrays, this-host rated
    mask — OR it across hosts for the global mask).
    """
    from jax.experimental import multihost_utils  # noqa: F401  (doc pointer)

    from predictionio_tpu.parallel.exchange import allgather_objects, exchange_by_owner

    P = jax.process_count()
    me = jax.process_index()
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    # validate BEFORE the exchange, then agree on the verdict: a lone
    # raise would strand the peers in the next collective until the
    # distributed timeout, so every host gathers the error flags and they
    # all raise together (round-2 advisor finding)
    err = ""
    if rows.size and (rows.min() < 0 or rows.max() >= num_rows):
        err = f"process {me}: row index out of range"
    elif cols.size and (cols.min() < 0 or cols.max() >= num_cols):
        err = f"process {me}: column index out of range"
    errors = [e for e in allgather_objects(err) if e]
    if errors:
        raise ValueError("; ".join(errors))

    rows, cols, vals = exchange_by_owner([rows, cols, vals], rows % P)
    seg = _segment(rows, cols, vals, num_rows, num_cols, widths)

    data_size = int(mesh.shape[data_axis])
    if data_size % P:
        raise ValueError(
            f"data axis ({data_size}) must divide evenly across {P} processes"
        )
    dpl = data_size // P  # data-axis devices per process
    m = int(np.lcm(8, dpl))

    # --- agree on per-width shapes (tiny metadata gather) -----------------
    local_meta = {
        "widths": {w: int(seg.per_width[w][0].size) for w in seg.per_width},
        "num_hot": int(seg.hot_rows.size),
        "nnz": int(rows.size),
    }
    metas = allgather_objects(local_meta)
    all_widths = sorted({w for mt in metas for w in mt["widths"]})
    hot_counts = [mt["num_hot"] for mt in metas]
    hot_offset = int(np.sum(hot_counts[:me]))
    num_hot_tot = int(np.sum(hot_counts))
    nnz_global = int(np.sum([mt["nnz"] for mt in metas]))

    def plan(width: int, n_seg_max: int) -> tuple[int, int]:
        """(c_local, n_chunks): every host pads its block to the same
        n_chunks * c_local rows; global chunk rows C = P * c_local."""
        budget = max(1, chunk_entries // (width * P))
        c_local = max(m, budget // m * m)
        c_local = min(c_local, -(-max(n_seg_max, 1) // m) * m)
        return c_local, -(-max(n_seg_max, 1) // c_local)

    sharding3 = NamedSharding(mesh, PartitionSpec(None, data_axis, None))
    sharding2 = NamedSharding(mesh, PartitionSpec(None, data_axis))

    padded_global = 0

    def assemble(seg_row, seg_start, seg_len, width, sentinel, n_seg_max):
        """Pack this host's block and build the global sharded arrays."""
        nonlocal padded_global
        c_local, n_chunks = plan(width, n_seg_max)
        n_pad = n_chunks * c_local
        padded_global += n_pad * width * P
        row_id, idx, val, mask = _fill_bucket(
            int(seg_row.size), n_pad, width, seg_row, seg_start, seg_len,
            seg.cols_s, seg.vals_s, sentinel,
        )
        glob3 = (n_chunks, P * c_local, width)
        glob2 = (n_chunks, P * c_local)
        return _Chunked(
            jax.make_array_from_process_local_data(
                sharding2, row_id.reshape(n_chunks, c_local), glob2
            ),
            jax.make_array_from_process_local_data(
                sharding3, idx.reshape(n_chunks, c_local, width), glob3
            ),
            jax.make_array_from_process_local_data(
                sharding3, val.reshape(n_chunks, c_local, width), glob3
            ),
            jax.make_array_from_process_local_data(
                sharding3, mask.reshape(n_chunks, c_local, width), glob3
            ),
        )

    empty_i64 = np.zeros(0, np.int64)
    normal_chunks = []
    for w in all_widths:
        n_seg_max = max(mt["widths"].get(w, 0) for mt in metas)
        seg_row, seg_start, seg_len = seg.per_width.get(
            w, (np.zeros(0, np.int32), empty_i64, empty_i64)
        )
        normal_chunks.append(
            assemble(seg_row, seg_start, seg_len, w, num_rows, n_seg_max)
        )

    hot_chunks = []
    hot_rows_groups = []
    if num_hot_tot:
        # groups of <= hot_group_slots GLOBAL slots bound the sweep's
        # Gramian accumulator; every host packs a (possibly empty) block
        # for every group so global shapes agree
        H = hot_group_slots
        n_groups = -(-num_hot_tot // H)
        g_slot = (seg.hot_slot.astype(np.int64) + hot_offset).astype(np.int64)
        my_counts = [
            int(np.count_nonzero((g_slot >= g * H) & (g_slot < (g + 1) * H)))
            for g in range(n_groups)
        ]
        all_counts = allgather_objects(my_counts)
        gathered_hot = allgather_objects(seg.hot_rows.tolist())
        hot_rows_all = np.concatenate(
            [np.asarray(h, np.int32) for h in gathered_hot]
        )
        rep_sharding = NamedSharding(mesh, PartitionSpec(None))
        for g in range(n_groups):
            sel = (g_slot >= g * H) & (g_slot < (g + 1) * H)
            h_g = min(H, num_hot_tot - g * H)
            n_seg_max = max(c[g] for c in all_counts)
            hot_chunks.append(
                assemble(
                    (g_slot[sel] - g * H).astype(np.int32),
                    seg.hot_start[sel], seg.hot_len[sel],
                    seg.w_max, h_g, n_seg_max,
                )
            )
            hr = np.full(h_g + 1, num_rows, dtype=np.int32)
            hr[:h_g] = hot_rows_all[g * H : g * H + h_g]
            # a raw numpy leaf must not enter a multi-process jit —
            # materialize the (identical-everywhere) slot map replicated
            hot_rows_groups.append(
                jax.make_array_from_callback(
                    hr.shape, rep_sharding,
                    lambda idx, hr=hr: hr[idx],
                )
            )

    bucketed = BucketedRatings(
        tuple(normal_chunks),
        tuple(hot_chunks),
        tuple(hot_rows_groups),
        num_rows,
        num_cols,
        nnz_global,
        padded_global,
    )
    return bucketed, seg.rated


def _allgather_coo(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Multi-host: exchange per-host COO shards so every process holds the
    identical global rating set (jax requires globally-consistent values
    for sharded ``device_put``). This one-time DCN gather replaces Spark's
    shuffle-on-read; all per-iteration exchange stays in GSPMD collectives.
    Ragged per-host sizes are padded to the max and masked out after."""
    from jax.experimental import multihost_utils

    n_local = np.array([len(vals)], dtype=np.int64)
    n_all = np.asarray(multihost_utils.process_allgather(n_local)).ravel()
    n_max = int(n_all.max())

    def pad(a, dtype):
        out = np.zeros(n_max, dtype=dtype)
        out[: len(a)] = a
        return out

    stacked = np.stack([pad(rows, np.int64), pad(cols, np.int64)]).astype(np.int64)
    gathered_idx = np.asarray(multihost_utils.process_allgather(stacked))
    gathered_val = np.asarray(multihost_utils.process_allgather(pad(vals, np.float32)))
    # gathered_idx: [P, 2, n_max]; gathered_val: [P, n_max]
    out_r, out_c, out_v = [], [], []
    for p, n in enumerate(n_all):
        out_r.append(gathered_idx[p, 0, :n])
        out_c.append(gathered_idx[p, 1, :n])
        out_v.append(gathered_val[p, :n])
    return (
        np.concatenate(out_r),
        np.concatenate(out_c),
        np.concatenate(out_v).astype(np.float32),
    )


def train_als(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_users: int,
    num_items: int,
    config: ALSConfig = ALSConfig(),
    mesh: Mesh | None = None,
    data_axis: str = "data",
    model_axis: str = "model",
    init_user: np.ndarray | None = None,
    init_item: np.ndarray | None = None,
) -> ALSFactors:
    """Train factor matrices from COO ratings.

    In a multi-process job, ``rows/cols/vals`` are this host's shard of
    the ratings (the sharded event-reader layout). With a mesh, shards are
    re-partitioned by row through a bounded-memory exchange — per-host
    memory stays O(nnz / num_hosts) (see :func:`_multihost_bucketed`);
    without a mesh they are all-gathered (legacy replicated fallback).

    ``init_user``/``init_item`` (``[num_users, K]`` / ``[num_items, K]``)
    seed the factors instead of the random draw — the warm-retrain path
    (``pio train --warm-start``). Unrated rows are still zeroed, and a
    checkpoint resume (``config.checkpoint_dir``) takes precedence.

    Returns host-strippable ``ALSFactors`` with the sentinel rows removed:
    ``user [num_users, K]``, ``item [num_items, K]``.
    """
    if config.precision not in _PRECISIONS:
        raise ValueError(
            f"ALSConfig.precision must be one of {sorted(_PRECISIONS)}, "
            f"got {config.precision!r}"
        )
    if config.solver not in ("auto", "cholesky", "pallas", "pallas_interpret"):
        raise ValueError(
            "ALSConfig.solver must be 'auto', 'cholesky', 'pallas' or "
            f"'pallas_interpret', got {config.solver!r}"
        )
    if config.bucketing not in ("auto", "host", "device"):
        raise ValueError(
            "ALSConfig.bucketing must be 'auto', 'host' or 'device', "
            f"got {config.bucketing!r}"
        )
    solver = config.solver
    if solver == "auto":
        # the Mosaic kernel is single-device; sharded sweeps keep the
        # portable Cholesky until the kernel is shard_map-wrapped
        on_tpu = jax.default_backend() == "tpu"
        solver = "pallas" if (on_tpu and mesh is None) else "cholesky"
    elif solver.startswith("pallas") and mesh is not None:
        # an explicit kernel request on a sharded sweep would compile the
        # single-device pallas_call under GSPMD — downgrade instead of
        # failing (covers "pallas" and "pallas_interpret" alike)
        logging.getLogger(__name__).warning(
            "solver=%r is single-device; using 'cholesky' on the mesh", solver
        )
        solver = "cholesky"
    if mesh is not None and model_axis not in mesh.shape:
        # a data-only mesh (e.g. `pio train --mesh data=8`): fall back to
        # replicated factor tables
        model_axis = None
    multihost = jax.process_count() > 1
    if multihost and mesh is not None:
        # bounded-memory path: per-host shards stay sharded; only rows are
        # re-partitioned (VERDICT round-1 missing #3)
        from predictionio_tpu.parallel.exchange import allgather_objects

        user_bucketed, u_rated = _multihost_bucketed(
            rows, cols, vals, num_users, num_items, mesh, data_axis,
            config.bucket_widths, config.chunk_entries, config.hot_group_slots,
        )
        item_bucketed, i_rated = _multihost_bucketed(
            cols, rows, vals, num_items, num_users, mesh, data_axis,
            config.bucket_widths, config.chunk_entries, config.hot_group_slots,
        )
        # the global rated mask is the OR of the per-host masks
        u_rated = np.bitwise_or.reduce(allgather_objects(np.packbits(u_rated)))
        i_rated = np.bitwise_or.reduce(allgather_objects(np.packbits(i_rated)))
        u_rated = np.unpackbits(u_rated, count=num_users).astype(bool)
        i_rated = np.unpackbits(i_rated, count=num_items).astype(bool)
    else:
        if multihost:
            # mesh-less multi-process training: legacy replicated path
            rows, cols, vals = _allgather_coo(
                np.asarray(rows), np.asarray(cols), np.asarray(vals)
            )
        row_multiple = 8
        if mesh is not None:
            # chunk rows must divide evenly over the data axis
            row_multiple = int(np.lcm(8, mesh.shape.get(data_axis, 1)))
        use_device_bucketing = mesh is None and not multihost and (
            config.bucketing == "device"
            or (
                config.bucketing == "auto"
                and jax.default_backend() not in ("cpu",)
            )
        )
        if use_device_bucketing:
            # transfer the COO ONCE and hand device arrays to both sides
            # (each side would otherwise re-upload the same ~12 bytes/nnz);
            # validate on host BEFORE the int32 cast so out-of-range int64
            # values cannot truncate into range
            r_h, c_h = np.asarray(rows), np.asarray(cols)
            v_h = np.asarray(vals, dtype=np.float32)
            if r_h.size and (r_h.min() < 0 or r_h.max() >= num_users):
                raise ValueError("row index out of range")
            if c_h.size and (c_h.min() < 0 or c_h.max() >= num_items):
                raise ValueError("column index out of range")
            small = max(num_users, num_items) < 2**31 and r_h.size < 2**31
            if small and r_h.size:
                rows_x = jnp.asarray(r_h.astype(np.int32))
                cols_x = jnp.asarray(c_h.astype(np.int32))
                vals_x = jnp.asarray(v_h)
            else:
                rows_x, cols_x, vals_x = r_h, c_h, v_h
            user_bucketed, u_rated = build_buckets_device(
                rows_x, cols_x, vals_x, num_users, num_items,
                widths=config.bucket_widths, row_multiple=row_multiple,
                chunk_entries=config.chunk_entries,
                hot_group_slots=config.hot_group_slots,
            )
            item_bucketed, i_rated = build_buckets_device(
                cols_x, rows_x, vals_x, num_items, num_users,
                widths=config.bucket_widths, row_multiple=row_multiple,
                chunk_entries=config.chunk_entries,
                hot_group_slots=config.hot_group_slots,
            )
        else:
            user_b = build_buckets(
                rows, cols, vals, num_users, num_items,
                widths=config.bucket_widths, row_multiple=row_multiple,
                chunk_entries=config.chunk_entries,
                hot_group_slots=config.hot_group_slots,
            )
            item_b = build_buckets(
                cols, rows, vals, num_items, num_users,
                widths=config.bucket_widths, row_multiple=row_multiple,
                chunk_entries=config.chunk_entries,
                hot_group_slots=config.hot_group_slots,
            )
            u_rated = rated_row_mask(user_b)
            i_rated = rated_row_mask(item_b)
            user_bucketed = _device_buckets(user_b, mesh, data_axis)
            item_bucketed = _device_buckets(item_b, mesh, data_axis)

    rank = config.rank
    if config.rank_pad_multiple:
        rank = -(-rank // config.rank_pad_multiple) * config.rank_pad_multiple

    key_u, key_i = jax.random.split(jax.random.PRNGKey(config.seed))
    # Table length: num_rows + 1 sentinel row, padded up so the row axis
    # divides the model-axis size (extra rows stay zero, never written).
    model_size = int(mesh.shape.get(model_axis, 1)) if mesh is not None else 1
    n_u = -(-(num_users + 1) // model_size) * model_size
    n_i = -(-(num_items + 1) // model_size) * model_size
    # MLlib seeds factors with nonnegative abs(normal) rows. On the
    # implicit objective the rows are additionally normalized to unit L2
    # (MLlib's exact init): with confidence weighting, an unlucky
    # small-norm draw parks a row in a slow convergence basin for many
    # sweeps — measurably, the similar-product fixture needs 5x the
    # sweeps to separate its item groups from one such draw. The
    # explicit objective keeps the historical /sqrt(rank) scale (same
    # expected norm) so explicitly-trained models are bit-identical
    # across this change. Unrated rows are zeroed so cold entities never
    # outscore trained ones (round-1 advisor fix).
    u_mask = np.append(u_rated, False)[:, None]
    i_mask = np.append(i_rated, False)[:, None]
    # draw at the canonical (num_rows+1) shape so the init — and therefore
    # the trained factors — are identical across mesh shapes, then zero-pad
    def _seed_table(key, init, num_rows):
        if init is None:
            draw = jnp.abs(
                jax.random.normal(key, (num_rows + 1, rank), jnp.float32)
            )
            if config.implicit:
                norms = jnp.linalg.norm(draw, axis=1, keepdims=True)
                return draw / jnp.maximum(norms, 1e-9)
            # multiply by the precomputed reciprocal (not a divide): the
            # historical op, so explicit inits are bit-identical to it
            return draw * (1.0 / np.sqrt(rank))
        init = np.asarray(init, dtype=np.float32)
        if init.shape[0] != num_rows:
            raise ValueError(
                f"warm init has {init.shape[0]} rows, expected {num_rows}"
            )
        table = np.zeros((num_rows + 1, rank), np.float32)
        k = min(rank, init.shape[1])
        table[:num_rows, :k] = init[:, :k]
        return jnp.asarray(table)

    uf = _seed_table(key_u, init_user, num_users)
    vf = _seed_table(key_i, init_item, num_items)
    uf = jnp.pad(uf * jnp.asarray(u_mask), ((0, n_u - num_users - 1), (0, 0)))
    vf = jnp.pad(vf * jnp.asarray(i_mask), ((0, n_i - num_items - 1), (0, 0)))
    if mesh is not None:
        # persistent tables sharded over the model axis (ALX): catalog
        # memory scales with the mesh instead of being replicated
        model_sharded = NamedSharding(mesh, PartitionSpec(model_axis, None))
        if multihost:
            # every host holds the identical full table; carve out the
            # addressable shards (device_put cannot target a global mesh)
            uf_h, vf_h = np.asarray(uf), np.asarray(vf)
            uf = jax.make_array_from_callback(
                uf_h.shape, model_sharded, lambda idx: uf_h[idx]
            )
            vf = jax.make_array_from_callback(
                vf_h.shape, model_sharded, lambda idx: vf_h[idx]
            )
        else:
            uf = jax.device_put(uf, model_sharded)
            vf = jax.device_put(vf, model_sharded)

    rep = None if mesh is None else NamedSharding(mesh, PartitionSpec())
    if mesh is not None:

        def _strip(a, b):
            # replicate BEFORE slicing: the canonical length need not
            # divide the model axis, so a sharded-dim slice is illegal
            # (reshard, not with_sharding_constraint — the latter doesn't
            # change the sharded *type* under explicit-sharding meshes)
            a = reshard(a, rep)
            b = reshard(b, rep)
            return a[: num_users + 1], b[: num_items + 1]

        # jitted ONCE per train: the jit cache is keyed on the function
        # object, so a per-save closure would retrace every checkpoint
        _strip_jit = jax.jit(_strip, out_shardings=rep)

    def _to_canonical(u: jax.Array, v: jax.Array) -> dict:
        """Checkpoint state at the canonical (num_rows+1, K) replicated
        shape: the on-disk layout must not depend on the mesh's model-axis
        size, or a resume on a different mesh fails the shape match
        (round-2 advisor finding). Always returns FRESH buffers (copies on
        the mesh-less path) so the async orbax save can overlap the next
        sweep, whose donation would otherwise race the live tables."""
        if mesh is None:
            return {"user": jnp.copy(u), "item": jnp.copy(v)}
        cu, ci = _strip_jit(u, v)
        return {"user": cu, "item": ci}

    def _canonical_like() -> dict:
        """Abstract restore template — no device work, just shapes."""
        return {
            "user": jax.ShapeDtypeStruct(
                (num_users + 1, rank), jnp.float32, sharding=rep
            ),
            "item": jax.ShapeDtypeStruct(
                (num_items + 1, rank), jnp.float32, sharding=rep
            ),
        }

    def _from_canonical(state: dict) -> tuple[jax.Array, jax.Array]:
        """Re-pad restored canonical factors to this mesh's table shape
        and reshard them over the model axis."""
        u, v = state["user"], state["item"]
        if mesh is None:
            return u, v
        return jax.jit(
            lambda a, b: (
                jnp.pad(a, ((0, n_u - (num_users + 1)), (0, 0))),
                jnp.pad(b, ((0, n_i - (num_items + 1)), (0, 0))),
            ),
            out_shardings=NamedSharding(mesh, PartitionSpec(model_axis, None)),
        )(u, v)

    manager = None
    start_step = 0
    if config.checkpoint_dir:
        from predictionio_tpu.utils.checkpoint import CheckpointManager

        manager = CheckpointManager(config.checkpoint_dir)
        latest = manager.latest_step()
        if latest is not None:
            try:
                state = manager.restore(latest, like=_canonical_like())
            except (ValueError, TypeError, KeyError) as exc:
                # shape/structure drift only (e.g. a pre-canonical padded
                # checkpoint, or a different rank); transient I/O errors
                # propagate rather than silently restarting from step 0
                logging.getLogger(__name__).warning(
                    "Checkpoint step %d is incompatible with this run "
                    "(%s); starting fresh", latest, exc,
                )
            else:
                uf, vf = _from_canonical(state)
                # a completed run restores and short-circuits the sweep loop
                start_step = min(latest, config.iterations)
                logging.getLogger(__name__).info(
                    "Resumed ALS from checkpoint step %d", latest
                )

    for step in range(start_step, config.iterations):
        uf, vf = als_sweep(
            uf, vf, user_bucketed, item_bucketed,
            reg=config.reg, implicit=config.implicit, alpha=config.alpha,
            precision=config.precision,
            solver=solver,
            mesh=mesh,
            data_axis=data_axis if mesh is not None else None,
            model_axis=model_axis if mesh is not None else None,
        )
        if manager is not None and (
            (step + 1) % config.checkpoint_interval == 0
            or step + 1 == config.iterations
        ):
            # _to_canonical hands the save fresh buffers, so the async
            # write overlaps the next sweep instead of serializing it
            manager.save(step + 1, _to_canonical(uf, vf))
    if manager is not None:
        manager.wait()
        manager.close()
    if mesh is not None:
        if jax.process_count() > 1:
            # multi-host: replicate before stripping the sentinel row —
            # np.asarray cannot assemble a non-fully-addressable array,
            # and a jitted identity reshards on any topology (device_put
            # cannot retarget a multi-process mesh)
            replicated = NamedSharding(mesh, PartitionSpec())
            uf, vf = jax.jit(
                lambda a, b: (a, b), out_shardings=replicated
            )(uf, vf)
        else:
            # single-host mesh: assemble the tables on HOST straight
            # from the per-device shards. The previous jitted replicate
            # materialized the FULL table on every device right at the
            # finish line — the one step whose peak per-device memory
            # was O(catalog) instead of O(catalog / model_axis), which
            # re-created the BENCH_r01 OOM the sharded sweep avoids.
            return ALSFactors(
                user=np.asarray(uf)[:num_users],
                item=np.asarray(vf)[:num_items],
            )
    return ALSFactors(user=uf[:num_users], item=vf[:num_items])


# ---------------------------------------------------------------------------
# Inference kernels
# ---------------------------------------------------------------------------


@jax.jit
def predict_scores(user_vec: jax.Array, item_factors: jax.Array) -> jax.Array:
    """Scores of one user against all items: ``item_factors @ user_vec``."""
    return item_factors @ user_vec


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_items(
    user_vec: jax.Array,
    item_factors: jax.Array,
    k: int,
    exclude_mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k item ids + scores for one user. ``exclude_mask`` (bool [I])
    drops items (e.g. already-rated) by sending them to -inf — the
    serving-time filter of the reference's recommendation templates."""
    scores = item_factors @ user_vec
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return top_k_scores(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_items_batch(
    user_idx: jax.Array,
    user_factors: jax.Array,
    item_factors: jax.Array,
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """Top-k for a BATCH of users in one dispatch: gather the user rows on
    device, score every item with one ``[B, K] @ [K, I]`` GEMM (MXU work,
    not B GEMVs), and ``lax.top_k`` each row. Returns ``([B, k] item ids,
    [B, k] scores)`` — the only host transfer is the 2·B·k result.

    This is the batch-amortized device serving path (ref
    ``core/workflow/BatchPredict.scala`` ``batchPredictBase``): per-query
    dispatch pays a full device round trip per prediction, which a
    tunneled/remote accelerator turns into ~hundreds of ms; one dispatch
    per chunk amortizes that latency over the whole chunk."""
    user_vecs = user_factors[user_idx]
    scores = user_vecs @ item_factors.T
    return top_k_scores(scores, k)
    # NB: donating the user_idx staging buffer was considered for the
    # pinned serving path and rejected: XLA input-output aliasing needs
    # byte-compatible shapes, and the (chunk,) int32 index buffer can
    # never alias the (chunk, k>=16) outputs — the donation would only
    # produce "donated buffers were not usable" warnings.
