"""Alternating Least Squares, TPU-native.

Replaces Spark MLlib's ``org.apache.spark.ml.recommendation.ALS``, the
training kernel behind the reference's Recommendation / Similar-Product /
E-Commerce templates (reached from ``PAlgorithm.train`` — see SURVEY.md
sections 3.9, 8.1). Nothing here is a port: MLlib's block-partitioned
shuffle becomes sharded dense compute + XLA collectives, following the
ALX recipe (PAPERS.md — "ALX: Large Scale Matrix Factorization on TPUs"):

* **Bucketed padding** — each row's ragged rating list is padded into one
  of a few fixed widths, so every step is a static-shape batched einsum
  the MXU can tile (no data-dependent shapes under jit).
* **Batched normal equations** — per row ``A x = b`` with
  ``A = Qᵀ W Q + λI`` built by ``[B,L,K]×[B,L,K] -> [B,K,K]`` einsums
  (MXU work) and solved by batched Cholesky.
* **Mesh sharding** — bucket rows are sharded over the ``data`` axis of
  the mesh; the opposite-side factor matrix is replicated (it is O(N·K),
  small next to the ratings), so the only collective is the all-gather
  GSPMD inserts when scattering solved rows back — riding ICI, replacing
  MLlib's netty shuffle.

Supports MLlib's two objectives:

* **explicit** — squared error on observed ratings with ALS-WR
  regularization (λ scaled by each row's rating count, MLlib default).
* **implicit** (Hu-Koren-Volinsky) — confidence ``c = 1 + α·|r|``,
  preference ``p = [r > 0]``, with the shared ``YᵀY`` Gramian computed
  once per half-sweep.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "ALSConfig",
    "ALSFactors",
    "BucketedRatings",
    "build_buckets",
    "train_als",
    "als_sweep",
    "predict_scores",
    "top_k_items",
]

_DEFAULT_BUCKET_WIDTHS = (8, 32, 128, 512, 2048, 8192, 32768)


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    """Hyperparameters (parity: MLlib ``ALS`` params ``rank``, ``maxIter``,
    ``regParam``, ``implicitPrefs``, ``alpha``, ``seed``)."""

    rank: int = 10
    iterations: int = 10
    reg: float = 0.1
    implicit: bool = False
    alpha: float = 1.0
    seed: int = 0
    #: pad rank up to a multiple of this for MXU-friendly K (0 = exact rank)
    rank_pad_multiple: int = 0
    #: orbax step-checkpoint directory ("" = off); training resumes from
    #: the latest step found there (resume-on-preemption, SURVEY.md 6.4)
    checkpoint_dir: str = ""
    checkpoint_interval: int = 5


class ALSFactors(NamedTuple):
    """The model: dense factor matrices. Row ``num_rows`` of each is a
    zero sentinel used as the scatter target for padding (stripped by
    :func:`train_als` before returning)."""

    user: jax.Array  # [num_users(+1), K]
    item: jax.Array  # [num_items(+1), K]


class _Bucket(NamedTuple):
    row_id: Any  # [B] int32 — sentinel = num_rows for padding rows
    idx: Any  # [B, L] int32 — column indices into the other side's factors
    val: Any  # [B, L] f32 — ratings (0 where masked)
    mask: Any  # [B, L] f32 — 1 for real entries


class BucketedRatings(NamedTuple):
    """One side of the ratings matrix in solver layout: a handful of
    fixed-width padded buckets (static shapes for XLA)."""

    buckets: tuple  # tuple[_Bucket, ...]
    num_rows: int
    num_cols: int


def build_buckets(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    num_cols: int,
    widths: Sequence[int] = _DEFAULT_BUCKET_WIDTHS,
    row_multiple: int = 8,
) -> BucketedRatings:
    """Host-side: COO ratings -> per-row padded buckets.

    Rows are grouped by rating count into the smallest width that fits;
    each bucket's row count is padded to ``row_multiple`` (keep it a
    multiple of the mesh's data-axis size so shards divide evenly).
    Rows with zero ratings are omitted — their factors stay zero.
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    if not (rows.shape == cols.shape == vals.shape):
        raise ValueError("rows/cols/vals must be 1-D arrays of equal length")
    if rows.size and (rows.min() < 0 or rows.max() >= num_rows):
        raise ValueError("row index out of range")
    if cols.size and (cols.min() < 0 or cols.max() >= num_cols):
        raise ValueError("column index out of range")

    order = np.argsort(rows, kind="stable")
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    uniq, starts, counts = np.unique(rows_s, return_index=True, return_counts=True)

    max_count = int(counts.max()) if counts.size else 0
    usable = [w for w in sorted(widths) if w >= 1]
    if not usable or max_count > usable[-1]:
        usable.append(max(max_count, 1))

    # assign each unique row to the smallest width that fits
    width_of = np.empty(len(uniq), dtype=np.int64)
    for w in sorted(usable, reverse=True):
        width_of[counts <= w] = w

    buckets = []
    for w in sorted(set(usable)):
        sel = np.nonzero(width_of == w)[0]
        if sel.size == 0:
            continue
        n = int(sel.size)
        n_pad = -(-n // row_multiple) * row_multiple
        row_id = np.full(n_pad, num_rows, dtype=np.int32)
        idx = np.zeros((n_pad, w), dtype=np.int32)
        val = np.zeros((n_pad, w), dtype=np.float32)
        mask = np.zeros((n_pad, w), dtype=np.float32)
        row_id[:n] = uniq[sel]
        # vectorized ragged fill: flat destination (row, lane) pairs for
        # every rating of the bucket's rows — no per-row Python loop
        # (this runs at full-catalog scale before the first TPU step)
        c_sel = counts[sel]
        dst_row = np.repeat(np.arange(n), c_sel)
        lane_end = np.cumsum(c_sel)
        dst_lane = np.arange(int(lane_end[-1]) if n else 0) - np.repeat(
            lane_end - c_sel, c_sel
        )
        src = np.repeat(starts[sel], c_sel) + dst_lane
        idx[dst_row, dst_lane] = cols_s[src]
        val[dst_row, dst_lane] = vals_s[src]
        mask[dst_row, dst_lane] = 1.0
        buckets.append(_Bucket(row_id, idx, val, mask))
    return BucketedRatings(tuple(buckets), num_rows, num_cols)


# ---------------------------------------------------------------------------
# Solver kernels (pure, jit-compiled)
# ---------------------------------------------------------------------------


def _solve_bucket(
    other_factors: jax.Array,  # [num_cols+1, K] — includes zero sentinel row
    bucket: _Bucket,
    reg: float,
    implicit: bool,
    alpha: float,
    yty: jax.Array | None,  # [K, K], implicit only
    mesh: Mesh | None,
    data_axis: str | None,  # mesh axis bucket rows are sharded over
) -> jax.Array:
    """New factors for one bucket's rows: batched normal equations.

    All heavy ops are [B,L,K]-shaped einsums -> MXU; solve is batched
    Cholesky on [B,K,K].
    """
    K = other_factors.shape[-1]
    if mesh is not None:
        # replicated table, row-sharded indices -> row-sharded gather; the
        # out_sharding makes the GSPMD decision explicit (each device
        # gathers only its rows' factors — the ALX sharded-gather step).
        gathered = other_factors.at[bucket.idx].get(
            out_sharding=NamedSharding(mesh, PartitionSpec(data_axis, None, None))
        )
    else:
        gathered = other_factors[bucket.idx]
    Q = gathered * bucket.mask[..., None]  # [B, L, K]
    eye = jnp.eye(K, dtype=other_factors.dtype)
    # Normal equations are solve-accuracy-sensitive: force full-f32 MXU
    # passes rather than the TPU's default bf16 matmul precision.
    hi = jax.lax.Precision.HIGHEST
    if implicit:
        conf_minus_1 = alpha * jnp.abs(bucket.val) * bucket.mask  # c - 1
        pref = (bucket.val > 0).astype(Q.dtype) * bucket.mask
        A = (
            yty
            + jnp.einsum("blk,bl,blj->bkj", Q, conf_minus_1, Q, precision=hi)
            + reg * eye
        )
        b = jnp.einsum("blk,bl->bk", Q, (1.0 + conf_minus_1) * pref, precision=hi)
    else:
        n_ratings = bucket.mask.sum(axis=-1)  # [B]
        A = jnp.einsum("blk,blj->bkj", Q, Q, precision=hi) + (
            reg * jnp.maximum(n_ratings, 1.0)[:, None, None] * eye
        )
        b = jnp.einsum("blk,bl->bk", Q, bucket.val * bucket.mask, precision=hi)
    # SPD by construction -> Cholesky
    L = jax.lax.linalg.cholesky(A)
    x = jax.lax.linalg.triangular_solve(
        L, b[..., None], left_side=True, lower=True
    )
    x = jax.lax.linalg.triangular_solve(
        L, x, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]  # [B, K]


def _half_sweep(
    factors: jax.Array,  # [num_rows+1, K] — side being updated
    other_factors: jax.Array,  # [num_cols+1, K]
    buckets: tuple,
    reg: float,
    implicit: bool,
    alpha: float,
    mesh: Mesh | None,
    data_axis: str | None,
) -> jax.Array:
    yty = None
    if implicit:
        # Gramian over the *other* side; sentinel row is zero so it is a
        # no-op term. On a mesh this is a sharded matmul + psum over ICI.
        yty = jnp.matmul(
            other_factors.T, other_factors, precision=jax.lax.Precision.HIGHEST
        )
    for bucket in buckets:
        new_rows = _solve_bucket(
            other_factors, bucket, reg, implicit, alpha, yty, mesh, data_axis
        )
        if mesh is not None:
            # scatter sharded rows into the replicated factor table — GSPMD
            # lowers this to the per-shard update + all-gather over ICI
            # that replaces MLlib's factor-block shuffle.
            factors = factors.at[bucket.row_id].set(
                new_rows, out_sharding=NamedSharding(mesh, PartitionSpec(None, None))
            )
        else:
            factors = factors.at[bucket.row_id].set(new_rows)
    # padding rows scattered into the sentinel; re-zero it
    return factors.at[factors.shape[0] - 1].set(0.0)


@functools.partial(
    jax.jit,
    static_argnames=("reg", "implicit", "alpha", "mesh", "data_axis"),
    donate_argnums=(0, 1),
)
def als_sweep(
    user_factors: jax.Array,
    item_factors: jax.Array,
    user_buckets: tuple,
    item_buckets: tuple,
    reg: float,
    implicit: bool,
    alpha: float,
    mesh: Mesh | None = None,
    data_axis: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """One full ALS iteration: solve users given items, then items given
    users. Compiled once; buffers donated so factors update in place."""
    user_factors = _half_sweep(
        user_factors, item_factors, user_buckets, reg, implicit, alpha, mesh, data_axis
    )
    item_factors = _half_sweep(
        item_factors, user_factors, item_buckets, reg, implicit, alpha, mesh, data_axis
    )
    return user_factors, item_factors


def _device_buckets(b: BucketedRatings, mesh: Mesh | None, data_axis: str) -> tuple:
    """Place bucket arrays on device — rows sharded over the mesh's data
    axis when a mesh is given (replaces Spark's RDD partitioning)."""
    out = []
    for bucket in b.buckets:
        if mesh is not None:
            row_sharded_1d = NamedSharding(mesh, PartitionSpec(data_axis))
            row_sharded_2d = NamedSharding(mesh, PartitionSpec(data_axis, None))
            out.append(
                _Bucket(
                    jax.device_put(bucket.row_id, row_sharded_1d),
                    jax.device_put(bucket.idx, row_sharded_2d),
                    jax.device_put(bucket.val, row_sharded_2d),
                    jax.device_put(bucket.mask, row_sharded_2d),
                )
            )
        else:
            out.append(
                _Bucket(
                    jnp.asarray(bucket.row_id),
                    jnp.asarray(bucket.idx),
                    jnp.asarray(bucket.val),
                    jnp.asarray(bucket.mask),
                )
            )
    return tuple(out)


def _allgather_coo(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Multi-host: exchange per-host COO shards so every process holds the
    identical global rating set (jax requires globally-consistent values
    for sharded ``device_put``). This one-time DCN gather replaces Spark's
    shuffle-on-read; all per-iteration exchange stays in GSPMD collectives.
    Ragged per-host sizes are padded to the max and masked out after."""
    from jax.experimental import multihost_utils

    n_local = np.array([len(vals)], dtype=np.int64)
    n_all = np.asarray(multihost_utils.process_allgather(n_local)).ravel()
    n_max = int(n_all.max())

    def pad(a, dtype):
        out = np.zeros(n_max, dtype=dtype)
        out[: len(a)] = a
        return out

    stacked = np.stack(
        [pad(rows, np.int64), pad(cols, np.int64)]
    ).astype(np.int64)
    gathered_idx = np.asarray(multihost_utils.process_allgather(stacked))
    gathered_val = np.asarray(
        multihost_utils.process_allgather(pad(vals, np.float32))
    )
    # gathered_idx: [P, 2, n_max]; gathered_val: [P, n_max]
    out_r, out_c, out_v = [], [], []
    for p, n in enumerate(n_all):
        out_r.append(gathered_idx[p, 0, :n])
        out_c.append(gathered_idx[p, 1, :n])
        out_v.append(gathered_val[p, :n])
    return (
        np.concatenate(out_r),
        np.concatenate(out_c),
        np.concatenate(out_v).astype(np.float32),
    )


def train_als(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_users: int,
    num_items: int,
    config: ALSConfig = ALSConfig(),
    mesh: Mesh | None = None,
    data_axis: str = "data",
) -> ALSFactors:
    """Train factor matrices from COO ratings.

    In a multi-process job, ``rows/cols/vals`` may be this host's shard of
    the ratings (the sharded event-reader layout); they are all-gathered
    once so bucket construction is globally consistent.

    Returns host-strippable ``ALSFactors`` with the sentinel rows removed:
    ``user [num_users, K]``, ``item [num_items, K]``.
    """
    if jax.process_count() > 1:
        rows, cols, vals = _allgather_coo(
            np.asarray(rows), np.asarray(cols), np.asarray(vals)
        )
    rank = config.rank
    if config.rank_pad_multiple:
        rank = -(-rank // config.rank_pad_multiple) * config.rank_pad_multiple

    row_multiple = 8
    if mesh is not None:
        # must be a multiple of the data-axis size so shards divide evenly
        row_multiple = int(np.lcm(8, mesh.shape.get(data_axis, 1)))
    user_b = build_buckets(rows, cols, vals, num_users, num_items, row_multiple=row_multiple)
    item_b = build_buckets(cols, rows, vals, num_items, num_users, row_multiple=row_multiple)

    key_u, key_i = jax.random.split(jax.random.PRNGKey(config.seed))
    scale = 1.0 / np.sqrt(rank)
    # MLlib seeds factors with abs(normal)/sqrt(rank) — keeps implicit ALS
    # preferences non-negative at iteration 0.
    uf = jnp.abs(jax.random.normal(key_u, (num_users + 1, rank), jnp.float32)) * scale
    vf = jnp.abs(jax.random.normal(key_i, (num_items + 1, rank), jnp.float32)) * scale
    uf = uf.at[num_users].set(0.0)
    vf = vf.at[num_items].set(0.0)
    if mesh is not None:
        replicated = NamedSharding(mesh, PartitionSpec())
        uf = jax.device_put(uf, replicated)
        vf = jax.device_put(vf, replicated)

    user_buckets = _device_buckets(user_b, mesh, data_axis)
    item_buckets = _device_buckets(item_b, mesh, data_axis)

    manager = None
    start_step = 0
    if config.checkpoint_dir:
        from predictionio_tpu.utils.checkpoint import CheckpointManager

        manager = CheckpointManager(config.checkpoint_dir)
        latest = manager.latest_step()
        if latest is not None and latest < config.iterations:
            state = manager.restore(latest, like={"user": uf, "item": vf})
            uf, vf = state["user"], state["item"]
            start_step = latest
            import logging

            logging.getLogger(__name__).info(
                "Resumed ALS from checkpoint step %d", latest
            )

    for step in range(start_step, config.iterations):
        uf, vf = als_sweep(
            uf, vf, user_buckets, item_buckets,
            reg=config.reg, implicit=config.implicit, alpha=config.alpha,
            mesh=mesh, data_axis=data_axis if mesh is not None else None,
        )
        if manager is not None and (
            (step + 1) % config.checkpoint_interval == 0
            or step + 1 == config.iterations
        ):
            manager.save(step + 1, {"user": uf, "item": vf})
            # block: the next sweep donates these buffers, so an async
            # save must not still be reading them
            manager.wait()
    if manager is not None:
        manager.wait()
        manager.close()
    return ALSFactors(user=uf[:num_users], item=vf[:num_items])


# ---------------------------------------------------------------------------
# Inference kernels
# ---------------------------------------------------------------------------


@jax.jit
def predict_scores(user_vec: jax.Array, item_factors: jax.Array) -> jax.Array:
    """Scores of one user against all items: ``item_factors @ user_vec``."""
    return item_factors @ user_vec


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_items(
    user_vec: jax.Array,
    item_factors: jax.Array,
    k: int,
    exclude_mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k item ids + scores for one user. ``exclude_mask`` (bool [I])
    drops items (e.g. already-rated) by sending them to -inf — the
    serving-time filter of the reference's recommendation templates."""
    scores = item_factors @ user_vec
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, -jnp.inf, scores)
    values, indices = jax.lax.top_k(scores, k)
    return indices, values
