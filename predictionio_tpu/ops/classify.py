"""Classification kernels: multinomial Naive Bayes + logistic regression.

Replaces Spark MLlib's ``mllib.classification.NaiveBayes`` and
``LogisticRegressionWithLBFGS`` used by the reference's Classification and
Text-Classification templates (external template repos; SURVEY.md
sections 3.9, 8.1). Both are single-jit programs: NB is two segment-sum
reductions; LR is full-batch gradient descent under ``lax.scan`` (no
Python-loop dispatch, one compile).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "NaiveBayesModel",
    "train_naive_bayes",
    "nb_predict_log_proba",
    "LogRegModel",
    "train_logreg",
    "logreg_predict_proba",
]


class NaiveBayesModel(NamedTuple):
    """log-prior [C] + log-likelihood [C, F] (parity: MLlib NaiveBayesModel
    ``pi``/``theta``)."""

    log_prior: jax.Array
    log_theta: jax.Array


@functools.partial(jax.jit, static_argnames=("num_classes",))
def _nb_fit(x: jax.Array, y: jax.Array, num_classes: int, smoothing: float):
    one_hot = jax.nn.one_hot(y, num_classes, dtype=x.dtype)  # [N, C]
    class_counts = one_hot.sum(axis=0)  # [C]
    # feature mass per class: [C, F] — one MXU GEMM
    feat = one_hot.T @ x
    log_prior = jnp.log(class_counts + smoothing) - jnp.log(
        class_counts.sum() + num_classes * smoothing
    )
    log_theta = jnp.log(feat + smoothing) - jnp.log(
        feat.sum(axis=1, keepdims=True) + smoothing * x.shape[1]
    )
    return NaiveBayesModel(log_prior, log_theta)


def train_naive_bayes(
    x: np.ndarray, y: np.ndarray, num_classes: int, smoothing: float = 1.0
) -> NaiveBayesModel:
    """Multinomial NB (parity: MLlib ``NaiveBayes.train`` with lambda).
    ``x`` must be non-negative feature counts/weights."""
    x = jnp.asarray(x, jnp.float32)
    if (x < 0).any():
        raise ValueError("multinomial Naive Bayes requires non-negative features")
    return _nb_fit(x, jnp.asarray(y, jnp.int32), num_classes, float(smoothing))


@jax.jit
def nb_predict_log_proba(model: NaiveBayesModel, x: jax.Array) -> jax.Array:
    """[B, F] -> [B, C] unnormalized log-posteriors."""
    return model.log_prior + x @ model.log_theta.T


class LogRegModel(NamedTuple):
    """weights [F, C] + bias [C]."""

    w: jax.Array
    b: jax.Array


@functools.partial(
    jax.jit, static_argnames=("num_classes", "iterations")
)
def _lr_fit(
    x: jax.Array,
    y: jax.Array,
    num_classes: int,
    iterations: int,
    lr: float,
    reg: float,
):
    n, f = x.shape
    one_hot = jax.nn.one_hot(y, num_classes, dtype=x.dtype)

    def step(carry, _):
        w, b = carry
        logits = x @ w + b
        p = jax.nn.softmax(logits, axis=-1)
        g = (p - one_hot) / n  # [N, C]
        gw = x.T @ g + reg * w
        gb = g.sum(axis=0)
        return (w - lr * gw, b - lr * gb), None

    w0 = jnp.zeros((f, num_classes), x.dtype)
    b0 = jnp.zeros((num_classes,), x.dtype)
    (w, b), _ = jax.lax.scan(step, (w0, b0), None, length=iterations)
    return LogRegModel(w, b)


def train_logreg(
    x: np.ndarray,
    y: np.ndarray,
    num_classes: int,
    iterations: int = 200,
    lr: float = 1.0,
    reg: float = 1e-4,
) -> LogRegModel:
    """Softmax regression by full-batch GD under ``lax.scan``
    (parity surface: MLlib ``LogisticRegressionWithLBFGS``; the optimizer
    differs, the model/served probabilities match)."""
    return _lr_fit(
        jnp.asarray(x, jnp.float32),
        jnp.asarray(y, jnp.int32),
        num_classes,
        int(iterations),
        float(lr),
        float(reg),
    )


@jax.jit
def logreg_predict_proba(model: LogRegModel, x: jax.Array) -> jax.Array:
    return jax.nn.softmax(x @ model.w + model.b, axis=-1)
