"""Version-compatibility shims for jax APIs the ops kernels use.

Two APIs the kernels are written against moved homes / landed late:

* ``shard_map`` graduated from ``jax.experimental.shard_map`` to the
  top-level ``jax.shard_map``; the keyword-only call style (``mesh=``,
  ``in_specs=``, ``out_specs=``) is identical in both homes, so call
  sites need no per-version branches.
* the ``out_sharding=`` hint on ``.at[].set/.add/.get`` and
  ``jnp.matmul`` (jax >= 0.6, the explicit-sharding work). On older jax
  the same GSPMD constraint is expressed by wrapping the result in
  ``jax.lax.with_sharding_constraint`` — inside jit (where every kernel
  here runs) the compiler sees the identical layout hint, so the chosen
  ICI exchanges do not change.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = [
    "HAS_OUT_SHARDING",
    "reshard",
    "shard_map",
    "sharded_gather",
    "sharded_matmul",
    "sharded_scatter_add",
    "sharded_scatter_set",
]

try:  # jax >= 0.5 (and late 0.4.x nightlies)
    from jax import shard_map as _jax_shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: the experimental home
    from jax.experimental.shard_map import shard_map as _jax_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_rep: bool = True):
    """``jax.shard_map`` with a version-portable replication-check knob.

    Kernels whose replicated outputs the static checker cannot infer
    (``all_gather`` -> ``top_k`` merge chains, e.g. the sharded serving
    top-K) pass ``check_rep=False``. The flag moved homes across jax
    releases — ``check_rep`` in the experimental API, ``check_vma`` in
    the new top-level one — so the translation lives here, beside the
    import-home shim, instead of in every call site."""
    if check_rep:
        return _jax_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    try:
        return _jax_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
    except TypeError:  # jax >= 0.7 renamed the knob
        return _jax_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )

try:  # jax >= 0.6 explicit-sharding API
    from jax.sharding import reshard  # type: ignore[attr-defined]
except ImportError:
    def reshard(x, sharding):
        """Older jax has no Explicit-mode sharded types, so inside jit a
        sharding constraint expresses the same layout change the real
        ``reshard`` performs."""
        return jax.lax.with_sharding_constraint(x, sharding)


def _version_tuple() -> tuple[int, ...]:
    parts = []
    for p in jax.__version__.split(".")[:3]:
        digits = "".join(ch for ch in p if ch.isdigit())
        parts.append(int(digits or 0))
    return tuple(parts)


#: the ``out_sharding=`` kwarg on indexed update ops / matmul
HAS_OUT_SHARDING = _version_tuple() >= (0, 6, 0)


def _constrained(value: jax.Array, sharding: Any) -> jax.Array:
    return jax.lax.with_sharding_constraint(value, sharding)


def sharded_scatter_set(arr, idx, val, sharding=None) -> jax.Array:
    """``arr.at[idx].set(val)`` with an output-sharding hint."""
    if sharding is None:
        return arr.at[idx].set(val)
    if HAS_OUT_SHARDING:
        return arr.at[idx].set(val, out_sharding=sharding)
    return _constrained(arr.at[idx].set(val), sharding)


def sharded_scatter_add(arr, idx, val, sharding=None) -> jax.Array:
    """``arr.at[idx].add(val)`` with an output-sharding hint."""
    if sharding is None:
        return arr.at[idx].add(val)
    if HAS_OUT_SHARDING:
        return arr.at[idx].add(val, out_sharding=sharding)
    return _constrained(arr.at[idx].add(val), sharding)


def sharded_gather(arr, idx, sharding=None) -> jax.Array:
    """``arr[idx]`` with an output-sharding hint."""
    if sharding is None:
        return arr[idx]
    if HAS_OUT_SHARDING:
        return arr.at[idx].get(out_sharding=sharding)
    return _constrained(arr[idx], sharding)


def sharded_matmul(a, b, precision=None, sharding=None) -> jax.Array:
    """``jnp.matmul`` with an output-sharding hint."""
    if sharding is None:
        return jnp.matmul(a, b, precision=precision)
    if HAS_OUT_SHARDING:
        return jnp.matmul(a, b, precision=precision, out_sharding=sharding)
    return _constrained(jnp.matmul(a, b, precision=precision), sharding)
