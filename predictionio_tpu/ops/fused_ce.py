"""Flash-style fused in-batch softmax cross-entropy (Pallas/TPU).

The two-tower training step's cost is NOT its GEMMs: at B=8192, D=64 the
logits matrix is [B, B] = 268 MB fp32, and the unfused XLA pipeline
(2 logits GEMMs -> 2 softmax-CE fwd -> softmax recompute + 4 GEMMs bwd)
streams it through HBM ~10 times per step. Arithmetic intensity of that
chain is ~D FLOPs/byte = 64, far under the v5e roofline crossover (~240),
capping MFU near 7% no matter how fast the MXU is.

This kernel never materializes the logits in HBM. One row-block sweep
computes logit tiles in VMEM, exponentiates in place, and reduces:

* forward: row sums ``rs`` (user->item denominators), column sums ``cs``
  (item->user denominators — the symmetric loss is the SAME matrix read
  down columns), and the diagonal (the positive-pair logits). The loss
  closes on the host side: ``0.5/B * (sum log rs + sum log cs - 2 sum d)``.
* backward: recomputes each tile (flash-attention-style rematerialization
  — a second 2*B*B*D FLOPs buys removing ~5 GB/step of HBM traffic),
  forms ``dL`` in VMEM, and feeds TWO grad GEMMs per tile:
  ``d_ue = dL @ ie`` written per block and ``d_ie += dL^T @ ue_blk``
  accumulated in a VMEM-resident output (consecutive revisits).

No running max is carried (vs. true flash softmax): tower vectors are
L2-normalized so logits are bounded by ``inv_temp`` (~10), and
``exp(10) * 8192`` sits comfortably inside fp32 — the max subtraction
would cost an extra pass for nothing.

HBM traffic per step collapses to O(B*D). FLOP accounting (matching the
MFU math in ``bench.py``): **useful** work is 6*B^2*D per step — the
forward logits GEMM (2*B^2*D) plus the two backward grad GEMMs
(4*B^2*D); the backward tile recompute adds 2*B^2*D of
**rematerialization** overhead that buys the HBM savings and is
deliberately excluded from the MFU numerator. Total executed is
8*B^2*D, so the step turns compute-bound — the condition MFU needs.
GEMM operands are cast to bf16 (fp32 accumulation), riding the MXU at
full rate.

No reference counterpart (the reference has no deep-retrieval template);
design per /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_inbatch_ce", "fused_ce_supported"]

#: rows of the logits computed per grid step. 128 keeps the live tile set
#: (L, E, dL at [TI, B] fp32) a few MB — VMEM-safe at B up to ~16k on v5e.
_TI = 128


#: the kernel carries no running max (logits are bounded by inv_temp for
#: L2-normalized towers), so exp(inv_temp) * B must stay finite in fp32:
#: inv_temp <= 60 leaves exp(60)*2^20 ~ 1.2e32 << fp32 max. Beyond that
#: (temperature < ~0.017) callers must use the max-subtracted XLA path.
MAX_INV_TEMP = 60.0


def fused_ce_supported(B: int, D: int, inv_temp: float = 1.0) -> bool:
    """Shapes/scales the kernel handles: full row blocks, lane-aligned D,
    and a temperature that cannot overflow the max-free exp."""
    return (
        B % _TI == 0
        and D % 8 == 0
        and B >= _TI
        and 0.0 < inv_temp <= MAX_INV_TEMP
    )


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(ue_ref, ie_ref, rs_ref, cs_ref, *, inv_temp, ti):
    i = pl.program_id(0)
    logits = (
        jnp.dot(
            ue_ref[:].astype(jnp.bfloat16),
            ie_ref[:].astype(jnp.bfloat16).T,
            preferred_element_type=jnp.float32,
        )
        * inv_temp
    )  # [TI, B]
    # exp in bf16: 2x the VPU transcendental rate, and the kernel's exp
    # only feeds the softmax DENOMINATORS (rs/cs sums) — the positive-pair
    # numerator term is computed exactly in fp32 by the caller. Sums
    # accumulate in fp32.
    e = jnp.exp(logits.astype(jnp.bfloat16)).astype(jnp.float32)
    rs_ref[:] = jnp.sum(e, axis=1, keepdims=True)  # [TI, 1]
    cs = jnp.sum(e, axis=0, keepdims=True)  # [1, B]

    @pl.when(i == 0)
    def _():
        cs_ref[:] = jnp.zeros_like(cs_ref)

    cs_ref[:] = cs_ref[:] + cs
    # NOTE: the diagonal (positive-pair logits) is deliberately NOT read
    # here — L_ii is just rowsum(ue*ie)*inv_temp, an O(B*D) elementwise
    # the caller computes outside; a masked in-kernel extraction costs
    # [TI, B] iota+select work per tile for nothing


def _bwd_kernel(
    ue_ref, ie_ref, rs_ref, cs_ref, due_ref, die_ref, *, inv_temp, ti, b
):
    i = pl.program_id(0)
    ue16 = ue_ref[:].astype(jnp.bfloat16)
    ie16 = ie_ref[:].astype(jnp.bfloat16)
    logits = (
        jnp.dot(ue16, ie16.T, preferred_element_type=jnp.float32) * inv_temp
    )
    # bf16 exp (see _fwd_kernel); the fwd pass computed rs/cs from the
    # SAME rounding, so the softmax here is self-consistent
    e = jnp.exp(logits.astype(jnp.bfloat16)).astype(jnp.float32)
    c = 0.5 * inv_temp / b
    # softmax terms of both CE directions share the tile. The positive
    # pair's -delta_ij correction is NOT applied here: it is a rowwise
    # subtraction (due_i -= 2c*ie_i, die_i -= 2c*ue_i) the caller does
    # outside — keeping the tile free of iota/select masks
    dl = (e * (c / rs_ref[:]) + e * (c / cs_ref[:])).astype(jnp.bfloat16)
    due_ref[:] = jnp.dot(dl, ie16, preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _():
        die_ref[:] = jnp.zeros_like(die_ref)

    die_ref[:] = die_ref[:] + jnp.dot(
        dl.T, ue16, preferred_element_type=jnp.float32
    )


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("inv_temp", "interpret"))
def _fwd_call(ue, ie, inv_temp: float, interpret: bool):
    B, D = ue.shape
    grid = (B // _TI,)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, inv_temp=inv_temp, ti=_TI),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TI, D), lambda i: (i, 0)),
            pl.BlockSpec((B, D), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_TI, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, B), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, B), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * B * B * D,
            bytes_accessed=2 * B * D * 4 * (B // _TI),
            transcendentals=B * B,
        ),
        interpret=interpret,
    )(ue, ie)


@functools.partial(jax.jit, static_argnames=("inv_temp", "interpret"))
def _bwd_call(ue, ie, rs, cs, inv_temp: float, interpret: bool):
    B, D = ue.shape
    grid = (B // _TI,)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, inv_temp=inv_temp, ti=_TI, b=B),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TI, D), lambda i: (i, 0)),
            pl.BlockSpec((B, D), lambda i: (0, 0)),
            pl.BlockSpec((_TI, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, B), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_TI, D), lambda i: (i, 0)),
            pl.BlockSpec((B, D), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, D), jnp.float32),
            jax.ShapeDtypeStruct((B, D), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=6 * B * B * D,
            bytes_accessed=4 * B * D * 4 * (B // _TI),
            transcendentals=B * B,
        ),
        interpret=interpret,
    )(ue, ie, rs, cs)


# ---------------------------------------------------------------------------
# custom-vjp entry point
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_inbatch_ce(
    ue: jax.Array, ie: jax.Array, inv_temp: float, interpret: bool = False
) -> jax.Array:
    """Mean symmetric in-batch softmax CE of L2-normalized tower outputs.

    Equals ``0.5 * (ce(ue@ie.T * t, arange) + ce(ie@ue.T * t, arange))``
    (the XLA reference path in ``ops/twotower.py``) without materializing
    either [B, B] matrix."""
    loss, _ = _fused_fwd(ue, ie, inv_temp, interpret)
    return loss


def _fused_fwd(ue, ie, inv_temp, interpret):
    rs, cs = _fwd_call(ue, ie, inv_temp, interpret)
    B = ue.shape[0]
    # positive-pair logits: the [B, B] diagonal is just the rowwise dot
    diag = jnp.sum(ue * ie, axis=1) * inv_temp
    loss = (
        0.5
        * (jnp.sum(jnp.log(rs)) + jnp.sum(jnp.log(cs)) - 2.0 * jnp.sum(diag))
        / B
    )
    return loss, (ue, ie, rs, cs)


def _fused_bwd(inv_temp, interpret, res, g):
    ue, ie, rs, cs = res
    due, die = _bwd_call(ue, ie, rs, cs, inv_temp, interpret)
    # the positive pair's -delta correction, hoisted out of the kernel:
    # d/due_i of (-diag terms) = -(2 * 0.5/B) * inv_temp * ie_i
    c2 = inv_temp / ue.shape[0]
    return (due - c2 * ie) * g, (die - c2 * ue) * g


fused_inbatch_ce.defvjp(_fused_fwd, _fused_bwd)
