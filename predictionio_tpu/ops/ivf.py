"""On-device IVF (inverted-file) approximate top-K retrieval.

Every exact query scores the whole catalog, so serving FLOPs per query
grow linearly with catalog size — fine at the 27k-item bench shape,
fatal at "millions of users x millions of items" (ROADMAP item 2). This
module makes per-query cost scale with ``nprobe * (catalog / nlist)``
instead:

* **Build** (model-load time, :func:`build_ivf`) — a jitted k-means
  (k-means++ seeding on a bounded subsample, batched Lloyd iterations
  with chunked assignment so the [n, nlist] distance matrix never
  materializes whole) partitions the item factors into ``nlist``
  clusters, then the factors are reordered **cluster-major**: one
  contiguous ``[nlist, W, K]`` slab tensor (W = the largest cluster,
  smaller clusters padded) plus a ``[nlist, W]`` permutation index back
  to original item ids (padding carries the ``num_items`` sentinel).
  Contiguous slabs are what make the probe stage a dense gather+GEMM
  instead of a sparse scatter walk — the clustered layout half of the
  ALX recipe (PAPERS.md, "Large Scale Matrix Factorization on TPUs").
* **Query** (:func:`ivf_topk_batch` / :func:`ivf_topk_users`) — a
  two-stage jitted kernel in the broadcast-score-reduce shape DrJAX
  frames as a MapReduce primitive (PAPERS.md): score the ``nlist``
  centroids, ``lax.top_k`` the ``nprobe`` best clusters, score ONLY
  those slabs, and merge a global top-K through the permutation index
  with :func:`predictionio_tpu.ops.topk.top_k_permuted` (tie-stable in
  original item id). With ``nprobe == nlist`` the kernel skips the
  gather and scores the full cluster-major table with one GEMM — the
  same dot shape as the exact path — so it reproduces exact top-K
  bit-identically (scores AND tie order); CI asserts this.
* **Filtering** — blacklist/seen-item filters are applied by
  OVER-FETCHING ``K + |excluded|`` candidates before the final merge
  (:func:`query_topk`'s callers), never by post-hoc dropping from an
  exact-K result: a post-hoc filter returns fewer than K items whenever
  popular (high-scoring) items are excluded, and approximate retrieval
  amplifies that hole.

Serving integration: :mod:`predictionio_tpu.workflow.device_state`
builds/releases :class:`AnnRuntime` per model generation (hot-swapped by
``/reload`` exactly like pinned factors); templates route their top-K
through it when present. Everything is strictly opt-in behind
``pio deploy --ann`` — with the flag off this module is never imported
(CI-guarded).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.topk import bucket_k, top_k_permuted

__all__ = [
    "IVFIndex",
    "AnnRuntime",
    "build_ivf",
    "update_ivf",
    "ivf_topk_batch",
    "ivf_topk_users",
    "query_topk",
    "auto_nlist",
    "shard_runtime",
]

#: id-capacity rounding for incrementally grown indexes: ``num_items``
#: is STATIC under jit (it is the sentinel and the mask bound), so every
#: distinct value costs one retrace — growing it in 1024-item jumps
#: means a steady trickle of cold-start items re-traces the query kernel
#: once per ~1024 injections instead of once per fold
_CAPACITY_STEP = 1024

#: rows per chunk of the Lloyd assignment scan — bounds the transient
#: [chunk, nlist] distance block at 64 MB for nlist=1024 instead of
#: materializing the full [n, nlist] matrix (1 GB at 256k items)
_ASSIGN_CHUNK = 16_384

#: k-means++ seeds on at most max(4096, 16 * nlist) subsampled rows:
#: seeding is a scan of nlist O(n*K) steps, so full-catalog seeding would
#: cost nlist/iters times MORE than all Lloyd iterations together
_SEED_SAMPLE_PER_LIST = 16
_SEED_SAMPLE_MIN = 4096


class IVFIndex(NamedTuple):
    """Cluster-major retrieval state. Array fields are pytree children;
    the int metadata travels in the treedef so it stays STATIC under jit
    (the query kernel's shapes and the sentinel id are compile-time
    constants).

    With ``--quantize int8`` (``build_ivf(quantize=True)``) ``slabs``
    holds int8 codes and ``slab_scales`` the per-lane f32 scales
    (``ops/quant``'s one rounding rule) — per-probe gather bytes drop
    ~4x, which is the dominating cost of the probe stage on
    bandwidth-bound hosts (PR 6's measurement). ``slab_scales is None``
    means the classic f32 layout; the treedef difference keeps the two
    modes on separate compiled programs."""

    centroids: Any  # [nlist, K] f32 (ALWAYS f32 — stage 1 stays exact)
    slabs: Any  # [nlist, W, K] f32 (or int8 codes) — zero-padded slabs
    slab_ids: Any  # [nlist, W] int32 — item id per slab row; pad = num_items
    num_items: int
    nlist: int
    slab_width: int
    slab_scales: Any = None  # [nlist, W] f32 per-lane scales (int8 mode)


jax.tree_util.register_pytree_node(
    IVFIndex,
    lambda x: ((x.centroids, x.slabs, x.slab_ids, x.slab_scales),
               (x.num_items, x.nlist, x.slab_width)),
    lambda aux, ch: IVFIndex(ch[0], ch[1], ch[2], *aux, ch[3]),
)


def auto_nlist(num_items: int) -> int:
    """Default cluster count: ~sqrt(catalog) balances the two stage
    costs (stage 1 scores nlist centroids, stage 2 scores ~nprobe * I /
    nlist items), the standard IVF sizing rule of thumb."""
    return max(1, int(round(float(num_items) ** 0.5)))


# ---------------------------------------------------------------------------
# Build: jitted k-means (k-means++ seeding + batched Lloyd iterations)
# ---------------------------------------------------------------------------


def _assign_chunked(x_pad: jax.Array, cents: jax.Array) -> jax.Array:
    """argmin_c ||x - c||^2 per row of ``x_pad [n_chunks, C, K]`` ->
    ``[n_chunks, C]`` int32, one [C, nlist] distance block at a time.
    ||x||^2 is row-constant, so centroid scores reduce to c.c - 2 x.c."""
    c2 = (cents * cents).sum(axis=-1)

    def one(xc: jax.Array) -> jax.Array:
        d = c2[None, :] - 2.0 * (xc @ cents.T)
        return jnp.argmin(d, axis=1).astype(jnp.int32)

    return jax.lax.map(one, x_pad)


@functools.partial(jax.jit, static_argnames=("nlist",))
def _kmeans_pp(key: jax.Array, x: jax.Array, nlist: int) -> jax.Array:
    """k-means++ seeding: first centroid uniform, then D^2 sampling via
    ``categorical(log d2)`` — one fused scan, no host round trips."""
    n = x.shape[0]
    key, k0 = jax.random.split(key)
    c0 = x[jax.random.randint(k0, (), 0, n)]
    cents = jnp.zeros((nlist, x.shape[1]), x.dtype).at[0].set(c0)
    d2 = ((x - c0) ** 2).sum(axis=-1)

    def body(carry, i):
        key, cents, d2 = carry
        key, kc = jax.random.split(key)
        # duplicate points drive d2 to exactly 0; log() sends them to
        # -inf (never re-picked). If EVERY point is already covered the
        # draw degrades to uniform rather than sampling NaNs.
        logits = jnp.where(d2 > 0, jnp.log(jnp.maximum(d2, 1e-30)), -jnp.inf)
        logits = jnp.where(jnp.any(d2 > 0), logits, jnp.zeros_like(logits))
        c = x[jax.random.categorical(kc, logits)]
        cents = cents.at[i].set(c)
        d2 = jnp.minimum(d2, ((x - c) ** 2).sum(axis=-1))
        return (key, cents, d2), None

    (_, cents, _), _ = jax.lax.scan(
        body, (key, cents, d2), jnp.arange(1, nlist)
    )
    return cents


@functools.partial(jax.jit, static_argnames=("iters", "n"))
def _lloyd(
    x: jax.Array, x_pad: jax.Array, cents: jax.Array, iters: int, n: int
) -> jax.Array:
    """``iters`` batched Lloyd iterations: chunked assignment, then a
    scatter-add mean update. Empty clusters keep their old centroid (the
    slab build simply emits an all-sentinel slab for them)."""

    def step(cents, _):
        a = _assign_chunked(x_pad, cents).reshape(-1)[:n]
        sums = jnp.zeros_like(cents).at[a].add(x)
        counts = jnp.zeros((cents.shape[0],), x.dtype).at[a].add(1.0)
        new = sums / jnp.maximum(counts, 1.0)[:, None]
        return jnp.where(counts[:, None] > 0, new, cents), None

    cents, _ = jax.lax.scan(step, cents, None, length=iters)
    return cents


@functools.partial(jax.jit, static_argnames=("n",))
def _final_assign(x_pad: jax.Array, cents: jax.Array, n: int) -> jax.Array:
    return _assign_chunked(x_pad, cents).reshape(-1)[:n]


def _balance_assignment(
    x: np.ndarray, cents: np.ndarray, assign: np.ndarray,
    nlist: int, cap: int,
) -> np.ndarray:
    """Cap every cluster at ``cap`` items: overloaded clusters keep
    their ``cap`` CLOSEST members and spill the rest to the nearest
    cluster with room. The slab width — which every probe pays for in
    gather bytes regardless of which cluster it hits — is bounded by
    ``cap`` instead of the most popular cluster's size (factor models
    concentrate mass on popular regions, so unbalanced widths of 2-3x
    the mean are routine). ``nlist * cap >= items`` by construction, so
    placement always succeeds."""
    counts = np.bincount(assign, minlength=nlist)
    if counts.max() <= cap:
        return assign
    own = cents[assign]
    d_own = ((x - own) ** 2).sum(axis=1)
    spilled: list = []
    for c in np.nonzero(counts > cap)[0]:
        members = np.nonzero(assign == c)[0]
        keep = members[np.argsort(d_own[members], kind="stable")]
        spilled.extend(keep[cap:].tolist())
    counts = np.minimum(counts, cap)
    spill = np.asarray(spilled)
    # nearest-with-room greedy, processed in spill order. Ranking keys
    # come from the GEMM identity ||x-c||^2 = ||x||^2 - 2 x.c + ||c||^2
    # (||x||^2 is row-constant, so c.c - 2 x.c sorts identically): the
    # naive [spill, nlist, K] broadcast would materialize tens of GB at
    # the million-item catalogs this stage exists for. Chunked so the
    # [chunk, nlist] key block stays bounded too.
    c2 = (cents * cents).sum(axis=1)
    for lo in range(0, spill.size, 65_536):
        part = spill[lo : lo + 65_536]
        keys = c2[None, :] - 2.0 * (x[part] @ cents.T)
        prefs = np.argsort(keys, axis=1, kind="stable")
        for item, pref in zip(part, prefs):
            for c in pref:
                if counts[c] < cap:
                    assign[item] = c
                    counts[c] += 1
                    break
    return assign


def build_ivf(
    item_factors: np.ndarray,
    nlist: int = 0,
    seed: int = 0,
    iters: int = 8,
    balance: float = 1.3,
    quantize: bool = False,
) -> tuple[IVFIndex, dict]:
    """Partition ``item_factors [I, K]`` into ``nlist`` clusters and lay
    them out cluster-major. ``nlist <= 0`` picks :func:`auto_nlist`.
    Returns ``(index, build_info)`` — build_info feeds the query
    server's ``/stats.json`` ``ann`` section.

    ``balance`` caps every cluster at ``ceil(items / nlist * balance)``
    members (spill-to-nearest-with-room, :func:`_balance_assignment`),
    bounding the slab width — and with it both probe-stage gather bytes
    and padding waste — near the mean cluster size; ``balance <= 0``
    keeps the raw k-means assignment. The cap only moves BOUNDARY items
    (the ones farthest from an overloaded centroid), so recall impact is
    marginal, and the ``nprobe == nlist`` mode stays bit-identical to
    exact regardless (every slab is scored).

    The O(I*nlist*K) k-means runs jitted on the default backend; the
    final reorder is a single host argsort over the assignment (O(I log
    I) once per model generation, trivial next to the solve that
    produced the factors)."""
    t0 = time.perf_counter()
    x = np.ascontiguousarray(np.asarray(item_factors, dtype=np.float32))
    if x.ndim != 2:
        raise ValueError(f"item_factors must be [I, K], got {x.shape}")
    num_items, dim = x.shape
    if num_items == 0:
        raise ValueError("cannot build an IVF index over an empty catalog")
    nlist = int(nlist) if nlist > 0 else auto_nlist(num_items)
    nlist = max(1, min(nlist, num_items))

    xd = jnp.asarray(x)
    chunk = min(_ASSIGN_CHUNK, max(1, num_items))
    n_chunks = -(-num_items // chunk)
    x_pad = jnp.pad(xd, ((0, n_chunks * chunk - num_items), (0, 0))).reshape(
        n_chunks, chunk, dim
    )
    key = jax.random.PRNGKey(seed)
    if nlist == 1:
        cents = xd.mean(axis=0, keepdims=True)
    else:
        n_seed = min(
            num_items, max(_SEED_SAMPLE_MIN, _SEED_SAMPLE_PER_LIST * nlist)
        )
        if n_seed < num_items:
            key, ks = jax.random.split(key)
            sample = xd[jax.random.choice(
                ks, num_items, (n_seed,), replace=False
            )]
        else:
            sample = xd
        cents = _kmeans_pp(key, sample, nlist)
        cents = _lloyd(xd, x_pad, cents, max(0, int(iters)), num_items)
    # np.array (copy): the balancing pass mutates the assignment, and a
    # zero-copy view of a jax buffer is read-only
    assign = np.array(_final_assign(x_pad, cents, num_items))
    cents_np = np.asarray(cents)
    if balance and balance > 0:
        cap = max(1, int(np.ceil(num_items / nlist * balance)))
        assign = _balance_assignment(x, cents_np, assign, nlist, cap)

    counts = np.bincount(assign, minlength=nlist)
    slab_width = int(max(1, counts.max()))
    # cluster-major reorder; the stable sort keeps items in ascending id
    # order WITHIN each cluster, so the layout is deterministic
    order = np.argsort(assign, kind="stable")
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    lane = np.arange(num_items) - np.repeat(starts, counts)
    slab_ids = np.full((nlist, slab_width), num_items, dtype=np.int32)
    slab_ids[assign[order], lane] = order.astype(np.int32)
    slabs = np.zeros((nlist, slab_width, dim), dtype=np.float32)
    slabs[assign[order], lane] = x[order]

    if quantize:
        # int8 slab storage (--quantize int8): k-means and the reorder
        # above ran on the f32 values; only the SERVED layout quantizes
        # (per-lane codes + scales — ops/quant owns the rounding rule)
        from predictionio_tpu.ops import quant

        codes, lane_scales = quant.quantize_slabs(slabs)
        slab_arr = jnp.asarray(codes)
        scale_arr = jnp.asarray(lane_scales)
    else:
        slab_arr = jnp.asarray(slabs)
        scale_arr = None
    index = IVFIndex(
        centroids=jnp.asarray(cents_np),
        slabs=slab_arr,
        slab_ids=jnp.asarray(slab_ids),
        num_items=num_items,
        nlist=nlist,
        slab_width=slab_width,
        slab_scales=scale_arr,
    )
    info = {
        "nlist": nlist,
        "slabWidth": slab_width,
        "catalogItems": num_items,
        # fraction of slab rows holding real items — 1/fill is the
        # padding overhead the largest cluster imposes on the others
        "fill": round(num_items / float(nlist * slab_width), 4),
        "emptyClusters": int((counts == 0).sum()),
        "balance": float(balance),
        "kmeansIters": int(iters),
        "seed": int(seed),
        "quantized": bool(quantize),
        "bytesIndex": _index_bytes(index),
        "buildSeconds": round(time.perf_counter() - t0, 3),
    }
    return index, info


def _index_bytes(index: IVFIndex) -> int:
    """Real served bytes of the index arrays (dtype-honest: int8 slabs
    count 1 byte/element, their scales 4)."""
    total = (
        index.centroids.size * index.centroids.dtype.itemsize
        + index.slabs.size * index.slabs.dtype.itemsize
        + index.slab_ids.size * index.slab_ids.dtype.itemsize
    )
    if index.slab_scales is not None:
        total += index.slab_scales.size * index.slab_scales.dtype.itemsize
    return int(total)


# ---------------------------------------------------------------------------
# Incremental update: fold-in without a k-means rebuild
# ---------------------------------------------------------------------------


def _host_mirror(index: IVFIndex) -> dict:
    """Mutable host-side view of an index for incremental maintenance:
    numpy slab copies, per-cluster fill counts, and an item -> slab-slot
    map. Built once per index generation, reused across folds. For a
    quantized index the mirror keeps the int8 codes AND the per-lane
    scales — fold-ins then re-quantize only the touched lanes (delta
    cost, never a full-catalog requantization)."""
    slabs = np.array(index.slabs)  # f32 rows, or int8 codes (quantized)
    slab_ids = np.array(index.slab_ids, dtype=np.int32)
    cents = np.asarray(index.centroids, dtype=np.float32)
    sentinel = index.num_items
    pos = np.full(sentinel, -1, np.int64)
    cl, lane = np.nonzero(slab_ids != sentinel)
    pos[slab_ids[cl, lane]] = cl * index.slab_width + lane
    return {
        "slabs": slabs,
        "slab_ids": slab_ids,
        "scales": (
            np.array(index.slab_scales, dtype=np.float32)
            if index.slab_scales is not None
            else None
        ),
        "centroids": cents,
        "c2": (cents * cents).sum(axis=1),
        "fill": (slab_ids != sentinel).sum(axis=1).astype(np.int64),
        "pos": pos,
        "capacity": sentinel,
    }


def _grow_width(state: dict, extra: int) -> None:
    nlist, width, dim = state["slabs"].shape
    pad = max(1, extra, width // 4)
    slabs = np.zeros((nlist, width + pad, dim), state["slabs"].dtype)
    slabs[:, :width] = state["slabs"]
    ids = np.full((nlist, width + pad), state["capacity"], np.int32)
    ids[:, :width] = state["slab_ids"]
    if state.get("scales") is not None:
        scales = np.zeros((nlist, width + pad), np.float32)
        scales[:, :width] = state["scales"]
        state["scales"] = scales
    # re-derive positions: lane arithmetic changed with the width
    pos = np.full(state["capacity"], -1, np.int64)
    cl, lane = np.nonzero(ids != state["capacity"])
    pos[ids[cl, lane]] = cl * (width + pad) + lane
    state["slabs"] = slabs
    state["slab_ids"] = ids
    state["pos"] = pos


def update_ivf(
    index: IVFIndex,
    item_ids: np.ndarray,
    vectors: np.ndarray,
    total_items: int,
    state: dict | None = None,
) -> tuple[IVFIndex, dict, dict]:
    """Fold changed/new item vectors into an existing index WITHOUT a
    k-means rebuild (ROADMAP PR-6 follow-up): each vector is assigned to
    its nearest EXISTING centroid's slab, spilling to the nearest
    cluster with room when the target slab is full (and growing the slab
    width as a last resort). Centroids stay fixed — the point of
    fold-in is that per-update cost scales with the delta, not the
    catalog; a periodic full rebuild (every ``/reload``) re-learns them.

    * an item already in the index whose nearest centroid is unchanged
      updates its slab row in place;
    * an item that MOVED clusters is tombstoned out of its old slab
      (sentinel id, zero row) and re-inserted;
    * a new item (``id >= capacity``) grows the id capacity in
      :data:`_CAPACITY_STEP` jumps — capacity is the jit-static sentinel,
      so stepping it bounds retraces.

    ``state`` is the reusable host mirror from a previous call (pass the
    second return value back in); None builds it from ``index``. Returns
    ``(new index, state, info)``."""
    if state is None:
        state = _host_mirror(index)
    item_ids = np.asarray(item_ids, np.int64)
    vectors = np.ascontiguousarray(np.asarray(vectors, np.float32))
    old_capacity = state["capacity"]
    capacity = old_capacity
    if total_items > capacity:
        capacity = -(-total_items // _CAPACITY_STEP) * _CAPACITY_STEP
        # rewrite the sentinel: padding slots must track the new bound
        # (an item id equal to the OLD capacity is now a real id)
        pad_mask = state["slab_ids"] == old_capacity
        state["slab_ids"][pad_mask] = capacity
        pos = np.full(capacity, -1, np.int64)
        pos[: state["pos"].size] = state["pos"]
        state["pos"] = pos
        state["capacity"] = capacity
    slabs = state["slabs"]
    ids = state["slab_ids"]
    fill = state["fill"]
    pos = state["pos"]
    scales = state.get("scales")
    width = slabs.shape[1]
    if scales is not None:
        # quantized slabs: the mirror stores int8 codes + per-lane
        # scales, so only the TOUCHED lanes re-quantize on scatter —
        # the same delta-cost rule as the factor-table fold-in
        from predictionio_tpu.ops import quant

        lane_vals, lane_scales = quant.quantize_table_host(vectors)
    else:
        lane_vals, lane_scales = vectors, None

    def write_lane(cl, lane, j):
        slabs[cl, lane] = lane_vals[j]
        if scales is not None:
            scales[cl, lane] = lane_scales[j]

    # nearest-centroid preference order per changed item, via the GEMM
    # identity (||x||^2 is row-constant); the delta is small, so the
    # [M, nlist] block is cheap
    keys = state["c2"][None, :] - 2.0 * (vectors @ state["centroids"].T)
    prefs = np.argsort(keys, axis=1, kind="stable")
    moved = inserted = in_place = spilled = 0
    for j, (iid, pref) in enumerate(zip(item_ids.tolist(), prefs)):
        cur = pos[iid]
        target = int(pref[0])
        if cur >= 0:
            cl, lane = divmod(int(cur), width)
            if cl == target:
                write_lane(cl, lane, j)
                in_place += 1
                continue
            ids[cl, lane] = capacity  # tombstone out of the old slab
            slabs[cl, lane] = 0
            if scales is not None:
                scales[cl, lane] = 0.0
            fill[cl] -= 1
            pos[iid] = -1
            moved += 1
        else:
            inserted += 1
        placed = False
        for rank_i, c in enumerate(pref.tolist()):
            if fill[c] >= width:
                continue
            lane = int(np.argmax(ids[c] == capacity))
            ids[c, lane] = iid
            write_lane(c, lane, j)
            fill[c] += 1
            pos[iid] = c * width + lane
            spilled += int(rank_i > 0)
            placed = True
            break
        if not placed:  # every slab full: widen, then retry is trivial
            _grow_width(state, 1)
            slabs = state["slabs"]
            ids = state["slab_ids"]
            pos = state["pos"]
            scales = state.get("scales")
            width = slabs.shape[1]
            lane = int(np.argmax(ids[target] == capacity))
            ids[target, lane] = iid
            write_lane(target, lane, j)
            fill[target] += 1
            pos[iid] = target * width + lane
    new_index = IVFIndex(
        centroids=index.centroids,
        # copies, not views: on CPU backends jnp.asarray adopts aligned
        # numpy buffers zero-copy, and `state` mutates these arrays in
        # place on the NEXT update while in-flight queries may still be
        # scoring this index
        slabs=jnp.asarray(slabs.copy()),
        slab_ids=jnp.asarray(ids.copy()),
        num_items=capacity,
        nlist=index.nlist,
        slab_width=width,
        slab_scales=(
            jnp.asarray(scales.copy()) if scales is not None else None
        ),
    )
    info = {
        "inPlace": in_place,
        "moved": moved,
        "inserted": inserted,
        "spilled": spilled,
        "capacity": capacity,
        "slabWidth": width,
    }
    return new_index, state, info


# ---------------------------------------------------------------------------
# Sharded slabs: the --shard-factors composition (ROADMAP item-2 follow-up)
# ---------------------------------------------------------------------------


def _shard_index(index: IVFIndex, mesh) -> IVFIndex:
    """Lay an index's cluster-major slabs out sharded over the mesh's
    ``model`` axis: ``nlist`` pads to a multiple of the axis (sentinel
    slab ids, zero slabs — the sharded kernel masks padded clusters out
    of stage 1 by the TRUE ``nlist`` in the static metadata), slabs and
    slab ids shard cluster-major, centroids stay replicated (tiny).
    Per-device slab memory drops to ``nlist/S · W · K``."""
    from predictionio_tpu.parallel import sharding  # lazy: avoids a cycle

    from jax.sharding import NamedSharding, PartitionSpec

    S = int(mesh.shape[sharding.MODEL_AXIS])
    nlist_pad = -(-index.nlist // S) * S
    pad = nlist_pad - index.nlist
    cents = np.asarray(index.centroids, np.float32)
    # dtype preserved: int8 codes shard as int8 (the whole point)
    slabs = np.asarray(index.slabs)
    ids = np.asarray(index.slab_ids, np.int32)
    scales = (
        np.asarray(index.slab_scales, np.float32)
        if index.slab_scales is not None
        else None
    )
    if pad:
        cents = np.concatenate(
            [cents, np.zeros((pad, cents.shape[1]), np.float32)]
        )
        slabs = np.concatenate(
            [slabs, np.zeros((pad,) + slabs.shape[1:], slabs.dtype)]
        )
        ids = np.concatenate(
            [ids, np.full((pad, ids.shape[1]), index.num_items, np.int32)]
        )
        if scales is not None:
            scales = np.concatenate(
                [scales, np.zeros((pad, scales.shape[1]), np.float32)]
            )
    ax = sharding.MODEL_AXIS
    return IVFIndex(
        centroids=jnp.asarray(cents),
        slabs=jax.device_put(
            slabs, NamedSharding(mesh, PartitionSpec(ax, None, None))
        ),
        slab_ids=jax.device_put(
            ids, NamedSharding(mesh, PartitionSpec(ax, None))
        ),
        num_items=index.num_items,
        nlist=index.nlist,
        slab_width=index.slab_width,
        slab_scales=(
            jax.device_put(
                scales, NamedSharding(mesh, PartitionSpec(ax, None))
            )
            if scales is not None
            else None
        ),
    )


def shard_runtime(runtime: "AnnRuntime", mesh) -> dict:
    """Re-lay a runtime's index sharded over the serving mesh (``pio
    deploy --shard-factors --ann``). The UNPADDED index is kept on the
    runtime as ``host_index`` so incremental fold-ins
    (:meth:`AnnRuntime.update_items`) keep operating on the clean id
    space and re-shard only the updated layout; queries route through
    :func:`predictionio_tpu.parallel.sharding.sharded_ivf_topk` once
    ``shard_mesh`` is set. Returns the info-dict delta for
    ``/stats.json``."""
    with runtime._lock:
        index = runtime.index
    sharded = _shard_index(index, mesh)
    S = int(mesh.shape["model"])
    sharded_bytes = (
        sharded.slabs.size * sharded.slabs.dtype.itemsize
        + sharded.slab_ids.size * sharded.slab_ids.dtype.itemsize
    )
    if sharded.slab_scales is not None:
        sharded_bytes += (
            sharded.slab_scales.size * sharded.slab_scales.dtype.itemsize
        )
    delta = {
        "shards": S,
        "bytesIndexPerDevice": int(
            sharded.centroids.size * sharded.centroids.dtype.itemsize
            + sharded_bytes // S
        ),
    }
    with runtime._lock:
        runtime.host_index = index
        runtime.index = sharded
        runtime.shard_mesh = mesh
        runtime.build_info.update(delta)  # /stats.json ann section
    return delta


# ---------------------------------------------------------------------------
# Query: two-stage jitted retrieval
# ---------------------------------------------------------------------------


def _ivf_topk(
    qvecs: jax.Array, index: IVFIndex, k: int, nprobe: int
) -> tuple[jax.Array, jax.Array]:
    """Shared kernel body (trace-time ``k``/``nprobe``): score
    centroids, select clusters, score slabs, tie-stable global merge."""
    nlist, width = index.nlist, index.slab_width
    lane_scales = index.slab_scales  # not None => int8 slab codes
    nprobe = max(1, min(int(nprobe), nlist))
    if nprobe >= nlist:
        # every cluster probed: skip stage 1 and the gather entirely and
        # score the whole cluster-major table with ONE [B,K]@[K,n*W]
        # GEMM — the same dot shape as the exact path, which is what
        # makes this mode bit-identical to exact top-K (CI-asserted;
        # in int8 mode the claim is determinism over the DEQUANTIZED
        # table, the strongest statement a lossy layout admits)
        flat = index.slabs.reshape(nlist * width, -1)
        if lane_scales is not None:
            scores = (qvecs @ flat.T.astype(jnp.float32)) * (
                lane_scales.reshape(1, nlist * width)
            )
        else:
            scores = qvecs @ flat.T
        ids = jnp.broadcast_to(
            index.slab_ids.reshape(1, nlist * width), scores.shape
        )
    else:
        cent_scores = qvecs @ index.centroids.T  # [B, nlist]
        _, probe = jax.lax.top_k(cent_scores, nprobe)  # [B, nprobe]
        # one gather+einsum per probe SLOT (static nprobe unroll): the
        # [B, W, K] intermediates stay cache-sized, measured ~25% faster
        # on CPU than the single [B, nprobe, W, K] materialization
        score_l = []
        id_l = []
        for j in range(nprobe):
            sel = probe[:, j]
            cand = index.slabs[sel]  # [B, W, K] — int8: 1/4 the gather bytes
            if lane_scales is not None:
                # dequantize AFTER the dot: one f32 multiply per lane
                # instead of per element (measured faster on CPU, exact
                # same value up to f32 rounding)
                s_j = jnp.einsum(
                    "bwk,bk->bw", cand.astype(jnp.float32), qvecs
                ) * lane_scales[sel]
            else:
                s_j = jnp.einsum("bwk,bk->bw", cand, qvecs)
            score_l.append(s_j)
            id_l.append(index.slab_ids[sel])
        scores = jnp.concatenate(score_l, axis=1)  # [B, nprobe*W]
        ids = jnp.concatenate(id_l, axis=1)
    # padding rows are zero vectors (score 0.0, which could outrank real
    # negative scores) — mask by the id sentinel, not by value
    scores = jnp.where(ids < index.num_items, scores, -jnp.inf)
    k = max(1, min(int(k), scores.shape[-1]))
    # item ids below 2^24 are exact in f32, unlocking the fast f32-keyed
    # merge; huge catalogs keep exactness via the sort-based path
    return top_k_permuted(scores, ids, k, big_ids=index.num_items >= (1 << 24))


@functools.partial(jax.jit, static_argnames=("k", "nprobe"))
def ivf_topk_batch(
    qvecs: jax.Array, index: IVFIndex, k: int, nprobe: int
) -> tuple[jax.Array, jax.Array]:
    """Approximate top-k for a batch of query VECTORS ``[B, K]``:
    ``([B, k] item ids, [B, k] scores)``, descending score, ties by
    ascending item id. Rows whose probed clusters hold fewer than ``k``
    real items carry the ``num_items`` sentinel (score ``-inf``) in the
    tail — callers drop it host-side (:func:`trim_row`)."""
    return _ivf_topk(qvecs, index, k, nprobe)


@functools.partial(jax.jit, static_argnames=("k", "nprobe"))
def ivf_topk_users(
    user_idx: jax.Array,
    user_factors: jax.Array,
    index: IVFIndex,
    k: int,
    nprobe: int,
) -> tuple[jax.Array, jax.Array]:
    """Approximate top-k for a batch of USERS: gather the user rows on
    device, then the two-stage kernel — the ANN counterpart of
    :func:`predictionio_tpu.ops.als.top_k_items_batch`, one dispatch per
    chunk."""
    return _ivf_topk(user_factors[user_idx], index, k, nprobe)


def trim_row(ids: np.ndarray, scores: np.ndarray, num_items: int):
    """Drop sentinel padding from one result row; returns plain lists."""
    keep = ids < num_items
    return ids[keep].tolist(), scores[keep].tolist()


class AnnRuntime:
    """Per-model serving state: the index, the deploy-time ``nprobe``,
    build info, and thread-safe query counters for ``/stats.json``.

    Attached to a model as ``model._pio_ann`` by the algorithm's
    ``build_ann_for_serving`` hook (driven by
    :mod:`predictionio_tpu.workflow.device_state` at (re)load), detached
    by ``release_ann_state`` when the generation is superseded."""

    def __init__(self, index: IVFIndex, nprobe: int, build_info: dict):
        self.index = index
        self.nprobe = max(1, min(int(nprobe), index.nlist))
        self.build_info = dict(build_info)
        self._lock = threading.Lock()
        self.queries = 0
        self.clusters_scored = 0
        self.candidates_scored = 0
        #: incremental-maintenance host mirror (built on first update)
        self._update_state: dict | None = None
        self.incremental_updates = 0
        self.items_folded = 0
        #: --shard-factors state (see :func:`shard_runtime`): when set,
        #: ``index`` holds the PADDED sharded layout queries run on and
        #: ``host_index`` the unpadded one fold-ins update
        self.shard_mesh = None
        self.host_index: IVFIndex | None = None

    def update_items(
        self, item_ids: np.ndarray, vectors: np.ndarray, total_items: int
    ) -> dict:
        """Fold changed/new item vectors into the live index — nearest-
        centroid slab assignment with spill, no k-means rebuild (see
        :func:`update_ivf`). Swaps ``self.index`` atomically; in-flight
        queries that already snapshotted the old index finish against
        it consistently."""
        with self._lock:
            state = self._update_state
            mesh = self.shard_mesh
            index = self.host_index if mesh is not None else self.index
        new_index, state, info = update_ivf(
            index, item_ids, vectors, total_items, state
        )
        # sharded serving: the fold updates the clean unpadded layout,
        # then the whole (delta-sized rebuilt) layout re-shards — queries
        # snapshotting the old sharded index finish against it
        new_sharded = (
            _shard_index(new_index, mesh) if mesh is not None else None
        )
        with self._lock:
            if mesh is not None:
                self.host_index = new_index
                self.index = new_sharded
            else:
                self.index = new_index
            self._update_state = state
            self.incremental_updates += 1
            self.items_folded += len(np.asarray(item_ids))
        return info

    def note_queries(self, n: int) -> None:
        """Account ``n`` queries' worth of scored clusters/candidates."""
        probed = self.nprobe
        if probed >= self.index.nlist:
            candidates = self.index.num_items  # exact-equivalent mode
        else:
            candidates = probed * self.index.slab_width
        with self._lock:
            self.queries += n
            self.clusters_scored += n * probed
            self.candidates_scored += n * candidates

    def stats_json(self) -> dict:
        with self._lock:
            q = self.queries
            clusters = self.clusters_scored
            candidates = self.candidates_scored
        total = q * self.index.num_items
        with self._lock:
            inc = self.incremental_updates
            folded = self.items_folded
        out = {
            "nprobe": self.nprobe,
            "queries": q,
            "clustersScored": clusters,
            "candidatesScored": candidates,
            "incrementalUpdates": inc,
            "itemsFolded": folded,
            # the headline number: what fraction of the catalog each
            # query paid for, vs 1.0 on the exact path
            "fractionOfCatalogScored": (
                round(candidates / total, 4) if total else 0.0
            ),
        }
        out.update(self.build_info)
        return out


def query_topk(
    runtime: AnnRuntime, qvec: np.ndarray, k: int
) -> tuple[list, list]:
    """Single-query retrieval through the index: top-``k`` as
    ``(item id list, score list)`` with sentinel padding trimmed.
    Callers applying blacklist/seen filters must OVER-FETCH here —
    ``k = wanted + len(excluded)`` — and drop excluded ids from the
    returned (longer) list, so the final result still holds ``wanted``
    items (see module docstring). ``k`` is bucketed to a power of two
    (floor 16) so the jitted kernel compiles once per bucket, exactly
    like the exact path's ``chunked_topk``."""
    index = runtime.index
    k = min(int(k), index.num_items)
    if k <= 0:
        return [], []
    kb = bucket_k(k, index.num_items)
    q = jnp.asarray(np.asarray(qvec, dtype=np.float32)[None, :])
    if runtime.shard_mesh is not None:
        from predictionio_tpu.parallel import sharding

        ids, scores = sharding.sharded_ivf_topk(
            q, index, kb, runtime.nprobe, runtime.shard_mesh
        )
    else:
        ids, scores = ivf_topk_batch(q, index, kb, runtime.nprobe)
    runtime.note_queries(1)
    ids_l, scores_l = trim_row(
        np.asarray(ids)[0], np.asarray(scores)[0], index.num_items
    )
    return ids_l[:k], scores_l[:k]
