"""Per-row symmetric int8 quantization — the ``--quantize int8`` tier.

PR 6 measured that gather bandwidth dominates IVF probes on CPU hosts
and PR 9 that per-device factor bytes are the hard catalog ceiling even
after S-way sharding; ALX (arxiv 2112.02194) shows mixed-precision
factorization is the standard TPU answer to both. This module brings
that idiom to the serving path: factor tables (and IVF slabs, see
:mod:`predictionio_tpu.ops.ivf`) are stored as **int8 codes plus one
f32 scale per row**, so a served catalog costs ``rank + 4`` bytes per
row instead of ``4·rank`` — ~4x more catalog per device multiplied on
top of the ``/S`` from sharding, and ~4x less memory traffic per
gathered candidate.

Quality is kept by a **recall-guarded two-stage top-K**:

1. **int8 coarse scan** — the query row is itself quantized and scored
   against the whole table with one int8×int8 GEMM accumulated in
   int32, rescaled by the product of the two scales. This stage
   OVER-FETCHES ``k' = max(4k, k + 64)`` candidates (:func:`overfetch`)
   so quantization noise at the k-th boundary costs candidates, never
   results.
2. **f32 rescore** — only the ``k'`` gathered candidates are
   dequantized and re-scored against the *unquantized* f32 query, then
   merged through the shared tie rule
   (:func:`predictionio_tpu.ops.topk.sort_merge_topk`: descending
   score, ties by ascending id). The final ordering is therefore
   exact-f32-deterministic over the dequantized rows — adversarial
   equal-score rows rank identically to the f32 exact path
   (CI-asserted), replicated and sharded alike.

This is ONE quantization rule in ONE module: piolint PIO305 bans raw
``int8`` construction anywhere else under ``ops/``, ``parallel/`` and
``workflow/`` (the same containment contract PIO304 enforces for
``shard_map``), so every code/scale pair in the repo agrees on the
rounding, the zero-row guard, and the re-quantize-on-scatter rule the
online fold-in relies on. Strictly opt-in: nothing imports this module
until a deploy passes ``--quantize int8`` (CI-guarded like ``--ann`` /
``--shard-factors``).
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.topk import bucket_k, sort_merge_topk

__all__ = [
    "QuantizedTable",
    "QuantRuntime",
    "quantize_rows",
    "quantize_rows_traced",
    "quantize_table_host",
    "quantize_slabs",
    "dequantize",
    "quantize_table",
    "quantization_error",
    "overfetch",
    "int8_matmul",
    "quantized_topk_batch",
    "quantized_topk_users",
    "run_topk",
    "topk_users",
    "table_bytes_f32",
]

#: symmetric code range: [-127, 127] (the -128 slot is unused so the
#: range is symmetric and negation is exact)
_QMAX = 127.0


# ---------------------------------------------------------------------------
# Quantize / dequantize primitives (the ONE rounding rule)
# ---------------------------------------------------------------------------


def quantize_rows_traced(mat: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Traceable core of the per-row symmetric quantizer: ``mat [..., K]
    f32 -> (codes [..., K] int8, scales [...] f32)`` with ``scale =
    amax(|row|)/127`` and ``code = rint(row/scale)`` (round-half-even —
    numpy and XLA agree, which is what keeps the host and device
    quantizers bit-identical). All-zero rows get scale 0 and zero codes,
    so ``dequantize`` reproduces them exactly. Callable from inside
    other traces (the sharded shard_map kernels quantize the resolved
    query rows in-kernel)."""
    amax = jnp.max(jnp.abs(mat), axis=-1)
    # reciprocal MULTIPLY, not division: numpy and XLA round a constant
    # division differently (XLA strength-reduces to a reciprocal), and
    # the host and device quantizers must agree bitwise or the fold-in's
    # host-side re-quantize drifts from the build-time layout
    scales = amax * np.float32(1.0 / _QMAX)
    safe = jnp.where(scales > 0, scales, 1.0)
    codes = jnp.clip(
        jnp.rint(mat / safe[..., None]), -_QMAX, _QMAX
    ).astype(jnp.int8)
    return codes, scales.astype(jnp.float32)


quantize_rows = jax.jit(quantize_rows_traced)


def quantize_table_host(mat: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Numpy mirror of :func:`quantize_rows` (same rounding, same
    zero-row guard) for build-time layout work — sharding a table before
    ``device_put``, and the IVF host mirror's per-lane re-quantize."""
    mat = np.asarray(mat, np.float32)
    amax = np.max(np.abs(mat), axis=-1)
    # same reciprocal-multiply rule as the traced quantizer (bitwise
    # host/device agreement — see quantize_rows_traced)
    scales = (amax * np.float32(1.0 / _QMAX)).astype(np.float32)
    safe = np.where(scales > 0, scales, 1.0)
    codes = np.clip(
        np.rint(mat / safe[..., None]), -_QMAX, _QMAX
    ).astype(np.int8)
    return codes, scales


def quantize_slabs(slabs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize IVF cluster-major slabs ``[nlist, W, K]`` per LANE row:
    ``(codes [nlist, W, K] int8, scales [nlist, W] f32)``. Zero-padded
    lanes quantize to zero codes + zero scale, so the sentinel masking
    in the query kernel is unchanged."""
    return quantize_table_host(np.asarray(slabs, np.float32))


def dequantize(codes, scales):
    """``codes [..., K] * scales [...]`` -> f32 rows; works on numpy and
    jax arrays alike (the backing of a :class:`QuantizedTable` may be
    either)."""
    if isinstance(codes, np.ndarray):
        return codes.astype(np.float32) * np.asarray(scales, np.float32)[
            ..., None
        ]
    return codes.astype(jnp.float32) * scales[..., None]


def quantization_error(mat: np.ndarray, codes, scales) -> dict:
    """Error accounting for the ``/stats.json`` ``quant`` block: how far
    the dequantized table sits from the f32 original. ``maxRelError`` is
    per-row (error relative to the row's own magnitude — the quantity
    the symmetric scheme bounds at ~0.5/127 per element)."""
    mat = np.asarray(mat, np.float32)
    deq = np.asarray(dequantize(np.asarray(codes), np.asarray(scales)))
    err = np.abs(deq - mat)
    amax = np.maximum(np.max(np.abs(mat), axis=-1, keepdims=True), 1e-12)
    return {
        "maxAbsError": round(float(err.max()) if err.size else 0.0, 6),
        "rmsError": round(
            float(np.sqrt(np.mean(err * err))) if err.size else 0.0, 6
        ),
        "maxRelError": round(
            float((err / amax).max()) if err.size else 0.0, 6
        ),
    }


def overfetch(k: int, limit: int) -> int:
    """Coarse-stage candidate count ``k' = max(4k, k+64)``, clamped to
    the catalog — enough head-room that an int8 ranking error at the
    k-th boundary moves a candidate WITHIN the rescored set instead of
    out of it (docs/serving.md discusses tuning)."""
    return max(1, min(int(limit), max(4 * int(k), int(k) + 64)))


def table_bytes_f32(rows: int, rank: int) -> int:
    """What the same table would cost served f32 — the baseline for the
    ``bytesSaved`` stat."""
    return int(rows) * int(rank) * 4


# ---------------------------------------------------------------------------
# The served container
# ---------------------------------------------------------------------------


class QuantizedTable:
    """An int8-served factor table: ``codes [N, K]`` + per-row
    ``scales [N]``, either host numpy or device (possibly sharded) jax
    arrays. Quacks enough like an ndarray for the serving and online
    fold-in paths — ``shape``/``len``, dequantizing ``__getitem__``, and
    ``__array__`` (full dequantize, used by release/re-layout/ANN-build
    paths that need the f32 values once)."""

    #: duck-type marker (isinstance would force the default serving path
    #: to import this module just to check)
    is_quantized = True

    __slots__ = ("codes", "scales")

    def __init__(self, codes, scales):
        self.codes = codes
        self.scales = scales

    @property
    def shape(self) -> tuple:
        return tuple(self.codes.shape)

    @property
    def dtype(self):
        return self.codes.dtype

    def __len__(self) -> int:
        return int(self.codes.shape[0])

    def __getitem__(self, idx):
        """Dequantized f32 row(s) — the fold-in's prior gather and the
        ANN path's query-row resolve both read through here, so only
        the touched rows are ever dequantized."""
        return dequantize(self.codes[idx], self.scales[idx])

    def __array__(self, dtype=None, copy=None):
        full = np.asarray(dequantize(np.asarray(self.codes),
                                     np.asarray(self.scales)))
        return full.astype(dtype) if dtype is not None else full

    @property
    def nbytes_codes(self) -> int:
        return int(self.codes.size) * self.codes.dtype.itemsize

    @property
    def nbytes_scales(self) -> int:
        return int(self.scales.size) * self.scales.dtype.itemsize


def quantize_table(mat) -> QuantizedTable:
    """Quantize a host f32 table and pin codes + scales on the default
    device — the replicated (non-sharded) ``--quantize`` layout. The
    sharded layout lives in
    :func:`predictionio_tpu.parallel.sharding.shard_quantized_table`."""
    codes, scales = quantize_table_host(np.asarray(mat, np.float32))
    return QuantizedTable(jax.device_put(codes), jax.device_put(scales))


# ---------------------------------------------------------------------------
# Two-stage top-K kernels
# ---------------------------------------------------------------------------


def int8_matmul(q_codes: jax.Array, table_codes: jax.Array) -> jax.Array:
    """``q_codes [B, K] @ table_codes.T [K, N]`` accumulated in int32 —
    the coarse scan's GEMM. int8 operands keep the memory traffic at a
    quarter of f32; on TPU the MXU runs this natively (the ALX
    mixed-precision recipe), on CPU XLA lowers it without VNNI so the
    win here is bandwidth (gathers, HBM), not FLOPs."""
    return jnp.matmul(q_codes, table_codes.T, preferred_element_type=jnp.int32)


def _two_stage_topk(qvecs, codes, scales, k: int, kp: int, num_items):
    """Shared trace body: int8 coarse scan -> ``kp`` over-fetch -> f32
    rescore of the gathered candidates -> tie-stable merge. ``num_items``
    is TRACED (the logical row bound; online fold-ins advance it while
    padding keeps the shapes fixed), ``k``/``kp`` static."""
    q_codes, q_scales = quantize_rows_traced(qvecs)
    acc = int8_matmul(q_codes, codes)  # [B, N] int32
    approx = acc.astype(jnp.float32) * q_scales[:, None] * scales[None, :]
    gid = jnp.arange(codes.shape[0], dtype=jnp.int32)
    approx = jnp.where(gid[None, :] < num_items, approx, -jnp.inf)
    _, cand = jax.lax.top_k(approx, kp)  # positions ARE ids (natural order)
    # keep the rescore gathers out of the top_k fusion — same XLA:CPU
    # TopkDecomposer cliff ops/topk.py documents
    cand = jax.lax.optimization_barrier(cand)
    deq = dequantize(codes[cand], scales[cand])  # [B, kp, K] f32 rows
    exact = jnp.einsum("bpk,bk->bp", deq, qvecs)
    valid = cand < num_items
    exact = jnp.where(valid, exact, -jnp.inf)
    ids = jnp.where(valid, cand, num_items)
    return sort_merge_topk(exact, ids, min(int(k), int(kp)))


@functools.partial(jax.jit, static_argnames=("k", "kp"))
def quantized_topk_batch(
    qvecs: jax.Array,
    codes: jax.Array,
    scales: jax.Array,
    k: int,
    kp: int,
    num_items: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Two-stage top-k for a batch of f32 query VECTORS against an
    int8 table: ``([B, k] ids, [B, k] f32 rescored scores)``, descending
    score, ties by ascending id. Rows past ``num_items`` (growth
    padding) carry the ``num_items`` sentinel at ``-inf``."""
    return _two_stage_topk(qvecs, codes, scales, k, kp, num_items)


@functools.partial(jax.jit, static_argnames=("k", "kp"))
def quantized_topk_users(
    user_idx: jax.Array,
    u_codes: jax.Array,
    u_scales: jax.Array,
    i_codes: jax.Array,
    i_scales: jax.Array,
    k: int,
    kp: int,
    num_items: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Two-stage top-k for a batch of USER indices: dequantize the user
    rows on device (the f32 queries the rescore stage uses), then the
    shared body — one dispatch per chunk."""
    q = dequantize(u_codes[user_idx], u_scales[user_idx])
    return _two_stage_topk(q, i_codes, i_scales, k, kp, num_items)


# ---------------------------------------------------------------------------
# Host-facing wrappers + runtime accounting
# ---------------------------------------------------------------------------


class QuantRuntime:
    """Per-model serving state of the quantized tier, attached as
    ``model._pio_quant`` by the algorithms' ``quantize_model_for_serving``
    hooks: the mode, the real byte ledger (codes/scales vs the f32
    baseline), measured quantization error, and thread-safe counters
    for the ``/stats.json`` ``quant`` block — including the MEASURED
    rescore depth (the ``k'`` each bucket actually paid)."""

    def __init__(self, mode: str, bytes_by_dtype: dict, bytes_f32: int,
                 error: dict | None = None):
        self.mode = str(mode)
        self.bytes_by_dtype = dict(bytes_by_dtype)
        self.bytes_f32 = int(bytes_f32)
        self.error = dict(error or {})
        self._lock = threading.Lock()
        self.queries = 0
        self.rescored = 0  # total candidates rescored (sum of k')
        self.rescore_depth_max = 0

    def note(self, n_queries: int, rescore_depth: int) -> None:
        with self._lock:
            self.queries += int(n_queries)
            self.rescored += int(n_queries) * int(rescore_depth)
            self.rescore_depth_max = max(
                self.rescore_depth_max, int(rescore_depth)
            )

    def stats_json(self) -> dict:
        with self._lock:
            q = self.queries
            rescored = self.rescored
            depth_max = self.rescore_depth_max
        total = sum(self.bytes_by_dtype.values())
        return {
            "dtype": self.mode,
            "bytesByDtype": dict(self.bytes_by_dtype),
            "bytesTotal": total,
            "bytesF32Equivalent": self.bytes_f32,
            "bytesSaved": self.bytes_f32 - total,
            "overfetch": "max(4k, k+64)",
            "queries": q,
            "candidatesRescored": rescored,
            "rescoreDepthMax": depth_max,
            "rescoreDepthMean": round(rescored / q, 1) if q else 0.0,
            "quantizationError": dict(self.error),
        }


def run_topk(
    runtime: QuantRuntime,
    user_qt: QuantizedTable,
    item_qt: QuantizedTable,
    user_idx: np.ndarray,
    k: int,
    shards=None,
) -> tuple[jax.Array, jax.Array]:
    """One chunk of the quantized serving path, results left ON DEVICE
    (callers concatenate chunks and cross the link once, the staging
    discipline every other path uses). ``k`` is the caller's (already
    bucketed) fetch size; the over-fetch derives from it so each bucket
    compiles one program. Routes through the shard_map kernel when the
    tables are model-sharded."""
    idx = jnp.asarray(np.asarray(user_idx, np.int32))
    if shards is not None:
        from predictionio_tpu.parallel import sharding

        num_items = int(shards.rows["item"])
        kp = overfetch(k, num_items)
        ids, scores = sharding.sharded_quantized_topk_users(
            idx, user_qt.codes, user_qt.scales,
            item_qt.codes, item_qt.scales,
            k, kp, num_items, shards.mesh,
        )
    else:
        num_items = int(item_qt.shape[0])
        kp = overfetch(k, num_items)
        ids, scores = quantized_topk_users(
            idx, user_qt.codes, user_qt.scales,
            item_qt.codes, item_qt.scales,
            k, kp, jnp.asarray(num_items, jnp.int32),
        )
    runtime.note(len(np.asarray(user_idx)), kp)
    return ids, scores


def topk_users(
    runtime: QuantRuntime,
    user_qt: QuantizedTable,
    item_qt: QuantizedTable,
    user_idx,
    k: int,
    shards=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Top-``k`` for a batch of user indices as numpy — the single-query
    predict path. ``k`` buckets to a power of two (floor 16) so the
    jitted programs compile once per bucket, like every other tier."""
    num_items = (
        int(shards.rows["item"]) if shards is not None
        else int(item_qt.shape[0])
    )
    k = max(1, min(int(k), num_items))
    kb = bucket_k(k, num_items)
    ids, scores = run_topk(
        runtime, user_qt, item_qt, np.asarray(user_idx, np.int32), kb,
        shards=shards,
    )
    ids = np.asarray(ids)
    scores = np.asarray(scores)
    # growth-padding sentinels (id == num_items at -inf) can reach the
    # tail when a shard holds fewer than kb real rows; trim before k
    out_i, out_s = [], []
    for r in range(ids.shape[0]):
        keep = ids[r] < num_items
        out_i.append(ids[r][keep][:k])
        out_s.append(scores[r][keep][:k])
    return np.asarray(out_i), np.asarray(out_s)
