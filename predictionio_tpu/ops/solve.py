"""Batched SPD solves — the ALS hot op, as a Pallas TPU kernel.

XLA's ``lax.linalg.cholesky`` lowers a batched [B,K,K] factorization to a
K-step sequential loop whose every step round-trips the whole batch
through HBM; at the flagship bench shape ([138k,64,64]) that measures
~1.1 s/solve on a v5e chip — ~60% of a whole ALS sweep. The kernel here
keeps each block of rows **resident in VMEM** and runs *blocked*
Gauss-Jordan elimination vectorized across the batch: pivot blocks of
P=8 columns are inverted with a tiny unrolled in-VMEM GJ, and the rank-P
updates run as batched MXU ``dot_general``s at full f32 precision.
Measured 369 ms vs 1133 ms for the XLA Cholesky at the bench shape
(~3x), with max rel err ~2e-5 vs LAPACK f64.

Gauss-Jordan without pivoting is numerically safe here: every ALS normal
matrix is SPD with an ALS-WR ridge (λ·max(n,1)·I), so diagonal pivots
stay bounded away from zero.

No reference analog — MLlib solves on CPU LAPACK
(``org.apache.spark.ml.recommendation.ALS`` CholeskySolver); this is the
TPU-native replacement for that hot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["spd_solve", "gj_solve_pallas", "cholesky_solve"]

#: max rows per kernel block (see _auto_block_rows). 48 is ~15% faster
#: for the STANDALONE kernel at K=64 on v5e, but inside the ALS sweep
#: its ~13 MB VMEM footprint starves the surrounding gather/einsum
#: pipeline and costs ~40% of the whole sweep — 32 is the fused optimum.
_BLOCK_ROWS = 32

#: usable scoped-VMEM budget for the kernel's whole working set.
_VMEM_BUDGET = 14 << 20

#: MEASURED total-VMEM multiplier over the [TB, K, K] A-block bytes: on
#: v5e the compiler reports ~17.1 MB of scoped vmem for TB=64, K=64
#: (A block 1 MB) — the loop-carried copy, rank-P operand copies, b/x,
#: and pipeline double-buffers multiply the block ~17x. The previous
#: heuristic budgeted the A block alone and OOM'd at K>=128 on real
#: hardware (only interpret-mode CI kept it alive).
_KERNEL_VMEM_MULTIPLIER = 17

#: deliberately conservative Mosaic ceiling: K<=128 is validated against
#: real v5e compilation; the VMEM model says blocks up to K~448 would
#: still fit, but those shapes are unvalidated (and tiny 1-3-row blocks
#: give the kernel no batching advantage anyway) — fall back to Cholesky.
_MAX_PALLAS_K = 256


def _auto_block_rows(K: int) -> int:
    """Largest block_rows whose TOTAL kernel working set
    (~_KERNEL_VMEM_MULTIPLIER x the [TB,K,K] A block) fits the VMEM
    budget: 32 at K=64 (capped), 8 at K=128, 3 at K=256 — validated
    against real Mosaic compilation, not just the interpreter."""
    tb = _VMEM_BUDGET // (_KERNEL_VMEM_MULTIPLIER * K * K * 4)
    if tb >= 8:
        tb = tb // 8 * 8
    return max(1, min(_BLOCK_ROWS, tb))

#: pivot-block width: rank-P updates run on the MXU; P=8 keeps the
#: in-VMEM pivot-block inversion tiny while giving the MXU real work.
_PIVOT_BLOCK = 8

_HI = jax.lax.Precision.HIGHEST


def cholesky_solve(A: jax.Array, b: jax.Array) -> jax.Array:
    """Batched SPD solve via XLA's Cholesky: A [.., K, K], b [.., K].
    The portable path (CPU tests, meshes) — slow on TPU at large batch."""
    L = jax.lax.linalg.cholesky(A)
    x = jax.lax.linalg.triangular_solve(L, b[..., None], left_side=True, lower=True)
    x = jax.lax.linalg.triangular_solve(
        L, x, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]


def _bdot(a: jax.Array, b: jax.Array) -> jax.Array:
    """Batched matmul [TB,m,k]@[TB,k,n] at full f32 (bf16 MXU passes lose
    ~1e-2 per rank-P update — measured 0.35 rel err over a 64-col sweep)."""
    return jax.lax.dot_general(
        a, b, (((2,), (1,)), ((0,), (0,))), precision=_HI,
        preferred_element_type=jnp.float32,
    )


def _gj_kernel(A_ref, b_ref, x_ref, *, pivot_block: int):
    """Blocked Gauss-Jordan solve of one [TB, K, K] block, fully in VMEM.

    Per pivot block: invert the [TB,P,P] diagonal block with an unrolled
    masked GJ (VPU), then eliminate its P columns from every row with two
    batched MXU matmuls. After all K/P blocks A is the identity and b
    holds the solution. All indices are static (Python-unrolled), so no
    dynamic-gather lowering is involved.
    """
    P = pivot_block
    A = A_ref[:]  # [TB, K, K]
    b = b_ref[:]  # [TB, K]
    K = A.shape[-1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, P), 1)
    for blk in range(K // P):
        s = blk * P
        R = A[:, s : s + P, :]  # pivot rows [TB,P,K]
        D = R[:, :, s : s + P]  # diagonal block [TB,P,P]
        rb = b[:, s : s + P]  # [TB,P]
        # --- invert D: P-step masked GJ carrying the inverse ------------
        Di = jnp.broadcast_to(jnp.eye(P, dtype=A.dtype), D.shape)
        M = D
        for j in range(P):
            sel = (iota == j).astype(A.dtype)  # [1,P] one-hot pivot
            prow = jnp.sum(M * sel[:, :, None], 1)  # [TB,P]
            irow = jnp.sum(Di * sel[:, :, None], 1)
            d = jnp.sum(prow * sel, 1)  # [TB]
            inv = 1.0 / d
            prow_s = prow * inv[:, None]
            irow_s = irow * inv[:, None]
            colj = jnp.sum(M * sel[:, None, :], 2)  # [TB,P]
            f = colj * (1.0 - sel)
            M = M - f[:, :, None] * prow_s[:, None, :]
            Di = Di - f[:, :, None] * irow_s[:, None, :]
            M = M * (1.0 - sel[:, :, None]) + sel[:, :, None] * prow_s[:, None, :]
            Di = Di * (1.0 - sel[:, :, None]) + sel[:, :, None] * irow_s[:, None, :]
        # --- rank-P elimination of the pivot columns from all rows ------
        C = A[:, :, s : s + P]  # [TB,K,P]
        F = _bdot(C, Di)
        # pivot rows need G = I - Di so they land on Di @ R (row-reduced
        # form); all other rows use F
        parts = []
        if s:
            parts.append(F[:, :s])
        parts.append(F[:, s : s + P] - Di)
        if s + P < K:
            parts.append(F[:, s + P :])
        G = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
        A = A - _bdot(G, R)
        b = b - _bdot(G, rb[..., None])[..., 0]
    x_ref[:] = b  # A reduced to I: b holds the solution


@functools.partial(
    jax.jit, static_argnames=("block_rows", "pivot_block", "interpret")
)
def gj_solve_pallas(
    A: jax.Array,  # [B, K, K]
    b: jax.Array,  # [B, K]
    block_rows: int | None = None,
    pivot_block: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Batched SPD solve, blocked Gauss-Jordan in VMEM. B is padded to a
    multiple of ``block_rows`` (default: auto-sized to the VMEM budget
    for this K); padding rows are identity systems (solve to 0); K must
    be a multiple of ``pivot_block`` (default ``_PIVOT_BLOCK``, read at
    call time so measurements can tune the module knobs)."""
    B, K = b.shape
    if pivot_block is None:
        pivot_block = _PIVOT_BLOCK
    if K % pivot_block:
        raise ValueError(f"K={K} must be a multiple of pivot_block={pivot_block}")
    if block_rows is None:
        block_rows = _auto_block_rows(K)
    n_pad = -(-B // block_rows) * block_rows - B
    if n_pad:
        eye = jnp.broadcast_to(jnp.eye(K, dtype=A.dtype), (n_pad, K, K))
        A = jnp.concatenate([A, eye], axis=0)
        b = jnp.concatenate([b, jnp.zeros((n_pad, K), b.dtype)], axis=0)
    out = pl.pallas_call(
        functools.partial(_gj_kernel, pivot_block=pivot_block),
        grid=(A.shape[0] // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, K, K), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, K), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, K), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((A.shape[0], K), b.dtype),
        interpret=interpret,
    )(A, b)
    return out[:B]


def spd_solve(A: jax.Array, b: jax.Array, method: str = "cholesky") -> jax.Array:
    """Dispatch: ``method`` in {"cholesky", "pallas", "pallas_interpret"}.

    Callers pick "pallas" on a real TPU backend (Mosaic-lowered);
    "pallas_interpret" runs the same kernel logic on CPU for tests;
    "cholesky" is the portable XLA path. K not divisible by the pivot
    block falls back to Cholesky (rank is usually a multiple of 8 —
    ``ALSConfig.rank_pad_multiple`` exists to make it one), as does
    K > 256, the validated Mosaic ceiling (see _MAX_PALLAS_K).
    """
    if method in ("pallas", "pallas_interpret"):
        K = A.shape[-1]
        if K % _PIVOT_BLOCK == 0 and K <= _MAX_PALLAS_K:
            A2 = A.reshape((-1, K, K))
            b2 = b.reshape((-1, K))
            x = gj_solve_pallas(A2, b2, interpret=(method == "pallas_interpret"))
            return x.reshape(b.shape)
        method = "cholesky"
    if method == "cholesky":
        return cholesky_solve(A, b)
    raise ValueError(
        f"spd_solve method must be 'cholesky', 'pallas' or 'pallas_interpret', got {method!r}"
    )
