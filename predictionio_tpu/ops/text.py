"""Text featurization: hashing vectorizer + TF-IDF.

Replaces the Spark MLlib ``HashingTF``/``IDF`` pair used by the
reference's Text-Classification template. The hashing trick keeps the
feature space static-shape (a jit requirement) and vocabulary-free; IDF
weights are a single host pass. Tokenization is lowercase word-splitting
with an optional stopword set, matching the template's preparator.
"""

from __future__ import annotations

import re
import zlib
from typing import Iterable, NamedTuple, Sequence

import numpy as np

__all__ = ["tokenize", "HashingTfIdf", "fit_tfidf"]

_TOKEN_RE = re.compile(r"[a-z0-9']+")


def tokenize(text: str, stopwords: frozenset = frozenset()) -> list[str]:
    return [t for t in _TOKEN_RE.findall(text.lower()) if t not in stopwords]


def _bucket(token: str, num_features: int) -> int:
    return zlib.crc32(token.encode()) % num_features


class HashingTfIdf(NamedTuple):
    """Fitted featurizer state: idf weights + config."""

    idf: np.ndarray  # [F]
    num_features: int
    stopwords: frozenset

    def transform(self, texts: Sequence[str]) -> np.ndarray:
        """texts -> [N, F] tf-idf matrix (dense; F is the hash dim)."""
        out = np.zeros((len(texts), self.num_features), dtype=np.float32)
        for i, text in enumerate(texts):
            for tok in tokenize(text, self.stopwords):
                out[i, _bucket(tok, self.num_features)] += 1.0
        return out * self.idf


def fit_tfidf(
    texts: Iterable[str],
    num_features: int = 4096,
    stopwords: Iterable[str] = (),
) -> HashingTfIdf:
    """Fit IDF over a corpus (parity: ``IDF.fit``): smoothed
    ``log((N+1)/(df+1)) + 1``."""
    stop = frozenset(stopwords)
    df = np.zeros(num_features, dtype=np.float64)
    n_docs = 0
    for text in texts:
        n_docs += 1
        seen = {_bucket(t, num_features) for t in tokenize(text, stop)}
        for b in seen:
            df[b] += 1.0
    idf = np.log((n_docs + 1.0) / (df + 1.0)) + 1.0
    return HashingTfIdf(idf=idf.astype(np.float32), num_features=num_features, stopwords=stop)
