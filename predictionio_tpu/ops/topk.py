"""Shared top-K selection kernels — one tie-break rule everywhere.

Every ranked surface in the product (recommendation / similarproduct /
twotower / ecommerce serving, ``pio batchpredict``, the IVF retrieval
merge) must order candidates identically, or the exact and approximate
paths diverge on tied scores and host/device results stop being
comparable. The rule is the one ``jax.lax.top_k`` implements natively:

    **descending score, ties broken by ascending item index.**

Three entry points share it:

* :func:`top_k_scores` — jitted ``lax.top_k`` over naturally-ordered
  scores (the exact device path; ties -> ascending position is the
  operator's own guarantee).
* :func:`top_k_permuted` — jitted tie-stable top-K when the score axis
  is NOT in item-id order (the IVF cluster-major merge): a two-key
  ``lax.sort`` on ``(-score, id)`` reproduces the exact rule in the
  *original id space*, which is what makes ``nprobe == nlist`` IVF
  bit-identical to exact retrieval including tie order.
* :func:`top_k_host` — the numpy mirror (argpartition + lexsort) used by
  the host serving paths, so host and device agree wherever the float
  scores do.

Before this module each template carried its own argsort-based variant;
similarproduct/ecommerce used ``argsort(...)[::-1]``, whose reversal
orders TIES by descending index — silently different from every other
path. Hoisting the helper is what fixed that.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "bucket_k",
    "top_k_scores",
    "top_k_permuted",
    "sort_merge_topk",
    "top_k_host",
]


def bucket_k(k: int, n_items: int, floor: int = 16) -> int:
    """The ONE pow2 fetch-size bucket every serving tier shares: ``k``
    rounds up to a power of two (``floor`` minimum), capped at the
    catalog. Jitted kernels take the bucketed value as their static
    ``k`` so the compile count is the bucket count, never the request
    cardinality — piolint PIO306 recognizes this helper (its name
    contains "bucket") and ``compile-budget.json``'s entries cite its
    math; changing the floor or rounding here moves every tier's bucket
    set at once instead of drifting per copy."""
    return min(int(n_items), max(floor, 1 << (max(1, int(k)) - 1).bit_length()))


def sort_merge_topk(
    scores: jax.Array, ids: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    """Tie-stable top-k of an (unordered) candidate list via ONE two-key
    ``lax.sort`` on ``(-score, id)`` — exact for ANY id width, at
    O(n log n) per row. This is :func:`top_k_permuted`'s ``big_ids``
    branch, shared as the cross-shard candidate reduce of the sharded
    serving kernels (``parallel/sharding.py``): there the candidate list
    is only ``S·k`` wide, so the sort is negligible — and the
    barrier-guarded fast path must not run, because XLA:CPU's
    TopkDecomposer aborts on a barrier-fed ``top_k`` under manual
    partitioning (shard_map). Not jitted standalone: it only ever runs
    inside an already-traced kernel."""
    neg, sid = jax.lax.sort((-scores, ids), num_keys=2)
    return sid[..., :k], -neg[..., :k]


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_scores(scores: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Top-k of ``scores`` along the last axis: ``(indices, values)``.
    Ties break toward the lower index (``lax.top_k``'s contract)."""
    values, indices = jax.lax.top_k(scores, k)
    return indices, values


@functools.partial(jax.jit, static_argnames=("k", "big_ids"))
def top_k_permuted(
    scores: jax.Array, ids: jax.Array, k: int, big_ids: bool = False
) -> tuple[jax.Array, jax.Array]:
    """Tie-stable top-k when position != item id: ``scores[..., n]``
    belongs to item ``ids[..., n]`` (any permutation/padding of the id
    space). Returns ``(ids [..., k], scores [..., k])`` ordered by
    descending score, ties by ascending id — the same ranking
    :func:`top_k_scores` produces on the naturally-ordered axis, which
    is what lets the IVF merge reproduce exact top-K bit-identically
    when every cluster is probed.

    A plain ``lax.top_k`` on the scores would break ties by *candidate
    position* (cluster-major order, not id order), and a full two-key
    ``lax.sort`` is O(n log n) per row — measured SLOWER than exact
    full-catalog scoring on CPU at bench shapes (and any non-trivial
    selection pass misses XLA:CPU's fast f32 TopK path by ~10-20x). The
    hot path therefore runs exactly ONE fast f32 ``top_k`` plus an
    O(k log k) sort, and the expensive exact-tie machinery hides behind
    a ``lax.cond`` that only executes when ties actually bite:

    1. ``lax.top_k`` over the (f32) scores selects by exact float order
       — but resolves ties by position.
    2. Position-ties only pick the wrong CANDIDATE SET when ties at the
       k-th-value boundary straddle it (ties strictly above select both
       members either way). A cheap reduce detects that — equality of
       the tied-at-boundary counts inside and across the whole row —
       and the repair branch runs ONLY then: a second ``top_k`` over
       ``-id`` (masked to boundary-tied candidates; ids are exact in
       f32 below 2^24) yields the tied candidates in ascending-id
       order, and pass 1's tie slots are reassigned from it.
    3. The k winners (gathered ids + original scores, bit-exact) are
       ordered by a two-key sort on ``(-score, id)`` — k elements per
       row, negligible next to the selection.

    ``big_ids=True`` (required when ids can reach 2^24, where f32
    spacing exceeds 1) keeps exactness through a full two-key sort —
    correct for any id, at the O(n log n) cost."""
    if big_ids:
        return sort_merge_topk(scores, ids, k)
    t, pos = jax.lax.top_k(scores, k)
    # the barrier keeps downstream slices/compares out of the top_k's
    # fusion: XLA:CPU's fast TopK rewrite bails when the sort's results
    # are consumed by a fused slice, silently falling back to a ~10x
    # slower generic sort (measured; same story for the repair branch)
    t, pos = jax.lax.optimization_barrier((t, pos))
    kth = t[..., -1:]

    def repair(_):
        is_strict = t > kth
        tie_key = jnp.where(scores == kth, -ids.astype(scores.dtype), -jnp.inf)
        tie_pos = jax.lax.optimization_barrier(jax.lax.top_k(tie_key, k))[1]
        # the j-th non-strict slot takes the j-th smallest-id boundary tie
        tie_rank = jnp.cumsum((~is_strict).astype(jnp.int32), axis=-1) - 1
        return jnp.where(
            is_strict,
            pos,
            jnp.take_along_axis(tie_pos, jnp.maximum(tie_rank, 0), axis=-1),
        )

    boundary_ties_bite = jnp.any(
        jnp.sum(scores == kth, axis=-1) > jnp.sum(t == kth, axis=-1)
    )
    final_pos = jax.lax.cond(boundary_ties_bite, repair, lambda _: pos, None)
    sel_ids = jnp.take_along_axis(ids, final_pos, axis=-1)
    sel_scores = jnp.take_along_axis(scores, final_pos, axis=-1)
    neg, out_ids = jax.lax.sort((-sel_scores, sel_ids), num_keys=2)
    return out_ids, -neg


def top_k_host(scores: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
    """Numpy top-k over the last axis of a 1-D or 2-D score array with
    the shared tie rule; returns ``(indices, values)``. ``argpartition``
    keeps it O(n + k log k) per row — the host serving path at catalog
    sizes below ~10^6 items."""
    k = min(int(k), scores.shape[-1])
    if k <= 0:
        shape = scores.shape[:-1] + (0,)
        return np.zeros(shape, np.int64), np.zeros(shape, scores.dtype)
    if scores.ndim == 1:
        part = np.argpartition(scores, -k)[-k:]
        top = part[np.lexsort((part, -scores[part]))]
        return top, scores[top]
    part = np.argpartition(scores, -k, axis=-1)[..., -k:]
    vals = np.take_along_axis(scores, part, axis=-1)
    # per-row lexsort: primary key descending value, secondary ascending
    # original index — np.lexsort's last key is primary
    order = np.lexsort((part, -vals), axis=-1)
    top = np.take_along_axis(part, order, axis=-1)
    return top, np.take_along_axis(scores, top, axis=-1)
