"""Two-tower retrieval, TPU-native (the DLRM/two-tower stretch family —
BASELINE.md configs[4]; no reference counterpart exists: PredictionIO has
no deep-retrieval template, so this is parity-plus).

TPU-first design:

* **Sharded embedding tables (EP)** — the user and item tables are
  sharded row-wise over the mesh's ``model`` axis. Lookups use the same
  shard-local-gather + psum pattern as the ALS sweep
  (:func:`predictionio_tpu.ops.als._gram_chunk`): under ``shard_map``
  each device gathers only ids living in its local shard (others masked
  to zero) and the partial embeddings psum over ``model`` — the
  catalog-sized tables never replicate, so table size scales with the
  mesh. The pattern is differentiable: the gather's VJP is a
  scatter-add into the LOCAL shard, so gradients stay sharded too.
* **Data-parallel batches** — interaction batches shard over ``data``;
  the in-batch logits matrix psums gradients across the batch via
  GSPMD's normal propagation.
* **In-batch sampled softmax** — each positive (u, i) pair treats the
  other items in the batch as negatives (symmetric u→i and i→u cross
  entropy). Standard two-tower training; duplicate items inside a batch
  act as false negatives, acceptable at the batch sizes used here.
* **Static shapes** — interactions are padded to a multiple of the
  batch size and each step ``dynamic_slice``s its batch from the
  device-resident permutation, so one compiled step serves the whole
  run.
"""

from __future__ import annotations

import dataclasses
import functools
from types import MappingProxyType
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from predictionio_tpu.ops.compat import reshard, shard_map

__all__ = [
    "TwoTowerConfig",
    "TwoTowerModel",
    "sharded_embedding_lookup",
    "train_two_tower",
]


@dataclasses.dataclass(frozen=True)
class TwoTowerConfig:
    dim: int = 32
    batch_size: int = 256
    epochs: int = 5
    learning_rate: float = 0.05
    temperature: float = 0.1
    seed: int = 0
    #: keep a loss-history entry every N steps (losses are computed every
    #: step on device and read back once per epoch)
    log_every: int = 50
    #: matmul input dtype for the in-batch logits ("bfloat16" rides the
    #: MXU at full rate with fp32 accumulation — the TPU-native default;
    #: "float32" for bit-for-bit comparisons)
    gemm_dtype: str = "bfloat16"
    #: the flash-style fused softmax-CE kernel (ops/fused_ce.py): "auto"
    #: uses it on single-device TPU runs with supported shapes, "off"
    #: forces the XLA path, "interpret" runs the kernel in interpreter
    #: mode (CPU tests). The [B, B] logits never touch HBM with it on.
    fused_ce: str = "auto"


class TwoTowerModel(NamedTuple):
    """Serving-ready tower outputs: dot(user_vec, item_vec) ranks items.
    Rows are L2-normalized, so scores are cosine similarities."""

    user_vecs: Any  # [U, D]
    item_vecs: Any  # [I, D]
    loss_history: tuple  # ((step, loss), ...)
    #: phase wall-clock: ingest (interaction upload), train (epoch loop),
    #: finalize (replicate + host readback). On a tunneled chip the
    #: ingest/finalize transfers dominate at small model sizes — benches
    #: must not book them against the training loop. Immutable default:
    #: a shared mutable {} would alias across default-built instances.
    timings: Any = MappingProxyType({})


def sharded_embedding_lookup(
    table: jax.Array,  # [N_pad, D], sharded over model axis rows
    ids: jax.Array,  # [B] int32
    mesh: Mesh | None,
    data_axis: str | None = "data",
    model_axis: str | None = "model",
) -> jax.Array:
    """Differentiable embedding lookup from a model-sharded table.

    Each device gathers only the rows of its local shard (out-of-shard
    ids contribute zero) and the partials psum over ``model`` — the
    table never materializes replicated, and the VJP scatter-adds into
    the local shard so gradients stay sharded (VERDICT r2 item 10: the
    sharded-embedding consumer of the ALS chunked-gather machinery)."""
    if mesh is None or model_axis is None or model_axis not in mesh.shape:
        return table[ids]
    S = int(mesh.shape[model_axis])
    if table.shape[0] % S:
        # a floored rps would make trailing rows unreachable and return
        # silently-zero embeddings for their ids
        raise ValueError(
            f"table rows ({table.shape[0]}) must divide the model axis ({S})"
        )
    rps = table.shape[0] // S

    def local(tbl, ids_l):
        me = jax.lax.axis_index(model_axis)
        lidx = ids_l - me * rps
        inr = (lidx >= 0) & (lidx < rps)
        e = tbl[jnp.where(inr, lidx, 0)] * inr[:, None].astype(tbl.dtype)
        return jax.lax.psum(e, model_axis)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(PartitionSpec(model_axis, None), PartitionSpec(data_axis)),
        out_specs=PartitionSpec(data_axis, None),
    )(table, ids)


@functools.lru_cache(maxsize=16)
def _epoch_program(
    mesh: Mesh | None,
    data_axis: str | None,
    model_axis: str | None,
    B: int,
    n_pad: int,
    steps_per_epoch: int,
    learning_rate: float,
    inv_temp: float,
    gemm_dtype_name: str,
    fused_ce_mode: str,
):
    """Build (and cache) the jitted per-epoch training program.

    The program is keyed on everything that shapes its trace, so repeat
    trains in one process — warm retrains, evaluation sweeps, the bench's
    warm-up/timed pair — reuse the SAME jit object instead of re-tracing
    a fresh closure each call (re-tracing the full-epoch scan costs ~1 s
    even with the persistent compile cache hitting)."""
    import jax

    gemm_dtype = jnp.bfloat16 if gemm_dtype_name == "bfloat16" else jnp.float32
    from predictionio_tpu.ops.fused_ce import (
        fused_ce_supported,
        fused_inbatch_ce,
    )

    # strict platform check: the axon tunnel backend also reports "tpu";
    # anything else (gpu, metal, ...) must take the XLA fallback rather
    # than attempt a Mosaic lowering
    on_tpu = jax.devices()[0].platform == "tpu"
    use_fused_base = (
        mesh is None  # in-batch negatives are global; mesh path stays XLA
        and gemm_dtype == jnp.bfloat16  # the kernel's GEMMs are bf16
        and (
            fused_ce_mode == "interpret"
            or (fused_ce_mode == "auto" and on_tpu)
        )
    )
    fused_interpret = fused_ce_mode == "interpret"
    rep_sharding = (
        None if mesh is None else NamedSharding(mesh, PartitionSpec())
    )
    tx = optax.adam(learning_rate)

    def _logits(a, b):
        # bf16 operands ride the MXU at full rate; accumulation stays
        # fp32 (preferred_element_type), so the softmax sees fp32 logits
        return (
            jnp.matmul(
                a.astype(gemm_dtype),
                b.astype(gemm_dtype).T,
                preferred_element_type=jnp.float32,
            )
            * inv_temp
        )

    def loss_fn(p, u_ids, i_ids):
        ue = sharded_embedding_lookup(p["user"], u_ids, mesh, data_axis, model_axis)
        ie = sharded_embedding_lookup(p["item"], i_ids, mesh, data_axis, model_axis)
        ue = ue / (jnp.linalg.norm(ue, axis=-1, keepdims=True) + 1e-8)
        ie = ie / (jnp.linalg.norm(ie, axis=-1, keepdims=True) + 1e-8)
        if use_fused_base and fused_ce_supported(B, ue.shape[-1], inv_temp):
            return fused_inbatch_ce(ue, ie, inv_temp, fused_interpret)
        labels = jnp.arange(B)
        if mesh is not None:
            # in-batch logits need every negative on every device: keep
            # the LEFT side batch-sharded and replicate the right side (a
            # tiny [B, D] all-gather) — [B@data, B@data] is not a legal
            # layout, and labels must shard like the logits rows
            rep = NamedSharding(mesh, PartitionSpec(None, None))
            ue_r = reshard(ue, rep)
            ie_r = reshard(ie, rep)
            labels = reshard(
                labels, NamedSharding(mesh, PartitionSpec(data_axis))
            )
        else:
            ue_r, ie_r = ue, ie
        # symmetric in-batch softmax: user->item and item->user
        l1 = optax.softmax_cross_entropy_with_integer_labels(
            _logits(ue, ie_r), labels
        )
        l2 = optax.softmax_cross_entropy_with_integer_labels(
            _logits(ie, ue_r), labels
        )
        return 0.5 * (l1.mean() + l2.mean())

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_epoch(p, o, epoch, r, c, perm_key):
        """ONE device program per epoch: permutation gather + a lax.scan
        over every step. A step-per-dispatch loop pays the host->device
        round trip per step — through a tunneled/remote accelerator that
        overhead alone caps throughput regardless of batch size. Returns
        per-step losses (read back once per epoch).

        Fresh permutation per epoch: in-batch softmax draws its negatives
        from the batch, so replaying one fixed batching would freeze
        every positive's negative set for the whole run."""
        perm = jax.random.permutation(jax.random.fold_in(perm_key, epoch), n_pad)
        r_all, c_all = r[perm], c[perm]
        if rep_sharding is not None:
            r_all = reshard(r_all, rep_sharding)
            c_all = reshard(c_all, rep_sharding)

        def body(carry, step):
            p, o = carry
            off = step * B
            u_ids = jax.lax.dynamic_slice(r_all, (off,), (B,))
            i_ids = jax.lax.dynamic_slice(c_all, (off,), (B,))
            if mesh is not None:
                # reshard, not with_sharding_constraint: make_mesh axes
                # are Explicit in current jax, and the batch must be
                # data-sharded before entering the shard_map lookups
                bspec = NamedSharding(mesh, PartitionSpec(data_axis))
                u_ids = reshard(u_ids, bspec)
                i_ids = reshard(i_ids, bspec)
            loss, grads = jax.value_and_grad(loss_fn)(p, u_ids, i_ids)
            updates, o = tx.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), loss

        (p, o), losses = jax.lax.scan(
            body, (p, o), jnp.arange(steps_per_epoch)
        )
        return p, o, losses

    return train_epoch, tx


def _pad_rows(n: int, mult: int) -> int:
    return -(-n // mult) * mult


def train_two_tower(
    rows: np.ndarray,
    cols: np.ndarray,
    num_users: int,
    num_items: int,
    config: TwoTowerConfig = TwoTowerConfig(),
    mesh: Mesh | None = None,
    data_axis: str = "data",
    model_axis: str = "model",
    init_user: np.ndarray | None = None,
    init_item: np.ndarray | None = None,
) -> TwoTowerModel:
    """Train user/item towers from implicit interaction pairs.

    ``rows[i]``/``cols[i]`` is one (user, item) interaction. Returns
    L2-normalized tower vectors as replicated host-readable arrays.
    ``init_user``/``init_item`` ([num_users, D] / [num_items, D]) seed
    the embedding tables (warm retrain carry-over); rows beyond them
    (shard padding) keep the random draw.
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    if rows.shape != cols.shape or rows.ndim != 1:
        raise ValueError("rows/cols must be equal-length 1-D arrays")
    if rows.size == 0:
        raise ValueError("two-tower training needs at least one interaction")
    if rows.min() < 0 or rows.max() >= num_users:
        raise ValueError("row index out of range")
    if cols.min() < 0 or cols.max() >= num_items:
        raise ValueError("column index out of range")

    S = 1
    if mesh is not None and model_axis in mesh.shape:
        S = int(mesh.shape[model_axis])
    elif mesh is not None:
        model_axis = None
    D = config.dim
    n_u = _pad_rows(num_users, S)
    n_i = _pad_rows(num_items, S)

    B = config.batch_size
    if mesh is not None:
        d_size = int(mesh.shape.get(data_axis, 1))
        B = _pad_rows(B, d_size)

    key = jax.random.PRNGKey(config.seed)
    k_u, k_i, k_perm = jax.random.split(key, 3)
    scale = 1.0 / np.sqrt(D)

    def _draw(k, n_real, n_padded):
        # draw at the canonical (n_real, D) shape and zero-pad the shard
        # rows — the jax PRNG keys its stream on the SHAPE, so drawing at
        # the padded shape would give a mesh whose model axis does not
        # divide the catalog a different init (hence a different trained
        # model) than single-device. Same rule as the ALS factor tables.
        base = jax.random.normal(k, (n_real, D), jnp.float32) * scale
        return jnp.pad(base, ((0, n_padded - n_real), (0, 0)))

    params = {
        "user": _draw(k_u, num_users, n_u),
        "item": _draw(k_i, num_items, n_i),
    }
    for name, init, n_real in (
        ("user", init_user, num_users), ("item", init_item, num_items)
    ):
        if init is None:
            continue
        init = np.asarray(init, np.float32)
        if init.shape != (n_real, D):
            raise ValueError(
                f"init_{name} must have shape {(n_real, D)}, got {init.shape}"
            )
        base = np.array(params[name])  # copy: asarray of a jax array is read-only
        base[:n_real] = init
        params[name] = jnp.asarray(base)
    if mesh is not None:
        spec = (
            PartitionSpec(model_axis, None)
            if model_axis
            else PartitionSpec(None, None)
        )
        sharded = NamedSharding(mesh, spec)
        params = {k: jax.device_put(v, sharded) for k, v in params.items()}

    # pad interactions to a whole number of batches by resampling real
    # pairs (padding with a sentinel would inject a fake item)
    nnz = rows.size
    n_pad = _pad_rows(nnz, B)
    reps = np.arange(n_pad) % nnz
    rep_sharding = None if mesh is None else NamedSharding(mesh, PartitionSpec())

    # upload the padded interaction set ONCE; every epoch's shuffle is a
    # device-side permutation gather (the previous per-epoch host
    # permutation + re-upload was a full-dataset transfer stall per epoch
    # — VERDICT r3 weak #6)
    import time as _time

    t_ingest = _time.perf_counter()
    r_base = jnp.asarray(rows[reps].astype(np.int32))
    c_base = jnp.asarray(cols[reps].astype(np.int32))
    if rep_sharding is not None:
        r_base = jax.device_put(r_base, rep_sharding)
        c_base = jax.device_put(c_base, rep_sharding)
    int(c_base[-1])  # hard sync: the upload is complete, not just enqueued
    t_ingest = _time.perf_counter() - t_ingest

    steps_per_epoch = n_pad // B
    inv_temp = 1.0 / config.temperature
    train_epoch, tx = _epoch_program(
        mesh, data_axis, model_axis, B, n_pad, steps_per_epoch,
        config.learning_rate, inv_temp, config.gemm_dtype, config.fused_ce,
    )
    opt_state = tx.init(params)

    history = []
    total_steps = config.epochs * steps_per_epoch
    t_train = _time.perf_counter()
    for epoch in range(config.epochs):
        params, opt_state, losses = train_epoch(
            params, opt_state, jnp.int32(epoch), r_base, c_base, k_perm
        )
        losses_np = np.asarray(losses)  # one readback per epoch
        for i, loss in enumerate(losses_np):
            step = epoch * steps_per_epoch + i
            if step % config.log_every == 0 or step == total_steps - 1:
                history.append((step, float(loss)))
    t_train = _time.perf_counter() - t_train

    def _finalize(p):
        u = p["user"] / (jnp.linalg.norm(p["user"], axis=-1, keepdims=True) + 1e-8)
        v = p["item"] / (jnp.linalg.norm(p["item"], axis=-1, keepdims=True) + 1e-8)
        return u, v

    t_final = _time.perf_counter()
    if mesh is not None and jax.process_count() > 1:
        # multi-host: replicate before the host reads the (possibly
        # model-sharded) tables; slicing off padding happens host-side
        u, v = jax.jit(
            _finalize, out_shardings=NamedSharding(mesh, PartitionSpec())
        )(params)
    else:
        # single host: keep the tables in their (possibly model-sharded)
        # layout and let np.asarray assemble per-device shards on HOST —
        # forcing replication here materialized the full tables on every
        # device at the finish line, the lone O(catalog)-per-device step
        # of an otherwise O(catalog / model_axis) training run
        u, v = jax.jit(_finalize)(params)
    user_vecs = np.asarray(u)[:num_users]
    item_vecs = np.asarray(v)[:num_items]
    t_final = _time.perf_counter() - t_final
    return TwoTowerModel(
        user_vecs=user_vecs,
        item_vecs=item_vecs,
        loss_history=tuple(history),
        timings={
            "ingest_seconds": round(t_ingest, 4),
            "train_seconds": round(t_train, 4),
            "finalize_seconds": round(t_final, 4),
        },
    )
