"""Two-tower retrieval, TPU-native (the DLRM/two-tower stretch family —
BASELINE.md configs[4]; no reference counterpart exists: PredictionIO has
no deep-retrieval template, so this is parity-plus).

TPU-first design:

* **Sharded embedding tables (EP)** — the user and item tables are
  sharded row-wise over the mesh's ``model`` axis. Lookups use the same
  shard-local-gather + psum pattern as the ALS sweep
  (:func:`predictionio_tpu.ops.als._gram_chunk`): under ``shard_map``
  each device gathers only ids living in its local shard (others masked
  to zero) and the partial embeddings psum over ``model`` — the
  catalog-sized tables never replicate, so table size scales with the
  mesh. The pattern is differentiable: the gather's VJP is a
  scatter-add into the LOCAL shard, so gradients stay sharded too.
* **Data-parallel batches** — interaction batches shard over ``data``;
  the in-batch logits matrix psums gradients across the batch via
  GSPMD's normal propagation.
* **In-batch sampled softmax** — each positive (u, i) pair treats the
  other items in the batch as negatives (symmetric u→i and i→u cross
  entropy). Standard two-tower training; duplicate items inside a batch
  act as false negatives, acceptable at the batch sizes used here.
* **Static shapes** — interactions are padded to a multiple of the
  batch size and each step ``dynamic_slice``s its batch from the
  device-resident permutation, so one compiled step serves the whole
  run.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "TwoTowerConfig",
    "TwoTowerModel",
    "sharded_embedding_lookup",
    "train_two_tower",
]


@dataclasses.dataclass(frozen=True)
class TwoTowerConfig:
    dim: int = 32
    batch_size: int = 256
    epochs: int = 5
    learning_rate: float = 0.05
    temperature: float = 0.1
    seed: int = 0
    #: report the training loss every N steps (host readback)
    log_every: int = 50


class TwoTowerModel(NamedTuple):
    """Serving-ready tower outputs: dot(user_vec, item_vec) ranks items.
    Rows are L2-normalized, so scores are cosine similarities."""

    user_vecs: Any  # [U, D]
    item_vecs: Any  # [I, D]
    loss_history: tuple  # ((step, loss), ...)


def sharded_embedding_lookup(
    table: jax.Array,  # [N_pad, D], sharded over model axis rows
    ids: jax.Array,  # [B] int32
    mesh: Mesh | None,
    data_axis: str | None = "data",
    model_axis: str | None = "model",
) -> jax.Array:
    """Differentiable embedding lookup from a model-sharded table.

    Each device gathers only the rows of its local shard (out-of-shard
    ids contribute zero) and the partials psum over ``model`` — the
    table never materializes replicated, and the VJP scatter-adds into
    the local shard so gradients stay sharded (VERDICT r2 item 10: the
    sharded-embedding consumer of the ALS chunked-gather machinery)."""
    if mesh is None or model_axis is None or model_axis not in mesh.shape:
        return table[ids]
    S = int(mesh.shape[model_axis])
    if table.shape[0] % S:
        # a floored rps would make trailing rows unreachable and return
        # silently-zero embeddings for their ids
        raise ValueError(
            f"table rows ({table.shape[0]}) must divide the model axis ({S})"
        )
    rps = table.shape[0] // S

    def local(tbl, ids_l):
        me = jax.lax.axis_index(model_axis)
        lidx = ids_l - me * rps
        inr = (lidx >= 0) & (lidx < rps)
        e = tbl[jnp.where(inr, lidx, 0)] * inr[:, None].astype(tbl.dtype)
        return jax.lax.psum(e, model_axis)

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(PartitionSpec(model_axis, None), PartitionSpec(data_axis)),
        out_specs=PartitionSpec(data_axis, None),
    )(table, ids)


def _pad_rows(n: int, mult: int) -> int:
    return -(-n // mult) * mult


def train_two_tower(
    rows: np.ndarray,
    cols: np.ndarray,
    num_users: int,
    num_items: int,
    config: TwoTowerConfig = TwoTowerConfig(),
    mesh: Mesh | None = None,
    data_axis: str = "data",
    model_axis: str = "model",
) -> TwoTowerModel:
    """Train user/item towers from implicit interaction pairs.

    ``rows[i]``/``cols[i]`` is one (user, item) interaction. Returns
    L2-normalized tower vectors as replicated host-readable arrays.
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    if rows.shape != cols.shape or rows.ndim != 1:
        raise ValueError("rows/cols must be equal-length 1-D arrays")
    if rows.size == 0:
        raise ValueError("two-tower training needs at least one interaction")
    if rows.min() < 0 or rows.max() >= num_users:
        raise ValueError("row index out of range")
    if cols.min() < 0 or cols.max() >= num_items:
        raise ValueError("column index out of range")

    S = 1
    if mesh is not None and model_axis in mesh.shape:
        S = int(mesh.shape[model_axis])
    elif mesh is not None:
        model_axis = None
    D = config.dim
    n_u = _pad_rows(num_users, S)
    n_i = _pad_rows(num_items, S)

    B = config.batch_size
    if mesh is not None:
        d_size = int(mesh.shape.get(data_axis, 1))
        B = _pad_rows(B, d_size)

    key = jax.random.PRNGKey(config.seed)
    k_u, k_i, k_perm = jax.random.split(key, 3)
    scale = 1.0 / np.sqrt(D)
    params = {
        "user": jax.random.normal(k_u, (n_u, D), jnp.float32) * scale,
        "item": jax.random.normal(k_i, (n_i, D), jnp.float32) * scale,
    }
    if mesh is not None:
        spec = (
            PartitionSpec(model_axis, None)
            if model_axis
            else PartitionSpec(None, None)
        )
        sharded = NamedSharding(mesh, spec)
        params = {k: jax.device_put(v, sharded) for k, v in params.items()}

    # pad interactions to a whole number of batches by resampling real
    # pairs (padding with a sentinel would inject a fake item)
    nnz = rows.size
    n_pad = _pad_rows(nnz, B)
    reps = np.arange(n_pad) % nnz
    rep_sharding = None if mesh is None else NamedSharding(mesh, PartitionSpec())

    # upload the padded interaction set ONCE; every epoch's shuffle is a
    # device-side permutation gather (the previous per-epoch host
    # permutation + re-upload was a full-dataset transfer stall per epoch
    # — VERDICT r3 weak #6)
    r_base = jnp.asarray(rows[reps].astype(np.int32))
    c_base = jnp.asarray(cols[reps].astype(np.int32))
    if rep_sharding is not None:
        r_base = jax.device_put(r_base, rep_sharding)
        c_base = jax.device_put(c_base, rep_sharding)

    permute_kw = (
        {"out_shardings": rep_sharding} if rep_sharding is not None else {}
    )

    @functools.partial(jax.jit, **permute_kw)
    def epoch_perm(epoch, r, c):
        """Fresh permutation per epoch: in-batch softmax draws its
        negatives from the batch, so replaying one fixed batching would
        freeze every positive's negative set for the whole run."""
        perm = jax.random.permutation(jax.random.fold_in(k_perm, epoch), n_pad)
        return r[perm], c[perm]

    def epoch_arrays(epoch: int):
        return epoch_perm(jnp.int32(epoch), r_base, c_base)

    tx = optax.adam(config.learning_rate)
    opt_state = tx.init(params)
    steps_per_epoch = n_pad // B
    inv_temp = 1.0 / config.temperature

    def loss_fn(p, u_ids, i_ids):
        ue = sharded_embedding_lookup(p["user"], u_ids, mesh, data_axis, model_axis)
        ie = sharded_embedding_lookup(p["item"], i_ids, mesh, data_axis, model_axis)
        ue = ue / (jnp.linalg.norm(ue, axis=-1, keepdims=True) + 1e-8)
        ie = ie / (jnp.linalg.norm(ie, axis=-1, keepdims=True) + 1e-8)
        labels = jnp.arange(B)
        if mesh is not None:
            # in-batch logits need every negative on every device: keep
            # the LEFT side batch-sharded and replicate the right side (a
            # tiny [B, D] all-gather) — [B@data, B@data] is not a legal
            # layout, and labels must shard like the logits rows
            rep = NamedSharding(mesh, PartitionSpec(None, None))
            ue_r = jax.sharding.reshard(ue, rep)
            ie_r = jax.sharding.reshard(ie, rep)
            labels = jax.sharding.reshard(
                labels, NamedSharding(mesh, PartitionSpec(data_axis))
            )
        else:
            ue_r, ie_r = ue, ie
        # symmetric in-batch softmax: user->item and item->user
        l1 = optax.softmax_cross_entropy_with_integer_labels(
            (ue @ ie_r.T) * inv_temp, labels
        )
        l2 = optax.softmax_cross_entropy_with_integer_labels(
            (ie @ ue_r.T) * inv_temp, labels
        )
        return 0.5 * (l1.mean() + l2.mean())

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(p, o, step, r_all, c_all):
        off = (step % steps_per_epoch) * B
        u_ids = jax.lax.dynamic_slice(r_all, (off,), (B,))
        i_ids = jax.lax.dynamic_slice(c_all, (off,), (B,))
        if mesh is not None:
            # reshard, not with_sharding_constraint: make_mesh axes are
            # Explicit in current jax, and the batch must be data-sharded
            # before entering the shard_map lookups
            bspec = NamedSharding(mesh, PartitionSpec(data_axis))
            u_ids = jax.sharding.reshard(u_ids, bspec)
            i_ids = jax.sharding.reshard(i_ids, bspec)
        loss, grads = jax.value_and_grad(loss_fn)(p, u_ids, i_ids)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    history = []
    total_steps = config.epochs * steps_per_epoch
    step = 0
    for epoch in range(config.epochs):
        r_all, c_all = epoch_arrays(epoch)
        for _ in range(steps_per_epoch):
            params, opt_state, loss = train_step(
                params, opt_state, step, r_all, c_all
            )
            if step % config.log_every == 0 or step == total_steps - 1:
                history.append((step, float(loss)))
            step += 1

    def _finalize(p):
        u = p["user"] / (jnp.linalg.norm(p["user"], axis=-1, keepdims=True) + 1e-8)
        v = p["item"] / (jnp.linalg.norm(p["item"], axis=-1, keepdims=True) + 1e-8)
        return u, v

    if mesh is not None:
        # replicate before the host reads the (possibly model-sharded)
        # tables; slicing off the padding rows happens host-side
        u, v = jax.jit(
            _finalize, out_shardings=NamedSharding(mesh, PartitionSpec())
        )(params)
    else:
        u, v = jax.jit(_finalize)(params)
    return TwoTowerModel(
        user_vecs=np.asarray(u)[:num_users],
        item_vecs=np.asarray(v)[:num_items],
        loss_history=tuple(history),
    )
