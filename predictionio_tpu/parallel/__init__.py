"""Distribution layer: multi-host init, mesh helpers, sharded input reads.

Parity: the reference distributes via Spark (driver + executors, netty
shuffle — SURVEY.md sections 3.9, 6.8). Here distribution is jax-native:

* ``jax.distributed.initialize`` + DCN for multi-host control (the Spark
  driver role collapses into host 0);
* ``jax.sharding.Mesh`` + GSPMD collectives over ICI inside jit for all
  data exchange (no user-visible comm API);
* deterministic per-host file shards for input (replacing HBase region
  locality).
"""

from predictionio_tpu.parallel.distributed import (
    initialize_from_env,
    is_multihost,
    process_count,
    process_index,
)
from predictionio_tpu.parallel.reader import (
    read_event_shards,
    write_event_shards,
)

__all__ = [
    "initialize_from_env",
    "is_multihost",
    "process_count",
    "process_index",
    "read_event_shards",
    "write_event_shards",
]
