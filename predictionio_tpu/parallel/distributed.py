"""Multi-host initialization (the ``jax.distributed`` + DCN control plane).

Parity: replaces the reference's Spark driver/executor topology
(``tools/Runner.scala`` spark-submit bridge — SURVEY.md section 4.1).
A multi-host job runs the SAME ``pio train`` on every host with three env
vars set; host 0 plays the coordinator (the Spark-driver role):

    PIO_COORDINATOR_ADDRESS=10.0.0.1:8476
    PIO_NUM_PROCESSES=4
    PIO_PROCESS_ID=<0..3>

After ``initialize_from_env()``, ``jax.devices()`` spans every chip of
the slice, a ``mesh_context()`` builds the global mesh, and the sharded
event reader gives each host its input shard
(``shard_index=process_index(), num_shards=process_count()``).
"""

from __future__ import annotations

import logging
import os

import jax

__all__ = [
    "initialize_from_env",
    "is_multihost",
    "process_count",
    "process_index",
]

logger = logging.getLogger(__name__)

_initialized = False


def initialize_from_env() -> bool:
    """Call ``jax.distributed.initialize`` if the ``PIO_COORDINATOR_*`` env
    triplet is present. Idempotent; returns True when running multi-host."""
    global _initialized
    coordinator = os.environ.get("PIO_COORDINATOR_ADDRESS")
    if not coordinator:
        return False
    if _initialized:
        return True
    num_processes = int(os.environ.get("PIO_NUM_PROCESSES", "1"))
    process_id = int(os.environ.get("PIO_PROCESS_ID", "0"))
    logger.info(
        "Initializing jax.distributed: coordinator=%s process=%d/%d",
        coordinator, process_id, num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_multihost() -> bool:
    return jax.process_count() > 1
