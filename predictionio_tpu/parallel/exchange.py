"""Cross-host exchange primitives (DCN control plane).

The round-1 multi-host input path all-gathered the ENTIRE rating set onto
every host (``ops/als.py:_allgather_coo`` — VERDICT.md weak/missing #3).
Round 2 bounded the *memory* with chunked ``process_allgather`` rounds,
but the *traffic* was still O(data · P): every host received the whole
global set and filtered locally (VERDICT round-2 weak #3). Round 3 makes
the re-partition a true point-to-point all-to-all: each host sends each
peer ONLY that peer's partition over a direct TCP connection (rendezvous
via one tiny metadata allgather), so aggregate traffic is O(data) — the
same contract as the Spark netty shuffle the reference relies on.

* :func:`allgather_objects` — small-metadata consensus (id sets, bucket
  shapes, hot-row counts). Still collective: metadata is tiny.
* :func:`exchange_by_owner` / :func:`exchange_objects_by_owner` — the
  all-to-all re-partition (each host keeps only the rows hashed to it),
  point-to-point by default; ``PIO_EXCHANGE_TRANSPORT=allgather``
  selects the collective fallback (e.g. hosts that cannot dial each
  other directly).
* :func:`exchange_traffic` — byte counters (sent/received per transport)
  so tests and operators can verify the O(data) bound.

Parity: replaces the implicit shuffle of Spark's ``partitionBy`` on the
rating RDD (reference: MLlib ALS block partitioning reached via
``core/controller/PAlgorithm.scala``).
"""

from __future__ import annotations

import logging
import os
import pickle
import socket
import struct
import threading
from typing import Any, Sequence

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "allgather_bytes",
    "allgather_objects",
    "exchange_by_owner",
    "exchange_objects_by_owner",
    "exchange_traffic",
    "reset_exchange_traffic",
    "crc_owner",
    "merge_keyed",
    "global_vocab",
    "global_sum_array",
]

#: cumulative transport byte counters (process-local)
_TRAFFIC = {"p2p_sent": 0, "p2p_received": 0, "allgather_received": 0}
_TRAFFIC_LOCK = threading.Lock()


def exchange_traffic() -> dict:
    """Copy of the cumulative per-transport byte counters."""
    with _TRAFFIC_LOCK:
        return dict(_TRAFFIC)


def reset_exchange_traffic() -> None:
    with _TRAFFIC_LOCK:
        for k in _TRAFFIC:
            _TRAFFIC[k] = 0


def _count(key: str, n: int) -> None:
    with _TRAFFIC_LOCK:
        _TRAFFIC[key] += n


def _gather(arr: np.ndarray) -> np.ndarray:
    """process_allgather: [*(local)] -> [P, *(local)] (same shape req'd)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr))


def allgather_bytes(data: bytes) -> list[bytes]:
    """Every process's ``data`` blob, in process order."""
    import jax

    if jax.process_count() == 1:
        return [data]
    n = np.array([len(data)], dtype=np.int64)
    sizes = _gather(n).ravel()
    buf = np.zeros(int(sizes.max()) if sizes.size else 0, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    gathered = _gather(buf)
    return [gathered[p, : sizes[p]].tobytes() for p in range(len(sizes))]


def allgather_objects(obj: Any) -> list[Any]:
    """Every process's picklable ``obj``, in process order. For small
    metadata only — id vocabularies, shape plans, counters."""
    return [pickle.loads(b) for b in allgather_bytes(pickle.dumps(obj))]


# ---------------------------------------------------------------------------
# Point-to-point transport
# ---------------------------------------------------------------------------

#: header = (sender rank, payload length, receiver's 16-byte exchange token).
#: The token is generated fresh per exchange by each receiver and distributed
#: through the rendezvous allgather, which rides the trusted jax.distributed
#: channel — so only real peers can present it. Without it, anything able to
#: reach the ephemeral port during the exchange window could feed
#: ``pickle.loads`` an arbitrary payload (advisor r3 medium finding).
_HDR = struct.Struct("<iq16s")
_TOKEN_LEN = 16


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = conn.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-message")
        got += r
    return bytes(buf)


def _p2p_host() -> str:
    """The address peers dial this process on. Override with
    ``PIO_P2P_HOST`` when the hostname is not routable between hosts."""
    override = os.environ.get("PIO_P2P_HOST")
    if override:
        return override
    host = socket.gethostname()
    try:
        resolved = socket.gethostbyname(host)
    except OSError:
        resolved = ""
    if resolved and not resolved.startswith("127."):
        return host
    # hostname resolves to loopback (the Debian '127.0.1.1 <hostname>'
    # convention) — peers dialing it would hit themselves. Use the
    # route-out interface address instead; loopback only as a last
    # resort (correct for single-machine multi-process tests).
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packets sent; routes only
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def pairwise_exchange(payloads: Sequence, timeout: float = 300.0) -> list[bytes]:
    """One all-to-all round of raw byte blobs, point-to-point.

    ``payloads[p]`` is this process's message FOR process ``p`` — either
    ``bytes`` or a zero-arg callable producing them. Callables are
    invoked one destination at a time, at send time, so the caller's
    peak memory holds ONE outgoing serialization instead of P-1 (the
    chunked-send path of VERDICT r3 weak #8; with bytes payloads, peak
    outgoing is the full sum). Returns ``received`` with ``received[p]``
    = process p's message for this process (``received[me] =
    payloads[me]``, no self-send). Each pair exchanges over a direct TCP
    connection — aggregate network traffic is exactly the sum of
    cross-process payload sizes, O(data), not the O(data · P) of a
    broadcast-and-filter exchange (VERDICT r2 weak #3). Sends follow a
    staggered ring (offset k → peer (me+k) % P) deliberately kept
    sequential: parallel sends would hold every serialization alive at
    once and concentrate P-1 connections on one accept queue.
    Rendezvous (addresses) goes through one tiny metadata allgather.
    """
    import jax

    P = jax.process_count()
    me = jax.process_index()

    def materialize(p: int) -> bytes:
        item = payloads[p]
        return item() if callable(item) else item

    if P == 1:
        return [materialize(0)]
    if len(payloads) != P:
        raise ValueError(f"need {P} payloads, got {len(payloads)}")

    import secrets

    server = socket.create_server(("0.0.0.0", 0), backlog=P)
    server.settimeout(timeout)
    port = server.getsockname()[1]
    my_token = secrets.token_bytes(_TOKEN_LEN)
    addrs = allgather_objects((_p2p_host(), port, my_token))

    results: list = [None] * P
    results[me] = materialize(me)
    fatal: list = []  # post-authentication failures (peers never retry)
    #: pre-auth connection failures. A GENUINE peer dying before token
    #: auth lands here and the exchange then waits out the full timeout
    #: (degraded failure latency — deliberate: failing fast on unproven
    #: connections would let any stray/port-scan kill the exchange by
    #: claiming a rank). The drop count is surfaced in the timeout error.
    dropped_preauth: list = []
    done = threading.Event()  # all peers reported OR fatal

    def handle(conn: socket.socket, peer: Any) -> None:
        authenticated = False
        try:
            with conn:
                conn.settimeout(timeout)
                rank, length, token = _HDR.unpack(_recv_exact(conn, _HDR.size))
                # reject garbage/stray connections: an unvalidated rank
                # (esp. negative) would silently overwrite a peer's slot,
                # and an absurd length would allocate unbounded memory
                max_len = int(
                    os.environ.get("PIO_P2P_MAX_PAYLOAD", str(1 << 33))
                )
                if not (0 <= rank < P) or rank == me or not (0 <= length <= max_len):
                    raise ConnectionError(
                        f"invalid peer header (rank={rank}, len={length})"
                    )
                if not secrets.compare_digest(token, my_token):
                    raise ConnectionError(
                        f"bad exchange token from claimed rank {rank} — "
                        "refusing payload (untrusted connector?)"
                    )
                authenticated = True
                results[rank] = _recv_exact(conn, length)
                _count("p2p_received", length)
                if all(r is not None for r in results):
                    done.set()
        except Exception as e:
            if authenticated:
                # a REAL peer died mid-payload; it will not retry, so
                # waiting out the deadline buys nothing — fail promptly
                fatal.append(e)
                done.set()
            else:
                # a stray or untrusted connection must not burn the
                # exchange: drop it and keep listening — completion is
                # "every peer reported", not "P-1 accepts". If this WAS a
                # real peer (reset mid-header/mid-auth), it never retries,
                # so the exchange will now run out the full timeout —
                # shout, so the operator sees the cause before the
                # timeout error names the missing rank
                dropped_preauth.append((peer, str(e)))
                logger.error(
                    "dropped unauthenticated p2p connection from %s: %s — "
                    "if this was a real peer the exchange will time out "
                    "in up to %.0fs",
                    peer, e, timeout,
                )

    def acceptor() -> None:
        import time

        deadline = time.monotonic() + timeout
        server.settimeout(1.0)
        handlers = []
        while not done.is_set() and time.monotonic() < deadline:
            try:
                conn, addr = server.accept()
            except TimeoutError:
                continue
            except OSError:
                break  # listener closed underneath us
            t = threading.Thread(target=handle, args=(conn, addr), daemon=True)
            t.start()
            handlers.append(t)
        for t in handlers:
            # once the exchange is decided, any handler still running is a
            # stray connection stalling in its header read — don't let it
            # hold the outcome hostage for the full timeout
            t.join(
                timeout=0.1
                if done.is_set()
                else max(0.0, deadline - time.monotonic()) + 1.0
            )

    acc = threading.Thread(target=acceptor, daemon=True)
    acc.start()
    try:
        # staggered ring schedule: at offset k everyone sends to (me+k) % P,
        # so no single host absorbs all P-1 connections at once
        for offset in range(1, P):
            dst = (me + offset) % P
            host, dport, dst_token = addrs[dst]
            data = materialize(dst)  # ONE serialization alive at a time
            with socket.create_connection((host, dport), timeout=timeout) as s:
                s.sendall(_HDR.pack(me, len(data), dst_token))
                s.sendall(data)
                _count("p2p_sent", len(data))
            del data
        done.wait(timeout)
        acc.join(timeout=2.0)
    finally:
        # always reclaim the listener — a failed send must not leave the
        # rendezvous socket open with the acceptor still feeding it
        server.close()
    if fatal:
        raise RuntimeError(f"pairwise exchange failed: {fatal[0]}") from fatal[0]
    missing = [p for p in range(P) if results[p] is None]
    if missing:
        hint = (
            f" ({len(dropped_preauth)} connection(s) were dropped before "
            f"authenticating — one of them may have been the missing peer)"
            if dropped_preauth
            else ""
        )
        raise RuntimeError(
            f"pairwise exchange timed out waiting for processes {missing}{hint}"
        )
    return results


def _use_p2p() -> bool:
    return os.environ.get("PIO_EXCHANGE_TRANSPORT", "p2p") != "allgather"


def exchange_by_owner(
    arrays: Sequence[np.ndarray],
    owner: np.ndarray,
    chunk: int = 1 << 20,
) -> list[np.ndarray]:
    """All-to-all re-partition of parallel arrays.

    ``owner[i]`` names the process that must end up with element ``i``.
    Returns this process's elements contributed by ALL processes,
    concatenated in process order (stable within each contribution).

    Default transport is point-to-point (O(data) aggregate traffic and
    O(local data) peak memory); ``PIO_EXCHANGE_TRANSPORT=allgather``
    falls back to chunked collective rounds (O(chunk · P) peak memory
    but O(data · P) traffic) for hosts without direct connectivity.
    """
    import jax

    P = jax.process_count()
    me = jax.process_index()
    arrays = [np.asarray(a) for a in arrays]
    n_local = arrays[0].shape[0]
    for a in arrays:
        if a.shape[0] != n_local:
            raise ValueError("exchange_by_owner arrays must share dim 0")
    owner = np.asarray(owner)
    if owner.shape != (n_local,):
        raise ValueError("owner must be 1-D aligned with the arrays")
    if P == 1:
        keep = owner == 0
        return [a[keep] for a in arrays]
    if _use_p2p():
        # the self-owned partition never crosses the wire — keep it as
        # arrays instead of a pointless pickle round-trip. Outgoing
        # partitions are pickled LAZILY (one at a time, at send time),
        # so peak memory is the partition copies (~1x local data) plus a
        # single in-flight serialization, not all P-1 of them (VERDICT
        # r3 weak #8).
        parts_self = None
        payloads: list = []
        for p in range(P):
            sel = owner == p
            part = [a[sel] for a in arrays]
            if p == me:
                parts_self = part
                payloads.append(b"")
            else:
                payloads.append(
                    lambda part=part: pickle.dumps(part, protocol=5)
                )
        received = pairwise_exchange(payloads)
        parts = [
            parts_self if p == me else pickle.loads(received[p])
            for p in range(P)
        ]  # [P][n_arrays]
        return [
            np.concatenate([parts[p][k] for p in range(P)])
            for k in range(len(arrays))
        ]
    return _exchange_by_owner_allgather(arrays, owner, chunk, P, me)


def _exchange_by_owner_allgather(
    arrays: list, owner: np.ndarray, chunk: int, P: int, me: int
) -> list[np.ndarray]:
    n_local = arrays[0].shape[0]
    n_rounds = int(_gather(np.array([-(-n_local // chunk)], np.int64)).max())
    out: list[list[np.ndarray]] = [[] for _ in arrays]
    for r in range(n_rounds):
        lo, hi = r * chunk, min((r + 1) * chunk, n_local)
        lo = min(lo, n_local)
        sl = slice(lo, max(hi, lo))
        own_r = owner[sl]
        n_r = own_r.shape[0]
        sizes = _gather(np.array([n_r], np.int64)).ravel()
        n_max = int(sizes.max())
        # owner channel: -1 padding never matches a process index
        own_pad = np.full(n_max, -1, dtype=np.int64)
        own_pad[:n_r] = own_r
        own_all = _gather(own_pad)  # [P, n_max]
        for k, a in enumerate(arrays):
            pad = np.zeros((n_max,) + a.shape[1:], dtype=a.dtype)
            pad[:n_r] = a[sl]
            got = _gather(pad)  # [P, n_max, ...]
            _count("allgather_received", got.nbytes)
            for p in range(P):
                sel = own_all[p] == me
                if sel.any():
                    out[k].append(got[p][sel])
    return [
        np.concatenate(chunks) if chunks else np.zeros((0,) + a.shape[1:], a.dtype)
        for chunks, a in zip(out, arrays)
    ]


def exchange_objects_by_owner(
    items: list, owner: Sequence[int], chunk: int = 65536
) -> list:
    """All-to-all re-partition of picklable items (template-level string
    triples). Point-to-point by default (see :func:`exchange_by_owner`)."""
    import jax

    P = jax.process_count()
    if P == 1:
        return list(items)
    me = jax.process_index()
    owner = list(owner)
    if _use_p2p():
        per_dest: list[list] = [[] for _ in range(P)]
        for it, ow in zip(items, owner):
            per_dest[ow].append(it)
        received = pairwise_exchange(
            [
                b""
                if p == me
                else (lambda lst=per_dest[p]: pickle.dumps(lst, protocol=5))
                for p in range(P)
            ]
        )
        out: list = []
        for p in range(P):
            out.extend(per_dest[me] if p == me else pickle.loads(received[p]))
        return out
    n_rounds = int(
        _gather(np.array([-(-max(len(items), 1) // chunk)], np.int64)).max()
    )
    out = []
    for r in range(n_rounds):
        sl = slice(r * chunk, (r + 1) * chunk)
        per_dest = [[] for _ in range(P)]
        for it, ow in zip(items[sl], owner[sl]):
            per_dest[ow].append(it)
        for contrib in allgather_objects(per_dest):
            out.extend(contrib[me])
    return out


def crc_owner(key: str, num_processes: int) -> int:
    """Deterministic cross-process owner of a string key."""
    import zlib

    return zlib.crc32(key.encode()) % num_processes


def merge_keyed(mapping: dict, combine, owner_key=None) -> dict:
    """Multi-host merge of per-host {key: value} maps: re-partition by
    ``crc_owner(owner_key(key))`` and fold values for identical keys with
    ``combine`` (e.g. ``max`` for latest-wins rating events, ``operator.add``
    for view counts). No-op in a single process.

    This is the coherence fix for the round-1 advisor's high finding:
    every host must agree on the global rating set before building
    BiMaps/COO, without replicating the whole set per host."""
    import jax

    P = jax.process_count()
    if P <= 1:
        return mapping
    if owner_key is None:
        owner_key = lambda k: k[0]  # noqa: E731 — (user, item) keys
    items = list(mapping.items())
    owner = [crc_owner(str(owner_key(k)), P) for k, _ in items]
    merged: dict = {}
    for k, v in exchange_objects_by_owner(items, owner):
        merged[k] = combine(merged[k], v) if k in merged else v
    return merged


def global_vocab(local_ids) -> list[str]:
    """Sorted union of every host's id set — the deterministic order all
    hosts build their BiMaps from. Single-process: sorted(local)."""
    import jax

    ids = set(local_ids)
    if jax.process_count() > 1:
        for other in allgather_objects(sorted(ids)):
            ids.update(other)
    return sorted(ids)


def global_sum_array(a: np.ndarray) -> np.ndarray:
    """Elementwise sum of a same-shaped array across processes."""
    import jax

    if jax.process_count() == 1:
        return np.asarray(a)
    return _gather(np.asarray(a)).sum(axis=0)
