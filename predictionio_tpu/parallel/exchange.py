"""Bounded-memory cross-host exchange primitives (DCN control plane).

The round-1 multi-host input path all-gathered the ENTIRE rating set onto
every host (``ops/als.py:_allgather_coo`` — VERDICT.md weak/missing #3):
per-host memory O(global nnz), a per-host OOM at ALX scale. These
helpers replace it with chunked exchanges whose peak extra memory is
O(chunk · num_processes), independent of the global data size:

* :func:`allgather_objects` — small-metadata consensus (id sets, bucket
  shapes, hot-row counts).
* :func:`exchange_by_owner` — the all-to-all re-partition (each host
  keeps only the rows hashed to it), built from chunked rounds of
  ``process_allgather`` so no host ever materializes the global array.

Parity: replaces the implicit shuffle of Spark's ``partitionBy`` on the
rating RDD (reference: MLlib ALS block partitioning reached via
``core/controller/PAlgorithm.scala``); the reference relies on Spark's
netty shuffle for the same bounded-memory guarantee.
"""

from __future__ import annotations

import pickle
from typing import Any, Sequence

import numpy as np

__all__ = [
    "allgather_bytes",
    "allgather_objects",
    "exchange_by_owner",
    "exchange_objects_by_owner",
    "crc_owner",
    "merge_keyed",
    "global_vocab",
    "global_sum_array",
]


def _gather(arr: np.ndarray) -> np.ndarray:
    """process_allgather: [*(local)] -> [P, *(local)] (same shape req'd)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr))


def allgather_bytes(data: bytes) -> list[bytes]:
    """Every process's ``data`` blob, in process order."""
    import jax

    if jax.process_count() == 1:
        return [data]
    n = np.array([len(data)], dtype=np.int64)
    sizes = _gather(n).ravel()
    buf = np.zeros(int(sizes.max()) if sizes.size else 0, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    gathered = _gather(buf)
    return [gathered[p, : sizes[p]].tobytes() for p in range(len(sizes))]


def allgather_objects(obj: Any) -> list[Any]:
    """Every process's picklable ``obj``, in process order. For small
    metadata only — id vocabularies, shape plans, counters."""
    return [pickle.loads(b) for b in allgather_bytes(pickle.dumps(obj))]


def exchange_by_owner(
    arrays: Sequence[np.ndarray],
    owner: np.ndarray,
    chunk: int = 1 << 20,
) -> list[np.ndarray]:
    """All-to-all re-partition of parallel arrays.

    ``owner[i]`` names the process that must end up with element ``i``.
    Returns this process's elements contributed by ALL processes,
    concatenated in process order (stable within each contribution).

    Memory: processed in rounds of at most ``chunk`` elements per host,
    so peak extra memory is O(chunk · P) regardless of global size —
    the bounded-shuffle contract Spark gives the reference.
    """
    import jax

    P = jax.process_count()
    me = jax.process_index()
    arrays = [np.asarray(a) for a in arrays]
    n_local = arrays[0].shape[0]
    for a in arrays:
        if a.shape[0] != n_local:
            raise ValueError("exchange_by_owner arrays must share dim 0")
    owner = np.asarray(owner)
    if owner.shape != (n_local,):
        raise ValueError("owner must be 1-D aligned with the arrays")
    if P == 1:
        keep = owner == 0
        return [a[keep] for a in arrays]

    n_rounds = int(_gather(np.array([-(-n_local // chunk)], np.int64)).max())
    out: list[list[np.ndarray]] = [[] for _ in arrays]
    for r in range(n_rounds):
        lo, hi = r * chunk, min((r + 1) * chunk, n_local)
        lo = min(lo, n_local)
        sl = slice(lo, max(hi, lo))
        own_r = owner[sl]
        n_r = own_r.shape[0]
        sizes = _gather(np.array([n_r], np.int64)).ravel()
        n_max = int(sizes.max())
        # owner channel: -1 padding never matches a process index
        own_pad = np.full(n_max, -1, dtype=np.int64)
        own_pad[:n_r] = own_r
        own_all = _gather(own_pad)  # [P, n_max]
        for k, a in enumerate(arrays):
            pad = np.zeros((n_max,) + a.shape[1:], dtype=a.dtype)
            pad[:n_r] = a[sl]
            got = _gather(pad)  # [P, n_max, ...]
            for p in range(P):
                sel = own_all[p] == me
                if sel.any():
                    out[k].append(got[p][sel])
    return [
        np.concatenate(chunks) if chunks else np.zeros((0,) + a.shape[1:], a.dtype)
        for chunks, a in zip(out, arrays)
    ]


def exchange_objects_by_owner(
    items: list, owner: Sequence[int], chunk: int = 65536
) -> list:
    """All-to-all re-partition of picklable items (template-level string
    triples). Chunked rounds bound peak memory at O(chunk · P)."""
    import jax

    P = jax.process_count()
    if P == 1:
        return list(items)
    me = jax.process_index()
    owner = list(owner)
    n_rounds = int(
        _gather(np.array([-(-max(len(items), 1) // chunk)], np.int64)).max()
    )
    out: list = []
    for r in range(n_rounds):
        sl = slice(r * chunk, (r + 1) * chunk)
        per_dest: list[list] = [[] for _ in range(P)]
        for it, ow in zip(items[sl], owner[sl]):
            per_dest[ow].append(it)
        for contrib in allgather_objects(per_dest):
            out.extend(contrib[me])
    return out


def crc_owner(key: str, num_processes: int) -> int:
    """Deterministic cross-process owner of a string key."""
    import zlib

    return zlib.crc32(key.encode()) % num_processes


def merge_keyed(mapping: dict, combine, owner_key=None) -> dict:
    """Multi-host merge of per-host {key: value} maps: re-partition by
    ``crc_owner(owner_key(key))`` and fold values for identical keys with
    ``combine`` (e.g. ``max`` for latest-wins rating events, ``operator.add``
    for view counts). No-op in a single process.

    This is the coherence fix for the round-1 advisor's high finding:
    every host must agree on the global rating set before building
    BiMaps/COO, without replicating the whole set per host."""
    import jax

    P = jax.process_count()
    if P <= 1:
        return mapping
    if owner_key is None:
        owner_key = lambda k: k[0]  # noqa: E731 — (user, item) keys
    items = list(mapping.items())
    owner = [crc_owner(str(owner_key(k)), P) for k, _ in items]
    merged: dict = {}
    for k, v in exchange_objects_by_owner(items, owner):
        merged[k] = combine(merged[k], v) if k in merged else v
    return merged


def global_vocab(local_ids) -> list[str]:
    """Sorted union of every host's id set — the deterministic order all
    hosts build their BiMaps from. Single-process: sorted(local)."""
    import jax

    ids = set(local_ids)
    if jax.process_count() > 1:
        for other in allgather_objects(sorted(ids)):
            ids.update(other)
    return sorted(ids)


def global_sum_array(a: np.ndarray) -> np.ndarray:
    """Elementwise sum of a same-shaped array across processes."""
    import jax

    if jax.process_count() == 1:
        return np.asarray(a)
    return _gather(np.asarray(a)).sum(axis=0)
