"""Sharded event-file layout: the training input path at scale.

Parity: replaces the reference's HBase-scan-to-RDD locality
(``storage/hbase/HBPEvents.scala`` ``TableInputFormat`` splits) with a
deterministic shard-per-host file layout (SURVEY.md section 8.3):
``pio export --sharded`` writes ``events-00000-of-00008.jsonl`` style
shards; each training host reads only the shards assigned to it by round
robin, so multi-host input needs no coordination and no shuffle.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Iterable, Iterator

from predictionio_tpu.data.event import Event, event_from_json, event_to_json

__all__ = ["write_event_shards", "read_event_shards", "shard_paths"]

_SHARD_RE = re.compile(r"events-(\d{5})-of-(\d{5})\.jsonl$")


def write_event_shards(
    events: Iterable[Event], out_dir: str, num_shards: int = 8
) -> list[str]:
    """Write events into ``num_shards`` JSONL shard files (round-robin —
    balanced regardless of entity skew). Returns the shard paths."""
    os.makedirs(out_dir, exist_ok=True)
    # remove stale shards from a prior export (a different shard count
    # would otherwise leave a mixed set that shard_paths rejects)
    for stale in glob.glob(os.path.join(out_dir, "events-*-of-*.jsonl")):
        os.remove(stale)
    paths = [
        os.path.join(out_dir, f"events-{i:05d}-of-{num_shards:05d}.jsonl")
        for i in range(num_shards)
    ]
    files = [open(p, "w") for p in paths]
    try:
        for n, event in enumerate(events):
            files[n % num_shards].write(
                json.dumps(event_to_json(event), default=str) + "\n"
            )
    finally:
        for f in files:
            f.close()
    return paths


def shard_paths(in_dir: str) -> list[str]:
    """All shard files of a directory, sorted; validates the -of- counts."""
    paths = sorted(
        p for p in glob.glob(os.path.join(in_dir, "events-*-of-*.jsonl"))
        if _SHARD_RE.search(p)
    )
    if not paths:
        raise FileNotFoundError(f"No event shards under {in_dir}")
    declared = {int(_SHARD_RE.search(p).group(2)) for p in paths}
    if len(declared) != 1 or len(paths) != declared.pop():
        raise ValueError(f"Incomplete/mixed shard set under {in_dir}")
    return paths


def read_event_shards(
    in_dir: str,
    host_index: int = 0,
    num_hosts: int = 1,
    validate: bool = False,
) -> Iterator[Event]:
    """Stream this host's events: shard files are assigned round-robin to
    hosts (file granularity keeps reads sequential — the locality story).
    ``validate=False`` by default: shards written by ``write_event_shards``
    are already validated on the ingest path."""
    for i, path in enumerate(shard_paths(in_dir)):
        if i % num_hosts != host_index:
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield event_from_json(json.loads(line), validate=validate)
