"""Model-axis sharded factor serving — ``pio deploy --shard-factors``.

BENCH_r01 died the moment the catalog outgrew one chip
(``f32[64761856,64]`` = 16.6 GB *per table* against 17 GB of HBM)
because serving replicates the factor tables on every device. Training
already shards them ALX-style (``ops/als.py`` keeps the persistent
tables ``PartitionSpec('model', None)`` and moves only O(C·K²) Gramian
blocks over ICI); this module extends the same layout through the
serving path so per-device factor memory is ``O((U+I)·K / S)`` for an
``S``-way model axis — the largest servable catalog scales with the
mesh instead of being capped by a single chip.

Three pieces:

* **Shard placement** — :func:`serving_mesh` builds a one-axis
  (``model``) mesh over the local devices and :func:`shard_table`
  ``device_put``\\ s a factor table row-sharded across it (rows padded
  to a multiple of the axis so every shard is even; padding rows are
  zero and masked out of every kernel by the LOGICAL row count).
  :class:`ShardInfo` carries the mesh plus the logical row counts so
  the padded physical shapes never leak into id spaces.
* **Sharded exact top-K** (:func:`sharded_topk_users`) — a shard_map
  kernel in the MapReduce shape DrJAX frames as a primitive (PAPERS.md):
  each device resolves the query rows from its USER shard (masked
  gather + ``psum`` — the catalog-sized table never moves), scores only
  its ITEM shard with one local GEMM, takes a local top-k (position
  order == global id order within a shard, so ``lax.top_k``'s tie rule
  is already the shared one), and ``all_gather``\\ s ONLY the ``S·k``
  finalists per query; the cross-shard reduce reuses the shared two-key
  tie rule (:func:`~predictionio_tpu.ops.topk.sort_merge_topk`), so the
  merged ranking is tie-stable-identical to the replicated exact kernel.
* **Sharded IVF** (:func:`sharded_ivf_topk`) — PR 6's cluster-major
  slabs shard over the same axis (``ops/ivf.shard_runtime``): centroids
  stay replicated (tiny), every device scores only the probed clusters
  it OWNS, and the same two-level tie-stable merge gathers ``S·k``
  candidates per query.

Every collective goes through the :mod:`predictionio_tpu.ops.compat`
shims (piolint PIO304 enforces that no module outside ``ops/compat.py``
touches ``jax.shard_map`` directly), so jax<0.6 hosts keep working.
Strictly opt-in: nothing imports this module until a deploy passes
``--shard-factors`` (CI-guarded like ``--ann``/``--online``).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from predictionio_tpu.ops.compat import shard_map
from predictionio_tpu.ops.topk import bucket_k, sort_merge_topk

__all__ = [
    "MODEL_AXIS",
    "ShardInfo",
    "serving_mesh",
    "shard_table",
    "shard_quantized_table",
    "gather_rows",
    "sharded_topk_users",
    "sharded_quantized_topk_users",
    "sharded_ivf_topk",
    "table_bytes",
    "sharded_table_bytes",
    "per_device_bytes",
    "per_device_bytes_quantized",
]

#: serving-side model axis name (matches the training mesh's axis so the
#: memory model reads the same: per-device rows = rows / S)
MODEL_AXIS = "model"

#: cold-start growth headroom (rows) when a sharded table must be
#: re-laid-out: growing by at least this much amortizes the
#: gather+re-shard over many fold-ins instead of paying it per new
#: entity (same bounded-retrace idea as ops/ivf._CAPACITY_STEP)
GROW_STEP = 1024


@dataclasses.dataclass
class ShardInfo:
    """Per-model sharded-serving state, attached as ``model._pio_shards``
    by the algorithms' ``shard_model_for_serving`` hooks.

    ``rows`` maps side name (``"user"``/``"item"``) to the LOGICAL row
    count — the physical tables are padded up to a multiple of the mesh
    axis, and every kernel masks by the logical count so padding rows
    can never score or be returned. Mutable on purpose: online
    cold-start fold-ins advance the logical counts (see
    ``workflow/device_state.swap_side_rows``)."""

    mesh: Mesh
    rows: dict

    @property
    def num_shards(self) -> int:
        return int(self.mesh.shape[MODEL_AXIS])


def serving_mesh(shards: int = 0) -> Mesh | None:
    """A one-axis (``model``) mesh over the local devices for sharded
    serving. ``shards`` caps the axis size (0 = all local devices).
    Returns ``None`` on a single-device host — sharding over one device
    is replication, so callers fall back to plain pinning."""
    devs = jax.devices()
    n = len(devs) if shards <= 0 else max(1, min(int(shards), len(devs)))
    if n < 2:
        return None
    return jax.make_mesh((n,), (MODEL_AXIS,), devices=devs[:n])


def table_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(MODEL_AXIS, None))


def padded_rows(n: int, shards: int) -> int:
    """Physical row count: logical rows padded up so every shard is even."""
    return -(-max(int(n), 1) // shards) * shards


def shard_table(mat, mesh: Mesh, capacity: int = 0) -> jax.Array:
    """Place a factor table row-sharded over the mesh's model axis.

    Rows are zero-padded to a multiple of the axis size (and up to
    ``capacity`` when given — the cold-start growth headroom), then
    ``device_put`` with ``PartitionSpec('model', None)``: each device
    receives ONLY its ``[rows/S, K]`` shard, which is the whole point —
    the full table never materializes in any single device's memory."""
    mat = np.asarray(mat, dtype=np.float32)
    if mat.ndim != 2:
        raise ValueError(f"factor table must be 2-D, got {mat.shape}")
    S = int(mesh.shape[MODEL_AXIS])
    n_pad = padded_rows(max(mat.shape[0], capacity), S)
    if n_pad != mat.shape[0]:
        mat = np.concatenate(
            [mat, np.zeros((n_pad - mat.shape[0], mat.shape[1]), mat.dtype)]
        )
    return jax.device_put(mat, table_spec(mesh))


def shard_quantized_table(mat, mesh: Mesh, capacity: int = 0):
    """Quantize a host f32 table (``ops/quant``'s one rounding rule) and
    place it int8-sharded over the mesh's model axis: codes
    ``PartitionSpec('model', None)``, per-row scales
    ``PartitionSpec('model')`` — per-device factor memory drops to
    ``rows/S · (rank + 4)`` bytes, the multiplicative composition of the
    sharding and quantization tiers (``pio deploy --shard-factors
    --quantize int8``). Zero padding rows quantize to zero codes + zero
    scale and stay masked by the logical row count like the f32 layout."""
    from predictionio_tpu.ops import quant

    mat = np.asarray(mat, dtype=np.float32)
    if mat.ndim != 2:
        raise ValueError(f"factor table must be 2-D, got {mat.shape}")
    S = int(mesh.shape[MODEL_AXIS])
    n_pad = padded_rows(max(mat.shape[0], capacity), S)
    if n_pad != mat.shape[0]:
        mat = np.concatenate(
            [mat, np.zeros((n_pad - mat.shape[0], mat.shape[1]), mat.dtype)]
        )
    codes, scales = quant.quantize_table_host(mat)
    return quant.QuantizedTable(
        jax.device_put(codes, table_spec(mesh)),
        jax.device_put(
            scales, NamedSharding(mesh, PartitionSpec(MODEL_AXIS))
        ),
    )


# ---------------------------------------------------------------------------
# Byte accounting (the bench's memory model; pure shape math, CPU-safe)
# ---------------------------------------------------------------------------


def table_bytes(rows: int, rank: int, itemsize: int = 4) -> int:
    """Bytes of one replicated factor table — what EVERY device pays
    without sharding."""
    return int(rows) * int(rank) * itemsize


def sharded_table_bytes(
    rows: int, rank: int, shards: int, itemsize: int = 4
) -> int:
    """Per-device bytes of the same table sharded ``shards``-way
    (including the even-shard padding — the only overhead, bounded by
    ``(shards-1)·rank·itemsize``)."""
    return padded_rows(rows, shards) // shards * int(rank) * itemsize


def per_device_bytes(arr) -> int:
    """MEASURED bytes the largest single device holds of ``arr`` — the
    quantity the scale bench asserts against ``table_bytes / S``."""
    per: dict = {}
    for s in arr.addressable_shards:
        per[s.device] = per.get(s.device, 0) + int(s.data.nbytes)
    return max(per.values()) if per else 0


def per_device_bytes_quantized(qt) -> int:
    """Measured per-device bytes of a sharded quantized table — codes
    AND scales, read from the actual array shards so the scale bench
    asserts served truth, not shape math."""
    return per_device_bytes(qt.codes) + per_device_bytes(qt.scales)


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _resolve_rows(tbl, idx):
    """Inside shard_map: gather rows ``idx`` (GLOBAL ids, replicated)
    from this device's table shard, masking out-of-shard rows to zero;
    the ``psum`` over the model axis then assembles the true rows on
    every device — only ``[B, K]`` crosses ICI, never the table."""
    rps = tbl.shape[0]  # local shard rows
    me = jax.lax.axis_index(MODEL_AXIS)
    lidx = idx - me * rps
    inr = (lidx >= 0) & (lidx < rps)
    rows = jnp.where(inr[:, None], tbl[jnp.where(inr, lidx, 0)], 0.0)
    return jax.lax.psum(rows, MODEL_AXIS)


@functools.partial(jax.jit, static_argnames=("mesh",))
def gather_rows(idx: jax.Array, tbl: jax.Array, mesh: Mesh) -> jax.Array:
    """Rows ``idx`` of a model-sharded table, replicated — the sharded
    analog of ``tbl[idx]`` that moves only the requested rows."""

    def local(i, t):
        return _resolve_rows(t, i)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(PartitionSpec(), PartitionSpec(MODEL_AXIS, None)),
        out_specs=PartitionSpec(),
        check_rep=False,
    )(idx, tbl)


@functools.partial(jax.jit, static_argnames=("k", "mesh"))
def sharded_topk_users(
    user_idx: jax.Array,
    user_tbl: jax.Array,
    item_tbl: jax.Array,
    k: int,
    num_items: jax.Array,
    mesh: Mesh,
) -> tuple[jax.Array, jax.Array]:
    """Exact top-k over model-sharded factor tables, one dispatch per
    batch: ``([B, k] item ids, [B, k] scores)``, descending score, ties
    by ascending item id — tie-stable-identical to
    :func:`predictionio_tpu.ops.als.top_k_items_batch` on the same
    factors (CI-asserted; within a shard position order IS global id
    order, so the local ``lax.top_k`` already applies the shared rule,
    and the cross-shard reduce is the shared two-key
    :func:`ops.topk.sort_merge_topk` rule).

    ``num_items`` (the LOGICAL catalog bound masking the padding rows)
    is a TRACED scalar on purpose: online cold-start fold-ins advance it
    on every batch while the padding-slot design keeps the table SHAPE
    fixed — static, it would recompile the serving kernel per fold.

    Per-device work: one masked row-resolve + psum for the query rows,
    one ``[B,K]@[K,I/S]`` GEMM over the LOCAL item shard, a local
    top-k, and an all-gather of ``S·k`` finalists per query — per-device
    memory and FLOPs both scale as ``catalog / S``."""
    S = int(mesh.shape[MODEL_AXIS])
    i_rps = item_tbl.shape[0] // S
    kk = min(int(k), i_rps)

    def local(idx, u_l, i_l, n_items):
        q = _resolve_rows(u_l, idx)  # [B, K] true user rows
        me = jax.lax.axis_index(MODEL_AXIS)
        scores = q @ i_l.T  # [B, I/S]
        base = (me * i_rps).astype(jnp.int32)
        gid = base + jnp.arange(i_rps, dtype=jnp.int32)
        # zero padding rows must never outrank real negative scores
        scores = jnp.where(gid[None, :] < n_items, scores, -jnp.inf)
        v, p = jax.lax.top_k(scores, kk)
        gi = base + p.astype(jnp.int32)
        gv = jax.lax.all_gather(v, MODEL_AXIS, axis=1, tiled=True)
        gids = jax.lax.all_gather(gi, MODEL_AXIS, axis=1, tiled=True)
        # cross-shard reduce: the shared two-key tie rule over S*kk
        # finalists (ops/topk.sort_merge_topk — the fast barrier path
        # is illegal under manual partitioning, see its docstring)
        return sort_merge_topk(gv, gids, min(int(k), S * kk))

    P = PartitionSpec
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(MODEL_AXIS, None), P(MODEL_AXIS, None), P()),
        out_specs=(P(), P()),
        check_rep=False,
    )(user_idx, user_tbl, item_tbl, jnp.asarray(num_items, jnp.int32))


@functools.partial(jax.jit, static_argnames=("k", "kp", "mesh"))
def sharded_quantized_topk_users(
    user_idx: jax.Array,
    u_codes: jax.Array,
    u_scales: jax.Array,
    i_codes: jax.Array,
    i_scales: jax.Array,
    k: int,
    kp: int,
    num_items: jax.Array,
    mesh: Mesh,
) -> tuple[jax.Array, jax.Array]:
    """Two-stage quantized top-k over model-sharded int8 tables (``pio
    deploy --shard-factors --quantize int8``), one dispatch per batch.

    Per-device work: resolve + DEQUANTIZE the query rows from the local
    user shard (masked gather + psum of ``[B, K]`` f32 rows — codes
    never leave their shard), re-quantize the assembled queries
    in-kernel, one int8×int8 ``[B,K]@[K,I/S]`` coarse GEMM over the
    LOCAL item shard, a per-shard over-fetch of ``kp`` candidates, an
    f32 rescore of ONLY those local candidates (each shard owns its
    finalists, so the rescore gather never crosses the interconnect),
    then the usual two-level tie-stable merge of ``S·k`` rescored
    finalists. Every stage applies the shared
    :func:`ops.topk.sort_merge_topk` rule on f32 rescored scores, so the
    ordering is exact-f32-deterministic — and identical to the
    replicated quantized kernel (and the f32 exact path's tie order)
    whenever the over-fetch covers the true top-k, which is what the
    bench's recall guard measures. (The per-shard over-fetch is a
    SUPERSET of the replicated kernel's global one, so sharding can
    only widen the rescored candidate pool, never narrow it.)"""
    from predictionio_tpu.ops import quant

    S = int(mesh.shape[MODEL_AXIS])
    i_rps = i_codes.shape[0] // S
    kk = min(int(k), i_rps)
    kpp = max(kk, min(int(kp), i_rps))

    def local(idx, uc, us, ic, isc, n_items):
        rps = uc.shape[0]
        me = jax.lax.axis_index(MODEL_AXIS)
        lidx = idx - me * rps
        inr = (lidx >= 0) & (lidx < rps)
        sel = jnp.where(inr, lidx, 0)
        rows = quant.dequantize(uc[sel], us[sel])
        q = jax.lax.psum(jnp.where(inr[:, None], rows, 0.0), MODEL_AXIS)
        q_codes, q_scales = quant.quantize_rows_traced(q)
        acc = quant.int8_matmul(q_codes, ic)  # [B, I/S] int32
        approx = acc.astype(jnp.float32) * q_scales[:, None] * isc[None, :]
        base = (me * i_rps).astype(jnp.int32)
        gid = base + jnp.arange(i_rps, dtype=jnp.int32)
        approx = jnp.where(gid[None, :] < n_items, approx, -jnp.inf)
        _, p = jax.lax.top_k(approx, kpp)  # local over-fetch
        # rescore: gather + dequantize only the local finalists, score
        # against the UNQUANTIZED f32 query
        deq = quant.dequantize(ic[p], isc[p])  # [B, kpp, K]
        exact = jnp.einsum("bpk,bk->bp", deq, q)
        gi = base + p.astype(jnp.int32)
        valid = gi < n_items
        exact = jnp.where(valid, exact, -jnp.inf)
        gi = jnp.where(valid, gi, n_items)
        li, lv = sort_merge_topk(exact, gi, kk)
        gv = jax.lax.all_gather(lv, MODEL_AXIS, axis=1, tiled=True)
        gids = jax.lax.all_gather(li, MODEL_AXIS, axis=1, tiled=True)
        return sort_merge_topk(gv, gids, min(int(k), S * kk))

    P = PartitionSpec
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(),
            P(MODEL_AXIS, None),
            P(MODEL_AXIS),
            P(MODEL_AXIS, None),
            P(MODEL_AXIS),
            P(),
        ),
        out_specs=(P(), P()),
        check_rep=False,
    )(
        user_idx, u_codes, u_scales, i_codes, i_scales,
        jnp.asarray(num_items, jnp.int32),
    )


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "mesh"))
def sharded_ivf_topk(
    qvecs: jax.Array,
    index,
    k: int,
    nprobe: int,
    mesh: Mesh,
) -> tuple[jax.Array, jax.Array]:
    """IVF retrieval over cluster-major slabs sharded on the model axis
    (``index`` from :func:`predictionio_tpu.ops.ivf.shard_runtime`:
    slabs/slab_ids ``PartitionSpec('model', None, ...)``, centroids
    replicated, ``nlist`` padded to a multiple of the axis with the
    TRUE count in the static metadata).

    Stage 1 (centroid scoring + probe selection) is replicated compute —
    identical on every device, so the probe set needs no exchange.
    Stage 2 each device gathers+scores ONLY the probed clusters it owns
    (out-of-shard probe slots masked), local-merges tie-stably, and
    all-gathers ``S·k`` finalists for the same cross-shard
    :func:`ops.topk.top_k_permuted` reduce the exact path uses. Result
    rows equal the unsharded :func:`ops.ivf.ivf_topk_batch` on the same
    index, including tie order; per-device slab memory is
    ``nlist/S · W · K``."""
    S = int(mesh.shape[MODEL_AXIS])
    nlist_pad = index.slabs.shape[0]  # physical cluster rows (global)
    lists_per = nlist_pad // S
    W = index.slab_width
    nlist_true = index.nlist
    num_items = index.num_items
    quantized = index.slab_scales is not None  # int8 slab codes
    nprobe = max(1, min(int(nprobe), nlist_true))
    kk = max(1, min(int(k), nprobe * W))

    def local(q, cent, slabs_l, ids_l, scales_l):
        me = jax.lax.axis_index(MODEL_AXIS)
        if nprobe >= nlist_true:
            # every cluster probed: skip stage 1 and score this shard's
            # whole cluster-major slab table with ONE GEMM — the same
            # per-item dot shape as the exact path and the unsharded
            # nprobe==nlist mode, which is what keeps this mode
            # bit-identical to exact top-K (scores AND tie order; int8
            # slabs keep determinism over the dequantized table)
            flat = slabs_l.reshape(-1, slabs_l.shape[-1])
            if quantized:
                scores = (q @ flat.T.astype(jnp.float32)) * (
                    scales_l.reshape(1, -1)
                )
            else:
                scores = q @ flat.T  # [B, lists_per*W]
            ids = jnp.broadcast_to(
                ids_l.reshape(1, -1), scores.shape
            )
            scores = jnp.where(ids < num_items, scores, -jnp.inf)
            ids = jnp.where(ids < num_items, ids, num_items)
        else:
            cs = q @ cent.T  # [B, nlist_pad], replicated compute
            col = jnp.arange(cs.shape[-1], dtype=jnp.int32)
            cs = jnp.where(col[None, :] < nlist_true, cs, -jnp.inf)
            _, probe = jax.lax.top_k(cs, nprobe)  # global cluster ids
            lp = probe - me * lists_per
            own = (lp >= 0) & (lp < lists_per)
            sc_parts = []
            id_parts = []
            # one gather+einsum per probe SLOT (static unroll, same
            # shape discipline as the unsharded kernel) — slots owned by
            # another shard read slab 0 but are fully masked out
            for j in range(nprobe):
                sel = jnp.where(own[:, j], lp[:, j], 0)
                cand = slabs_l[sel]  # [B, W, K] — int8: 1/4 gather bytes
                ids_j = ids_l[sel]  # [B, W]
                if quantized:
                    s_j = jnp.einsum(
                        "bwk,bk->bw", cand.astype(jnp.float32), q
                    ) * scales_l[sel]
                else:
                    s_j = jnp.einsum("bwk,bk->bw", cand, q)
                valid = own[:, j, None] & (ids_j < num_items)
                sc_parts.append(jnp.where(valid, s_j, -jnp.inf))
                id_parts.append(jnp.where(valid, ids_j, num_items))
            scores = jnp.concatenate(sc_parts, axis=1)
            ids = jnp.concatenate(id_parts, axis=1)
        # local candidate order is (probe slot, lane) — NOT id order —
        # so the local merge must already be tie-stable in id space
        li, lv = sort_merge_topk(scores, ids, kk)
        gv = jax.lax.all_gather(lv, MODEL_AXIS, axis=1, tiled=True)
        gi = jax.lax.all_gather(li, MODEL_AXIS, axis=1, tiled=True)
        return sort_merge_topk(gv, gi, min(int(k), S * kk))

    P = PartitionSpec
    # zero-size scale placeholder when unquantized: shard_map wants a
    # concrete operand per spec, and a dead [S, 0] input costs nothing
    scales_arg = (
        index.slab_scales
        if quantized
        else jnp.zeros((S, 0), jnp.float32)
    )
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(),
            P(),
            P(MODEL_AXIS, None, None),
            P(MODEL_AXIS, None),
            P(MODEL_AXIS, None),
        ),
        out_specs=(P(), P()),
        check_rep=False,
    )(qvecs, index.centroids, index.slabs, index.slab_ids, scales_arg)


# ---------------------------------------------------------------------------
# Host-facing wrappers (numpy in, numpy out — what templates call)
# ---------------------------------------------------------------------------


def topk_users(
    info: ShardInfo, user_tbl, item_tbl, user_idx, k: int
) -> tuple[np.ndarray, np.ndarray]:
    """Top-``k`` for a batch of user INDICES through the sharded exact
    kernel; ``k`` buckets to a power of two (floor 16) so the jitted
    program compiles once per bucket, exactly like the exact and ANN
    paths. Returns ``([B, k] ids, [B, k] scores)`` as numpy."""
    num_items = int(info.rows["item"])
    k = max(1, min(int(k), num_items))
    kb = bucket_k(k, num_items)
    idx = jnp.asarray(np.asarray(user_idx, dtype=np.int32))
    ids, scores = sharded_topk_users(
        idx, user_tbl, item_tbl, kb, num_items, info.mesh
    )
    return np.asarray(ids)[:, :k], np.asarray(scores)[:, :k]
