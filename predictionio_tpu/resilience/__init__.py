"""Resilience layer — retries, circuit breaking, deadlines, fault injection.

At production scale transient storage/network faults are the common
case, not the exception (cf. the distributed-Spark lineage of the
reference: every MLlib stage assumes retried tasks); this package gives
the framework one vocabulary for surviving them:

* :class:`RetryPolicy` — exponential backoff + full jitter, idempotency-
  aware, budgeted by a :class:`Deadline` that is consumed *across*
  attempts and propagated ambiently (:func:`deadline_scope`);
* :class:`CircuitBreaker` — closed -> open -> half-open with probe
  requests, so a dead dependency fails fast instead of stacking
  timeouts;
* :class:`FaultInjector` — the deterministic harness that proves the
  above actually works (tests + ``bench.py`` ``resilience`` section);
* a process-wide stats registry: transports register their counters
  here and servers surface :func:`stats_snapshot` on ``/stats.json``.

Everything is strictly opt-in: the built-in defaults (0 retries, no
breaker, no deadline) reproduce the prior single-attempt behavior
byte-for-byte, guarded by ``tests/test_ci_guards.py``. The package is
stdlib-only and jax-free by contract (same guard): resilience is host
orchestration, never device work.
"""

from __future__ import annotations

import dataclasses
import threading
import weakref
from typing import Any

from predictionio_tpu.resilience.breaker import CircuitBreaker, CircuitOpenError
from predictionio_tpu.resilience.faults import FaultError, FaultInjector
from predictionio_tpu.resilience.retry import (
    Deadline,
    DeadlineExceededError,
    RetryPolicy,
    current_deadline,
    deadline_scope,
)

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "Deadline",
    "DeadlineExceededError",
    "FaultError",
    "FaultInjector",
    "RetryPolicy",
    "RpcDefaults",
    "current_deadline",
    "deadline_scope",
    "get_rpc_defaults",
    "register_stats",
    "set_rpc_defaults",
    "stats_snapshot",
]


# ---------------------------------------------------------------------------
# Stats registry: named to_json() providers, surfaced on /stats.json
# ---------------------------------------------------------------------------

_stats_lock = threading.Lock()
#: weak values: a replaced QueryService / storage client must not pin its
#: stats (nor keep reporting) after it is garbage collected
_stats_registry: "weakref.WeakValueDictionary[str, Any]" = (
    weakref.WeakValueDictionary()
)


def register_stats(name: str, provider: Any) -> None:
    """Register an object with a ``to_json()`` method under ``name``;
    later registrations replace earlier ones (latest client wins)."""
    with _stats_lock:
        _stats_registry[name] = provider


def stats_snapshot() -> dict[str, Any]:
    """``{name: provider.to_json()}`` for every live registered provider."""
    with _stats_lock:
        providers = dict(_stats_registry)
    out: dict[str, Any] = {}
    for name, provider in sorted(providers.items()):
        try:
            out[name] = provider.to_json()
        except Exception as e:  # a broken provider must not break /stats.json
            out[name] = {"error": str(e)[:200]}
    return out


# ---------------------------------------------------------------------------
# Process-wide RPC resilience defaults (set by `pio deploy --retry-*`)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RpcDefaults:
    """Fallbacks for storage transports whose source config does not set
    its own ``retries``/``breaker_*`` properties. The built-in values are
    the do-nothing configuration (single attempt, no breaker, no
    deadline) — resilience is strictly opt-in."""

    retries: int = 0
    retry_writes: bool = False
    breaker_threshold: int = 0  # 0 = breaker disabled
    breaker_reset_s: float = 5.0
    deadline_s: float = 0.0  # 0 = per-attempt timeout only


_rpc_defaults = RpcDefaults()
_rpc_defaults_lock = threading.Lock()


def set_rpc_defaults(**kwargs: Any) -> RpcDefaults:
    """Replace the process-wide RPC resilience defaults (CLI layer);
    returns the new value."""
    global _rpc_defaults
    with _rpc_defaults_lock:
        _rpc_defaults = dataclasses.replace(_rpc_defaults, **kwargs)
        return _rpc_defaults


def get_rpc_defaults() -> RpcDefaults:
    with _rpc_defaults_lock:
        return _rpc_defaults
