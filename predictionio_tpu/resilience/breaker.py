"""Circuit breaker: closed -> open -> half-open with probe requests.

When a dependency (the remote storage server, the feedback event server)
is *down*, naive callers stack full timeouts: every request pays the
whole connect/read timeout before failing, so a 30-second storage outage
turns into minutes of convoyed handler threads. The breaker converts
that into fast failures: after ``failure_threshold`` consecutive
transport failures it opens and rejects calls instantly; after
``reset_timeout_s`` it lets exactly ONE probe through (half-open) — a
probe success closes the circuit, a probe failure re-opens it for
another full reset window.

Only *transport-level* failures should be recorded — an application
error (HTTP 4xx, "unknown method") proves the dependency is up and must
``record_success``; classifying is the transport's job.

Stdlib-only by contract (tests/test_ci_guards.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

__all__ = ["CircuitBreaker", "CircuitOpenError"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitOpenError(Exception):
    """Fast failure: the circuit is open and the call was not attempted."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """Thread-safe three-state breaker with an injectable clock.

    Use either the low-level protocol — ``acquire()`` before the call
    (False = fail fast), then exactly one of ``record_success()`` /
    ``record_failure()`` — or the :meth:`call` wrapper.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_s: float = 5.0,
        name: str = "",
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if reset_timeout_s < 0:
            raise ValueError("reset_timeout_s must be >= 0")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        # monotonic counters for /stats.json
        self._opened_count = 0
        self._fast_fails = 0
        self._probes = 0

    # ------------------------------------------------------------- protocol
    def acquire(self) -> bool:
        """May this call proceed? False means the circuit is open — fail
        fast without touching the dependency."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.reset_timeout_s:
                    self._state = HALF_OPEN
                    self._probe_in_flight = True
                    self._probes += 1
                    return True
                self._fast_fails += 1
                return False
            # HALF_OPEN: one probe at a time
            if self._probe_in_flight:
                self._fast_fails += 1
                return False
            self._probe_in_flight = True
            self._probes += 1
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self._state = CLOSED

    def record_cancelled(self) -> None:
        """The caller aborted the attempt for its own reasons (e.g. a
        tight deadline starved it before the dependency could answer):
        the dependency's health is UNKNOWN, so this neither counts toward
        the failure streak nor closes the circuit — it only releases a
        half-open probe slot so the breaker cannot wedge."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_in_flight = False
                # old opened_at is kept: the next acquire may re-probe
                # immediately instead of waiting a fresh reset window
                self._state = OPEN

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                # failed probe: back to a full reset window
                self._probe_in_flight = False
                self._state = OPEN
                self._opened_at = self._clock()
                self._opened_count += 1
            elif (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._state = OPEN
                self._opened_at = self._clock()
                self._opened_count += 1

    # ------------------------------------------------------------ convenience
    @property
    def state(self) -> str:
        with self._lock:
            # surface open->half-open eligibility without mutating: an
            # operator reading /stats.json should see "open" until a
            # probe actually goes out
            return self._state

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(
                0.0, self.reset_timeout_s - (self._clock() - self._opened_at)
            )

    def call(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` under the breaker; raises :class:`CircuitOpenError`
        instead of calling when open."""
        if not self.acquire():
            raise CircuitOpenError(
                f"circuit '{self.name or 'breaker'}' is open",
                retry_after_s=self.retry_after_s(),
            )
        try:
            result = fn()
        except BaseException:
            # BaseException: a KeyboardInterrupt/SystemExit mid-probe must
            # still release the half-open probe slot or the breaker wedges
            self.record_failure()
            raise
        self.record_success()
        return result

    def to_json(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutiveFailures": self._consecutive_failures,
                "failureThreshold": self.failure_threshold,
                "resetTimeoutSeconds": self.reset_timeout_s,
                "openedCount": self._opened_count,
                "fastFails": self._fast_fails,
                "probes": self._probes,
            }
