"""Kill-9 chaos harness for the ingestion path (``pio chaos-ingest``).

Nothing in a test suite proves crash safety like actually crashing: this
harness spawns a **real event-server subprocess** on a scratch storage
directory, drives concurrent retrying writers against it over real HTTP,
SIGKILLs the server at seeded-random points mid-traffic (including while
a deliberately torn request body is on the wire), restarts it, and at
the end verifies the three invariants the rest of this repo's
crash-safety work exists to provide:

1. **zero acked loss** — every event the server acknowledged (HTTP 201)
   before any kill is present after the final restart;
2. **zero duplicates** — retried writes (same client ``eventId``) never
   double-count: the storage dedup index absorbs them;
3. **clean recovery** — the startup sweep leaves no unquarantined torn
   files (``*.tmp`` / ``*.pending``) anywhere in the store.

A final **drain phase** SIGTERMs a server started with
``--drain-deadline-s`` while writers are in flight and asserts it exits
0 within the deadline with no raw 500s (late arrivals get clean 503 +
``Retry-After``).

Writer-side faults are scheduled through the deterministic
:class:`~predictionio_tpu.resilience.faults.FaultInjector` — just before
each kill the injector aborts a burst of writer calls client-side, so
the "request abandoned exactly at the kill point" path is exercised on
every cycle, not only when the race happens to land.

Kill cycles and verdicts feed the ``chaos_ingest`` bench section (and
its CI smoke guard: >= 3 kill cycles, ``ackedLost == 0``,
``duplicates == 0``).

Stdlib-only by contract (the resilience package's piolint manifest
entry): the harness drives the server over the wire and inspects the
store through the filesystem and the REST API — it never imports the
storage layer it is trying to catch lying.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any

from predictionio_tpu.resilience.faults import FaultError, FaultInjector

__all__ = ["ChaosConfig", "ChaosError", "run_chaos_ingest"]

_ACCESS_KEY = "chaos-ingest-key"
_APP_NAME = "chaosapp"


class ChaosError(RuntimeError):
    """The harness itself could not run (setup/spawn failure) — distinct
    from a chaos verdict, which is reported, not raised."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Knobs of one chaos run (CLI: ``pio chaos-ingest``)."""

    cycles: int = 3  # SIGKILL/restart cycles
    writers: int = 4
    events_per_writer: int = 120  # across the whole run, per writer
    backend: str = "sqlite"  # sqlite | columnar (columnar forces FSYNC=true)
    seed: int = 0
    #: events streamed through POST /events/bulk.json in the bulk-writer
    #: phase (SIGKILL lands mid-stream; the whole stream is retried with
    #: the same ids until a clean summary). 0 disables the phase.
    bulk_events: int = 1000
    drain_deadline_s: float = 5.0  # the SIGTERM-under-load phase
    startup_timeout_s: float = 60.0
    #: overall wall-clock budget; expiry fails the run rather than hanging CI
    total_timeout_s: float = 300.0
    base_dir: str | None = None  # None = fresh tempdir
    keep_dir: bool = False

    def __post_init__(self) -> None:
        if self.backend not in ("sqlite", "columnar"):
            raise ValueError("backend must be 'sqlite' or 'columnar'")
        if self.cycles < 1 or self.writers < 1 or self.events_per_writer < 1:
            raise ValueError("cycles, writers, events_per_writer must be >= 1")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _ServerProc:
    """One event-server subprocess on a fixed port + scratch storage env."""

    def __init__(self, env: dict, port: int, extra_args: tuple[str, ...] = ()):
        self.port = port
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "predictionio_tpu.tools.console",
                "eventserver", "--ip", "127.0.0.1", "--port", str(port),
                *extra_args,
            ],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    def wait_ready(self, timeout_s: float) -> float:
        """Poll ``/readyz`` until 200; returns seconds to readiness."""
        t0 = time.monotonic()
        url = f"http://127.0.0.1:{self.port}/readyz"
        while time.monotonic() - t0 < timeout_s:
            if self.proc.poll() is not None:
                raise ChaosError(
                    f"event server exited rc={self.proc.returncode} before ready"
                )
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.status == 200:
                        return time.monotonic() - t0
            except Exception:
                pass
            time.sleep(0.05)
        raise ChaosError(f"event server not ready within {timeout_s:g}s")

    def kill9(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def sigterm(self) -> None:
        self.proc.send_signal(signal.SIGTERM)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=30)


class _Writers:
    """Concurrent retrying writers. Each event carries a deterministic
    client ``eventId``; any transport failure or non-201 answer is
    retried with the SAME id — the idempotent-ingestion contract is what
    makes this loop safe, and this harness is what proves it."""

    def __init__(self, port: int, n_writers: int, per_writer: int,
                 injector: FaultInjector, stop: threading.Event, seed: int):
        self.port = port
        self.injector = injector
        self.stop = stop
        self.acked: dict[str, int] = {}  # eventId -> ack count (1 expected)
        self.duplicate_acks = 0  # 201s with "duplicate": true (retries absorbed)
        #: an already-acked id re-sent WITHOUT the duplicate flag coming
        #: back means the server double-stored it — the core violation
        self.dedup_violations = 0
        self._lock = threading.Lock()
        self._threads = [
            threading.Thread(
                target=self._run, name=f"chaos-writer-{w}",
                args=(w, per_writer, random.Random(seed * 1000 + w)),
                daemon=True,
            )
            for w in range(n_writers)
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def done(self) -> bool:
        return all(not t.is_alive() for t in self._threads)

    def acked_count(self) -> int:
        with self._lock:
            return len(self.acked)

    def join(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return self.done()

    def _post(self, event_id: str, payload: bytes) -> dict:
        # the injector sits on the CLIENT side: a scheduled fault aborts
        # this call exactly where a kill-9'd connection would
        self.injector.before_call("writer-post")
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/events.json?accessKey={_ACCESS_KEY}",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())

    def _run(self, writer: int, per_writer: int, rng: random.Random) -> None:
        for i in range(per_writer):
            event_id = f"w{writer}-e{i:05d}"
            payload = json.dumps(
                {
                    "eventId": event_id,
                    "event": "rate",
                    "entityType": "user",
                    "entityId": f"u{writer}",
                    "targetEntityType": "item",
                    "targetEntityId": f"i{i % 97}",
                    "properties": {"rating": float(1 + i % 5)},
                }
            ).encode()
            while not self.stop.is_set():
                try:
                    body = self._post(event_id, payload)
                except (urllib.error.URLError, urllib.error.HTTPError,
                        ConnectionError, TimeoutError, OSError, FaultError):
                    # server down / mid-kill / injected abort: back off a
                    # touch and re-send the SAME eventId
                    time.sleep(0.05 + rng.random() * 0.15)
                    continue
                if body.get("eventId"):
                    with self._lock:
                        self.acked[event_id] = self.acked.get(event_id, 0) + 1
                        if body.get("duplicate"):
                            self.duplicate_acks += 1
                    if rng.random() < 0.15:
                        # deliberate retransmit of an ALREADY-acked event:
                        # the lost-ack retry in miniature, forced often
                        # enough to prove dedup rather than hoping the
                        # kill window produces it. Best-effort — a kill
                        # racing the probe is fine, a missing duplicate
                        # flag on a delivered answer is not.
                        try:
                            again = self._post(event_id, payload)
                        except Exception:
                            pass
                        else:
                            with self._lock:
                                if again.get("duplicate"):
                                    self.duplicate_acks += 1
                                else:
                                    self.dedup_violations += 1
                    break
                time.sleep(0.05 + rng.random() * 0.15)
            else:
                return  # harness timed out; report what was acked so far


def _torn_request(port: int, event_id: str) -> None:
    """Send a request whose body stops halfway (Content-Length promises
    more) and abandon the socket — the classic torn write a crashing
    client (or a server kill mid-read) produces. The server must never
    ack it, and no storage garbage may survive it unquarantined."""
    body = json.dumps(
        {
            "eventId": event_id,
            "event": "rate",
            "entityType": "user",
            "entityId": "torn",
            "targetEntityType": "item",
            "targetEntityId": "torn",
        }
    ).encode()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=2) as s:
            head = (
                f"POST /events.json?accessKey={_ACCESS_KEY} HTTP/1.1\r\n"
                f"Host: 127.0.0.1:{port}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n"
            ).encode()
            s.sendall(head + body[: len(body) // 2])
            # abandon mid-body; RST on close
    except OSError:
        pass  # server may already be dead — the tear still happened


def _storage_env(base: str, backend: str) -> dict:
    env = dict(os.environ)
    env.pop("PIO_JAX_PLATFORMS", None)
    env["JAX_PLATFORMS"] = "cpu"  # a sitecustomize-preloaded jax stays on CPU
    # children must resolve predictionio_tpu regardless of the caller's
    # cwd or install state (same injection `pio run` performs)
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = (
        pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    env["PIO_FS_BASEDIR"] = str(base)
    env["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "CHAOS_META"
    env["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "CHAOS_FS"
    env["PIO_STORAGE_SOURCES_CHAOS_META_TYPE"] = "sqlite"
    env["PIO_STORAGE_SOURCES_CHAOS_META_PATH"] = os.path.join(base, "meta.db")
    env["PIO_STORAGE_SOURCES_CHAOS_FS_TYPE"] = "localfs"
    env["PIO_STORAGE_SOURCES_CHAOS_FS_PATH"] = os.path.join(base, "models")
    if backend == "sqlite":
        env["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "CHAOS_META"
    else:
        env["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "CHAOS_COL"
        env["PIO_STORAGE_SOURCES_CHAOS_COL_TYPE"] = "columnar"
        env["PIO_STORAGE_SOURCES_CHAOS_COL_PATH"] = os.path.join(base, "events")
        # "acked == durable" is only a promise when the tail is fsync'd
        env["PIO_STORAGE_SOURCES_CHAOS_COL_FSYNC"] = "true"
    return env


def _setup_app(env: dict) -> None:
    proc = subprocess.run(
        [
            sys.executable, "-m", "predictionio_tpu.tools.console",
            "app", "new", _APP_NAME, "--access-key", _ACCESS_KEY,
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    if proc.returncode != 0:
        raise ChaosError(f"app setup failed: {proc.stderr[-500:]}")


def _fetch_all_events(port: int) -> list[dict]:
    url = (
        f"http://127.0.0.1:{port}/events.json?accessKey={_ACCESS_KEY}&limit=-1"
    )
    with urllib.request.urlopen(url, timeout=30) as resp:
        return json.loads(resp.read())


def _unquarantined_torn_files(base: str) -> list[str]:
    """Any ``*.tmp`` / ``*.pending`` file outside a ``quarantine/`` dir
    is a torn write the recovery sweep missed."""
    bad: list[str] = []
    for root, dirs, files in os.walk(base):
        if "quarantine" in root.split(os.sep):
            continue
        for name in files:
            if name.endswith((".tmp", ".pending", ".pending.tmp", ".repair")):
                bad.append(os.path.join(root, name))
    return sorted(bad)


class _BulkStreamAttempt:
    """One full-duplex attempt at streaming the bulk payload: the
    sender thread (caller) trickles chunked-transfer frames while a
    reader thread collects the per-chunk NDJSON statuses as they
    arrive — so a SIGKILL mid-stream leaves a truthful record of
    exactly which chunks were ACKED before the socket died."""

    def __init__(self, port: int):
        self.statuses: list[dict] = []
        self.summary: dict | None = None
        self.error: str | None = None
        self._sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        head = (
            f"POST /events/bulk.json?accessKey={_ACCESS_KEY}&chunkRows=200 "
            "HTTP/1.1\r\n"
            f"Host: 127.0.0.1:{port}\r\n"
            "Content-Type: application/x-ndjson\r\n"
            "Transfer-Encoding: chunked\r\n\r\n"
        ).encode()
        self._sock.sendall(head)
        self._reader = threading.Thread(
            target=self._read_response, name="chaos-bulk-reader", daemon=True
        )
        self._reader.start()

    def send_piece(self, piece: bytes) -> None:
        self._sock.sendall(
            f"{len(piece):X}\r\n".encode() + piece + b"\r\n"
        )

    def finish_send(self) -> None:
        self._sock.sendall(b"0\r\n\r\n")

    def _read_response(self) -> None:
        try:
            f = self._sock.makefile("rb")
            status_line = f.readline()
            if b"200" not in status_line:
                self.error = f"unexpected status {status_line!r}"
                return
            while True:  # headers
                line = f.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
            buf = b""
            while True:  # de-chunk the response stream
                size_line = f.readline()
                if not size_line:
                    break
                size = int(size_line.split(b";")[0].strip() or b"0", 16)
                if size == 0:
                    break
                buf += f.read(size)
                f.read(2)
                while b"\n" in buf:
                    line, _, buf = buf.partition(b"\n")
                    if not line.strip():
                        continue
                    obj = json.loads(line)
                    if obj.get("done"):
                        self.summary = obj
                    else:
                        self.statuses.append(obj)
        except (OSError, ValueError) as e:
            self.error = str(e)

    def wait(self, timeout_s: float) -> None:
        self._reader.join(timeout=timeout_s)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _bulk_phase(env: dict, cfg: ChaosConfig, rng: random.Random,
                base: str) -> dict:
    """Bulk-route chaos: stream ``bulk_events`` NDJSON events with
    deterministic client ids through ``POST /events/bulk.json``
    (chunked transfer, trickled), SIGKILL the server mid-stream, then
    retry the WHOLE stream with the same ids until a clean summary —
    while a side writer keeps single-event POSTs flowing so the tail
    (and, on the columnar backend, the background compaction scheduler
    started via ``--compact-*``) churns underneath. Verdict: every
    acked chunk's events survive exactly once, retries are absorbed as
    duplicates, no unquarantined torn chunk files remain."""
    port = _free_port()
    extra: tuple[str, ...] = ("--stats",)
    if cfg.backend == "columnar":
        # aggressive scheduler: compaction generation bumps land DURING
        # the bulk stream and the kill window
        extra += (
            "--compact-interval-s", "0.3",
            "--compact-tail-mb", "0.0001",
            "--compact-min-interval-s", "0.2",
        )
    server = _ServerProc(env, port, extra_args=extra)
    lines = [
        json.dumps(
            {
                "eventId": f"bulk-e{i:05d}",
                "event": "rate",
                "entityType": "user",
                "entityId": f"bu{i % 13}",
                "targetEntityType": "item",
                "targetEntityId": f"bi{i % 41}",
                "properties": {"rating": float(1 + i % 5)},
            }
        ).encode() + b"\n"
        for i in range(cfg.bulk_events)
    ]
    ids = [f"bulk-e{i:05d}" for i in range(cfg.bulk_events)]
    stop_side = threading.Event()
    side_acked: dict[str, int] = {}
    side_lock = threading.Lock()

    def side_writer() -> None:
        i = 0
        while not stop_side.is_set():
            i += 1
            eid = f"bside-e{i:05d}"
            payload = json.dumps(
                {
                    "eventId": eid,
                    "event": "rate",
                    "entityType": "user",
                    "entityId": "side",
                    "targetEntityType": "item",
                    "targetEntityId": f"si{i % 7}",
                }
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/events.json?accessKey={_ACCESS_KEY}",
                data=payload,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=5) as resp:
                    body = json.loads(resp.read())
            except Exception:
                time.sleep(0.05)
                continue
            if body.get("eventId"):
                with side_lock:
                    side_acked[eid] = side_acked.get(eid, 0) + 1
            time.sleep(0.01)

    acked_chunk_ids: set[str] = set()
    kills = 0
    attempts = 0
    report: dict[str, Any] = {"events": cfg.bulk_events}
    try:
        server.wait_ready(cfg.startup_timeout_s)
        side = threading.Thread(target=side_writer, daemon=True,
                                name="chaos-bulk-side")
        side.start()
        deadline = time.monotonic() + cfg.total_timeout_s / 2
        summary = None
        while summary is None and time.monotonic() < deadline:
            attempts += 1
            kill_this_attempt = kills == 0
            kill_at = rng.uniform(0.3, 0.7) * len(lines)
            try:
                attempt = _BulkStreamAttempt(port)
            except OSError:
                time.sleep(0.1)
                continue
            try:
                sent = 0
                for lo in range(0, len(lines), 100):
                    attempt.send_piece(b"".join(lines[lo:lo + 100]))
                    sent += 100
                    time.sleep(0.005)
                    if kill_this_attempt and sent >= kill_at:
                        server.kill9()
                        kills += 1
                        break
                else:
                    attempt.finish_send()
                    attempt.wait(30.0)
                    summary = attempt.summary
            except OSError:
                pass  # mid-kill socket death: the retry owns recovery
            finally:
                attempt.wait(2.0)
                for st in attempt.statuses:
                    lo = int(st.get("lineStart", 0))
                    n = int(st.get("received", 0))
                    if st.get("storageError") is None:
                        acked_chunk_ids.update(ids[lo:lo + n])
                attempt.close()
            if kill_this_attempt and kills:
                server = _ServerProc(env, port, extra_args=extra)
                server.wait_ready(cfg.startup_timeout_s)
        compactions = None
        if cfg.backend == "columnar" and summary is not None:
            # the side writer keeps the tail growing past the (tiny)
            # watermark; wait for the scheduler to actually fire so the
            # exactly-once verification below runs AGAINST a generation
            # bump, not merely next to a dormant thread
            stats_url = (
                f"http://127.0.0.1:{port}/stats.json?accessKey={_ACCESS_KEY}"
            )
            wait_until = time.monotonic() + 5.0
            while time.monotonic() < wait_until:
                try:
                    with urllib.request.urlopen(stats_url, timeout=5) as resp:
                        compactions = (
                            json.loads(resp.read())
                            .get("compaction", {})
                            .get("compactions")
                        )
                except Exception:
                    compactions = None
                if compactions:
                    break
                time.sleep(0.2)
        stop_side.set()
        side.join(timeout=10)
        stored = _fetch_all_events(port)
        counts: dict[str, int] = {}
        for evd in stored:
            eid = evd.get("eventId") or ""
            counts[eid] = counts.get(eid, 0) + 1
        bulk_lost = sorted(
            e for e in acked_chunk_ids if counts.get(e, 0) == 0
        )
        bulk_dups = sorted(
            e for e in counts
            if e.startswith(("bulk-", "bside-")) and counts[e] > 1
        )
        with side_lock:
            side_lost = sorted(
                e for e in side_acked if counts.get(e, 0) == 0
            )
        report.update(
            attempts=attempts,
            kills=kills,
            completed=summary is not None,
            summary=summary,
            ackedChunkEvents=len(acked_chunk_ids),
            ackedLost=len(bulk_lost),
            ackedLostIds=bulk_lost[:20],
            duplicates=len(bulk_dups),
            duplicateIds=bulk_dups[:20],
            sideAcked=len(side_acked),
            sideAckedLost=len(side_lost),
            schedulerCompactions=compactions,
            unquarantinedTornFiles=len(_unquarantined_torn_files(base)),
        )
    finally:
        stop_side.set()
        server.stop()
    report["ok"] = bool(
        report.get("completed")
        and report.get("kills", 0) >= 1
        and report.get("ackedLost") == 0
        and report.get("duplicates") == 0
        and report.get("sideAckedLost") == 0
        and report.get("unquarantinedTornFiles") == 0
        and (report.get("summary") or {}).get("stored", 0)
        + (report.get("summary") or {}).get("duplicates", 0)
        == cfg.bulk_events
        # columnar runs the background scheduler underneath the phase;
        # a run where it never fired proves nothing about coordination
        and (
            cfg.backend != "columnar"
            or bool(report.get("schedulerCompactions"))
        )
    )
    return report


def _drain_phase(env: dict, cfg: ChaosConfig, rng: random.Random) -> dict:
    """SIGTERM under load: a fresh server with ``--drain-deadline-s``
    gets concurrent writers, then SIGTERM mid-traffic. Verdict: exit 0
    within the deadline (+ grace), every response a 201 or a clean 503,
    zero raw 500s / dropped connections after the ack."""
    port = _free_port()
    server = _ServerProc(
        env, port, extra_args=("--drain-deadline-s", str(cfg.drain_deadline_s))
    )
    statuses: list[int] = []
    lock = threading.Lock()
    stop = threading.Event()

    def drain_writer(w: int) -> None:
        i = 0
        while not stop.is_set():
            i += 1
            payload = json.dumps(
                {
                    "eventId": f"drain-w{w}-e{i}",
                    "event": "rate",
                    "entityType": "user",
                    "entityId": f"d{w}",
                    "targetEntityType": "item",
                    "targetEntityId": f"i{i % 7}",
                }
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/events.json?accessKey={_ACCESS_KEY}",
                data=payload,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    status = resp.status
            except urllib.error.HTTPError as e:
                status = e.code
            except OSError:
                # listener already gone (post-drain) — not a protocol
                # violation, the request was never admitted
                break
            with lock:
                statuses.append(status)
            time.sleep(0.005)

    try:
        server.wait_ready(cfg.startup_timeout_s)
        writers = [
            threading.Thread(target=drain_writer, args=(w,), daemon=True)
            for w in range(cfg.writers)
        ]
        for t in writers:
            t.start()
        time.sleep(0.3 + rng.random() * 0.2)  # real traffic in flight
        t_term = time.monotonic()
        server.sigterm()
        try:
            exit_code = server.proc.wait(
                timeout=cfg.drain_deadline_s + cfg.startup_timeout_s
            )
        except subprocess.TimeoutExpired:
            server.stop()
            return {"exitCode": None, "error": "drain never exited"}
        exit_seconds = time.monotonic() - t_term
        stop.set()
        for t in writers:
            t.join(timeout=10)
    finally:
        stop.set()
        server.stop()
    with lock:
        counts = {str(s): statuses.count(s) for s in sorted(set(statuses))}
        raw_500s = sum(1 for s in statuses if s >= 500 and s != 503)
    return {
        "exitCode": exit_code,
        "exitSeconds": round(exit_seconds, 3),
        "withinDeadline": exit_seconds <= cfg.drain_deadline_s + 2.0,
        "responses": counts,
        "raw500s": raw_500s,
        "drainDeadlineSeconds": cfg.drain_deadline_s,
    }


def run_chaos_ingest(cfg: ChaosConfig) -> dict:
    """Run the full harness; returns the report dict (``report["ok"]`` is
    the overall verdict — the CLI exit code and the bench smoke guard key
    off the individual invariants)."""
    base = cfg.base_dir or tempfile.mkdtemp(prefix="pio_chaos_")
    os.makedirs(base, exist_ok=True)
    env = _storage_env(base, cfg.backend)
    rng = random.Random(cfg.seed)
    injector = FaultInjector()
    t_start = time.monotonic()
    report: dict[str, Any] = {
        "backend": cfg.backend,
        "cycles": cfg.cycles,
        "writers": cfg.writers,
        "eventsPerWriter": cfg.events_per_writer,
        "seed": cfg.seed,
    }
    port = _free_port()
    server: _ServerProc | None = None
    stop = threading.Event()
    try:
        _setup_app(env)
        server = _ServerProc(env, port)
        cold_start = server.wait_ready(cfg.startup_timeout_s)
        writers = _Writers(
            port, cfg.writers, cfg.events_per_writer, injector, stop, cfg.seed
        )
        writers.start()
        recovery_s: list[float] = []
        kills = 0
        total = cfg.writers * cfg.events_per_writer
        for cycle in range(cfg.cycles):
            # kill points are keyed to writer PROGRESS, not wall time, so
            # every kill is guaranteed to land mid-stream (with work both
            # behind it — acked events that must survive — and ahead of
            # it — events whose retries must converge after restart). The
            # seeded jitter moves each point around its progress anchor.
            target = max(
                1,
                int(total * (cycle + 1) / (cfg.cycles + 1))
                - rng.randrange(max(1, total // (4 * cfg.cycles))),
            )
            while (
                writers.acked_count() < target
                and not writers.done()
                and time.monotonic() - t_start < cfg.total_timeout_s
            ):
                time.sleep(0.01)
            # abort a burst of in-flight writer calls client-side at the
            # exact kill point (deterministic via the injector schedule)
            # and put one torn half-request on the wire
            injector.fail_next(cfg.writers)
            _torn_request(port, f"torn-c{cycle}")
            server.kill9()
            kills += 1
            time.sleep(0.05 + rng.random() * 0.2)  # writers bang on a dead port
            server = _ServerProc(env, port)
            recovery_s.append(server.wait_ready(cfg.startup_timeout_s))
        # final convergence: writers finish acking everything
        budget = cfg.total_timeout_s - (time.monotonic() - t_start)
        finished = writers.join(max(5.0, budget))
        stop.set()

        expected = {
            f"w{w}-e{i:05d}"
            for w in range(cfg.writers)
            for i in range(cfg.events_per_writer)
        }
        acked = dict(writers.acked)
        stored = _fetch_all_events(port)
        stored_counts: dict[str, int] = {}
        for ev in stored:
            eid = ev.get("eventId") or ""
            stored_counts[eid] = stored_counts.get(eid, 0) + 1
        acked_lost = sorted(e for e in acked if stored_counts.get(e, 0) == 0)
        duplicates = sorted(
            e for e, n in stored_counts.items() if n > 1
        )
        torn_acked = [e for e in stored_counts if e.startswith("torn-")]
        torn_files = _unquarantined_torn_files(base)
        report.update(
            killCycles=kills,
            writersFinished=finished,
            ackedTotal=len(acked),
            ackedExpected=len(expected),
            ackedLost=len(acked_lost),
            ackedLostIds=acked_lost[:20],
            duplicates=len(duplicates),
            duplicateIds=duplicates[:20],
            duplicateAcksAbsorbed=writers.duplicate_acks,
            dedupViolations=writers.dedup_violations,
            tornRequestsStored=len(torn_acked),
            unquarantinedTornFiles=len(torn_files),
            unquarantinedTornFilePaths=torn_files[:20],
            coldStartSeconds=round(cold_start, 3),
            recoverySeconds=[round(s, 3) for s in recovery_s],
            meanRecoverySeconds=round(sum(recovery_s) / len(recovery_s), 3)
            if recovery_s
            else None,
            injector=injector.to_json(),
        )
    finally:
        stop.set()
        if server is not None:
            server.stop()
    if cfg.bulk_events > 0:
        report["bulk"] = _bulk_phase(env, cfg, rng, base)
    report["drain"] = _drain_phase(env, cfg, rng)
    if not cfg.keep_dir and cfg.base_dir is None:
        shutil.rmtree(base, ignore_errors=True)
    else:
        report["storageDir"] = base
    drain = report["drain"]
    report["ok"] = bool(
        report.get("killCycles", 0) >= cfg.cycles
        and report.get("writersFinished")
        and report.get("ackedLost") == 0
        and report.get("duplicates") == 0
        and report.get("dedupViolations") == 0
        and report.get("tornRequestsStored") == 0
        and report.get("unquarantinedTornFiles") == 0
        and (cfg.bulk_events <= 0 or report.get("bulk", {}).get("ok"))
        and drain.get("exitCode") == 0
        and drain.get("raw500s") == 0
        and drain.get("withinDeadline")
    )
    return report
