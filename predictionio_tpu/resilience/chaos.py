"""Kill-9 chaos harness for the ingestion path (``pio chaos-ingest``).

Nothing in a test suite proves crash safety like actually crashing: this
harness spawns a **real event-server subprocess** on a scratch storage
directory, drives concurrent retrying writers against it over real HTTP,
SIGKILLs the server at seeded-random points mid-traffic (including while
a deliberately torn request body is on the wire), restarts it, and at
the end verifies the three invariants the rest of this repo's
crash-safety work exists to provide:

1. **zero acked loss** — every event the server acknowledged (HTTP 201)
   before any kill is present after the final restart;
2. **zero duplicates** — retried writes (same client ``eventId``) never
   double-count: the storage dedup index absorbs them;
3. **clean recovery** — the startup sweep leaves no unquarantined torn
   files (``*.tmp`` / ``*.pending``) anywhere in the store.

A final **drain phase** SIGTERMs a server started with
``--drain-deadline-s`` while writers are in flight and asserts it exits
0 within the deadline with no raw 500s (late arrivals get clean 503 +
``Retry-After``).

Writer-side faults are scheduled through the deterministic
:class:`~predictionio_tpu.resilience.faults.FaultInjector` — just before
each kill the injector aborts a burst of writer calls client-side, so
the "request abandoned exactly at the kill point" path is exercised on
every cycle, not only when the race happens to land.

Kill cycles and verdicts feed the ``chaos_ingest`` bench section (and
its CI smoke guard: >= 3 kill cycles, ``ackedLost == 0``,
``duplicates == 0``).

Stdlib-only by contract (the resilience package's piolint manifest
entry): the harness drives the server over the wire and inspects the
store through the filesystem and the REST API — it never imports the
storage layer it is trying to catch lying.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
import zlib
from typing import Any

from predictionio_tpu.resilience.faults import FaultError, FaultInjector

__all__ = [
    "ChaosConfig",
    "ChaosError",
    "FleetChaosConfig",
    "ServeChaosConfig",
    "run_chaos_fleet",
    "run_chaos_ingest",
    "run_chaos_partitioned",
    "run_chaos_serve",
]

_ACCESS_KEY = "chaos-ingest-key"
_APP_NAME = "chaosapp"


class ChaosError(RuntimeError):
    """The harness itself could not run (setup/spawn failure) — distinct
    from a chaos verdict, which is reported, not raised."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Knobs of one chaos run (CLI: ``pio chaos-ingest``)."""

    cycles: int = 3  # SIGKILL/restart cycles
    writers: int = 4
    events_per_writer: int = 120  # across the whole run, per writer
    backend: str = "sqlite"  # sqlite | columnar (columnar forces FSYNC=true)
    seed: int = 0
    #: events streamed through POST /events/bulk.json in the bulk-writer
    #: phase (SIGKILL lands mid-stream; the whole stream is retried with
    #: the same ids until a clean summary). 0 disables the phase.
    bulk_events: int = 1000
    drain_deadline_s: float = 5.0  # the SIGTERM-under-load phase
    #: >1 adds the partitioned-ingest drill: a columnar store with
    #: PARTITIONS=P (its own scratch dir — partitioned stores are sealed
    #: by a marker and never share a path with a plain one), one
    #: partition's appender chaos-killed mid-bulk-stream (torn tail bytes
    #: + dead thread — the in-process kill-9 signature), then a real
    #: whole-server SIGKILL mid-retry. Verdict: zero acked loss, zero
    #: duplicates, surviving partitions kept storing while the victim
    #: failed, and the killed partition catches up after restart.
    partitions: int = 1
    #: with ``partitions``: replicate each partition across N stores and
    #: require ``ack_quorum`` fsync-durable copies per ack; the drill then
    #: also kills one non-leader replica (quorum loss must fail that
    #: partition's appends loudly and flip /readyz) and asserts replica
    #: catch-up after restart
    replication: int = 0
    ack_quorum: int = 0  # 0 = majority default (replication//2 + 1)
    startup_timeout_s: float = 60.0
    #: overall wall-clock budget; expiry fails the run rather than hanging CI
    total_timeout_s: float = 300.0
    base_dir: str | None = None  # None = fresh tempdir
    keep_dir: bool = False

    def __post_init__(self) -> None:
        if self.backend not in ("sqlite", "columnar"):
            raise ValueError("backend must be 'sqlite' or 'columnar'")
        if self.cycles < 1 or self.writers < 1 or self.events_per_writer < 1:
            raise ValueError("cycles, writers, events_per_writer must be >= 1")
        if self.partitions < 1:
            raise ValueError("partitions must be >= 1")
        if self.replication < 0 or self.replication == 1:
            raise ValueError("replication must be 0 (off) or >= 2")
        if self.ack_quorum and not self.replication:
            raise ValueError("ack-quorum requires replication")
        if self.replication and self.ack_quorum > self.replication:
            raise ValueError("ack-quorum cannot exceed replication")
        if self.replication and self.partitions < 2:
            raise ValueError(
                "the replicated drill needs partitions >= 2: the replica "
                "kill must leave OTHER partitions making progress"
            )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _ServerProc:
    """One event-server subprocess on a fixed port + scratch storage env."""

    def __init__(self, env: dict, port: int, extra_args: tuple[str, ...] = ()):
        self.port = port
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "predictionio_tpu.tools.console",
                "eventserver", "--ip", "127.0.0.1", "--port", str(port),
                *extra_args,
            ],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    def wait_ready(self, timeout_s: float) -> float:
        """Poll ``/readyz`` until 200; returns seconds to readiness."""
        t0 = time.monotonic()
        url = f"http://127.0.0.1:{self.port}/readyz"
        while time.monotonic() - t0 < timeout_s:
            if self.proc.poll() is not None:
                raise ChaosError(
                    f"event server exited rc={self.proc.returncode} before ready"
                )
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.status == 200:
                        return time.monotonic() - t0
            except Exception:
                pass
            time.sleep(0.05)
        raise ChaosError(f"event server not ready within {timeout_s:g}s")

    def kill9(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def sigterm(self) -> None:
        self.proc.send_signal(signal.SIGTERM)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=30)


class _Writers:
    """Concurrent retrying writers. Each event carries a deterministic
    client ``eventId``; any transport failure or non-201 answer is
    retried with the SAME id — the idempotent-ingestion contract is what
    makes this loop safe, and this harness is what proves it."""

    def __init__(self, port: int, n_writers: int, per_writer: int,
                 injector: FaultInjector, stop: threading.Event, seed: int):
        self.port = port
        self.injector = injector
        self.stop = stop
        self.acked: dict[str, int] = {}  # eventId -> ack count (1 expected)
        self.duplicate_acks = 0  # 201s with "duplicate": true (retries absorbed)
        #: an already-acked id re-sent WITHOUT the duplicate flag coming
        #: back means the server double-stored it — the core violation
        self.dedup_violations = 0
        self._lock = threading.Lock()
        self._threads = [
            threading.Thread(
                target=self._run, name=f"chaos-writer-{w}",
                args=(w, per_writer, random.Random(seed * 1000 + w)),
                daemon=True,
            )
            for w in range(n_writers)
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def done(self) -> bool:
        return all(not t.is_alive() for t in self._threads)

    def acked_count(self) -> int:
        with self._lock:
            return len(self.acked)

    def join(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return self.done()

    def _post(self, event_id: str, payload: bytes) -> dict:
        # the injector sits on the CLIENT side: a scheduled fault aborts
        # this call exactly where a kill-9'd connection would
        self.injector.before_call("writer-post")
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/events.json?accessKey={_ACCESS_KEY}",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())

    def _run(self, writer: int, per_writer: int, rng: random.Random) -> None:
        for i in range(per_writer):
            event_id = f"w{writer}-e{i:05d}"
            payload = json.dumps(
                {
                    "eventId": event_id,
                    "event": "rate",
                    "entityType": "user",
                    "entityId": f"u{writer}",
                    "targetEntityType": "item",
                    "targetEntityId": f"i{i % 97}",
                    "properties": {"rating": float(1 + i % 5)},
                }
            ).encode()
            while not self.stop.is_set():
                try:
                    body = self._post(event_id, payload)
                except (urllib.error.URLError, urllib.error.HTTPError,
                        ConnectionError, TimeoutError, OSError, FaultError):
                    # server down / mid-kill / injected abort: back off a
                    # touch and re-send the SAME eventId
                    time.sleep(0.05 + rng.random() * 0.15)
                    continue
                if body.get("eventId"):
                    with self._lock:
                        self.acked[event_id] = self.acked.get(event_id, 0) + 1
                        if body.get("duplicate"):
                            self.duplicate_acks += 1
                    if rng.random() < 0.15:
                        # deliberate retransmit of an ALREADY-acked event:
                        # the lost-ack retry in miniature, forced often
                        # enough to prove dedup rather than hoping the
                        # kill window produces it. Best-effort — a kill
                        # racing the probe is fine, a missing duplicate
                        # flag on a delivered answer is not.
                        try:
                            again = self._post(event_id, payload)
                        except Exception:
                            pass
                        else:
                            with self._lock:
                                if again.get("duplicate"):
                                    self.duplicate_acks += 1
                                else:
                                    self.dedup_violations += 1
                    break
                time.sleep(0.05 + rng.random() * 0.15)
            else:
                return  # harness timed out; report what was acked so far


def _torn_request(port: int, event_id: str) -> None:
    """Send a request whose body stops halfway (Content-Length promises
    more) and abandon the socket — the classic torn write a crashing
    client (or a server kill mid-read) produces. The server must never
    ack it, and no storage garbage may survive it unquarantined."""
    body = json.dumps(
        {
            "eventId": event_id,
            "event": "rate",
            "entityType": "user",
            "entityId": "torn",
            "targetEntityType": "item",
            "targetEntityId": "torn",
        }
    ).encode()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=2) as s:
            head = (
                f"POST /events.json?accessKey={_ACCESS_KEY} HTTP/1.1\r\n"
                f"Host: 127.0.0.1:{port}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n"
            ).encode()
            s.sendall(head + body[: len(body) // 2])
            # abandon mid-body; RST on close
    except OSError:
        pass  # server may already be dead — the tear still happened


def _storage_env(base: str, backend: str) -> dict:
    env = dict(os.environ)
    env.pop("PIO_JAX_PLATFORMS", None)
    env["JAX_PLATFORMS"] = "cpu"  # a sitecustomize-preloaded jax stays on CPU
    # children must resolve predictionio_tpu regardless of the caller's
    # cwd or install state (same injection `pio run` performs)
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = (
        pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    env["PIO_FS_BASEDIR"] = str(base)
    env["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "CHAOS_META"
    env["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "CHAOS_FS"
    env["PIO_STORAGE_SOURCES_CHAOS_META_TYPE"] = "sqlite"
    env["PIO_STORAGE_SOURCES_CHAOS_META_PATH"] = os.path.join(base, "meta.db")
    env["PIO_STORAGE_SOURCES_CHAOS_FS_TYPE"] = "localfs"
    env["PIO_STORAGE_SOURCES_CHAOS_FS_PATH"] = os.path.join(base, "models")
    if backend == "sqlite":
        env["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "CHAOS_META"
    else:
        env["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "CHAOS_COL"
        env["PIO_STORAGE_SOURCES_CHAOS_COL_TYPE"] = "columnar"
        env["PIO_STORAGE_SOURCES_CHAOS_COL_PATH"] = os.path.join(base, "events")
        # "acked == durable" is only a promise when the tail is fsync'd
        env["PIO_STORAGE_SOURCES_CHAOS_COL_FSYNC"] = "true"
    return env


def _setup_app(env: dict) -> None:
    proc = subprocess.run(
        [
            sys.executable, "-m", "predictionio_tpu.tools.console",
            "app", "new", _APP_NAME, "--access-key", _ACCESS_KEY,
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    if proc.returncode != 0:
        raise ChaosError(f"app setup failed: {proc.stderr[-500:]}")


def _fetch_all_events(port: int) -> list[dict]:
    url = (
        f"http://127.0.0.1:{port}/events.json?accessKey={_ACCESS_KEY}&limit=-1"
    )
    with urllib.request.urlopen(url, timeout=30) as resp:
        return json.loads(resp.read())


def _unquarantined_torn_files(base: str) -> list[str]:
    """Any ``*.tmp`` / ``*.pending`` file outside a ``quarantine/`` dir
    is a torn write the recovery sweep missed."""
    bad: list[str] = []
    for root, dirs, files in os.walk(base):
        if "quarantine" in root.split(os.sep):
            continue
        for name in files:
            if name.endswith((".tmp", ".pending", ".pending.tmp", ".repair")):
                bad.append(os.path.join(root, name))
    return sorted(bad)


class _BulkStreamAttempt:
    """One full-duplex attempt at streaming the bulk payload: the
    sender thread (caller) trickles chunked-transfer frames while a
    reader thread collects the per-chunk NDJSON statuses as they
    arrive — so a SIGKILL mid-stream leaves a truthful record of
    exactly which chunks were ACKED before the socket died."""

    def __init__(self, port: int):
        self.statuses: list[dict] = []
        self.summary: dict | None = None
        self.error: str | None = None
        self._sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        head = (
            f"POST /events/bulk.json?accessKey={_ACCESS_KEY}&chunkRows=200 "
            "HTTP/1.1\r\n"
            f"Host: 127.0.0.1:{port}\r\n"
            "Content-Type: application/x-ndjson\r\n"
            "Transfer-Encoding: chunked\r\n\r\n"
        ).encode()
        self._sock.sendall(head)
        self._reader = threading.Thread(
            target=self._read_response, name="chaos-bulk-reader", daemon=True
        )
        self._reader.start()

    def send_piece(self, piece: bytes) -> None:
        self._sock.sendall(
            f"{len(piece):X}\r\n".encode() + piece + b"\r\n"
        )

    def finish_send(self) -> None:
        self._sock.sendall(b"0\r\n\r\n")

    def _read_response(self) -> None:
        try:
            f = self._sock.makefile("rb")
            status_line = f.readline()
            if b"200" not in status_line:
                self.error = f"unexpected status {status_line!r}"
                return
            while True:  # headers
                line = f.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
            buf = b""
            while True:  # de-chunk the response stream
                size_line = f.readline()
                if not size_line:
                    break
                size = int(size_line.split(b";")[0].strip() or b"0", 16)
                if size == 0:
                    break
                buf += f.read(size)
                f.read(2)
                while b"\n" in buf:
                    line, _, buf = buf.partition(b"\n")
                    if not line.strip():
                        continue
                    obj = json.loads(line)
                    if obj.get("done"):
                        self.summary = obj
                    else:
                        self.statuses.append(obj)
        except (OSError, ValueError) as e:
            self.error = str(e)

    def wait(self, timeout_s: float) -> None:
        self._reader.join(timeout=timeout_s)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _bulk_phase(env: dict, cfg: ChaosConfig, rng: random.Random,
                base: str) -> dict:
    """Bulk-route chaos: stream ``bulk_events`` NDJSON events with
    deterministic client ids through ``POST /events/bulk.json``
    (chunked transfer, trickled), SIGKILL the server mid-stream, then
    retry the WHOLE stream with the same ids until a clean summary —
    while a side writer keeps single-event POSTs flowing so the tail
    (and, on the columnar backend, the background compaction scheduler
    started via ``--compact-*``) churns underneath. Verdict: every
    acked chunk's events survive exactly once, retries are absorbed as
    duplicates, no unquarantined torn chunk files remain."""
    port = _free_port()
    extra: tuple[str, ...] = ("--stats",)
    if cfg.backend == "columnar":
        # aggressive scheduler: compaction generation bumps land DURING
        # the bulk stream and the kill window
        extra += (
            "--compact-interval-s", "0.3",
            "--compact-tail-mb", "0.0001",
            "--compact-min-interval-s", "0.2",
        )
    server = _ServerProc(env, port, extra_args=extra)
    lines = [
        json.dumps(
            {
                "eventId": f"bulk-e{i:05d}",
                "event": "rate",
                "entityType": "user",
                "entityId": f"bu{i % 13}",
                "targetEntityType": "item",
                "targetEntityId": f"bi{i % 41}",
                "properties": {"rating": float(1 + i % 5)},
            }
        ).encode() + b"\n"
        for i in range(cfg.bulk_events)
    ]
    ids = [f"bulk-e{i:05d}" for i in range(cfg.bulk_events)]
    stop_side = threading.Event()
    side_acked: dict[str, int] = {}
    side_lock = threading.Lock()

    def side_writer() -> None:
        i = 0
        while not stop_side.is_set():
            i += 1
            eid = f"bside-e{i:05d}"
            payload = json.dumps(
                {
                    "eventId": eid,
                    "event": "rate",
                    "entityType": "user",
                    "entityId": "side",
                    "targetEntityType": "item",
                    "targetEntityId": f"si{i % 7}",
                }
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/events.json?accessKey={_ACCESS_KEY}",
                data=payload,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=5) as resp:
                    body = json.loads(resp.read())
            except Exception:
                time.sleep(0.05)
                continue
            if body.get("eventId"):
                with side_lock:
                    side_acked[eid] = side_acked.get(eid, 0) + 1
            time.sleep(0.01)

    acked_chunk_ids: set[str] = set()
    kills = 0
    attempts = 0
    report: dict[str, Any] = {"events": cfg.bulk_events}
    try:
        server.wait_ready(cfg.startup_timeout_s)
        side = threading.Thread(target=side_writer, daemon=True,
                                name="chaos-bulk-side")
        side.start()
        deadline = time.monotonic() + cfg.total_timeout_s / 2
        summary = None
        while summary is None and time.monotonic() < deadline:
            attempts += 1
            kill_this_attempt = kills == 0
            kill_at = rng.uniform(0.3, 0.7) * len(lines)
            try:
                attempt = _BulkStreamAttempt(port)
            except OSError:
                time.sleep(0.1)
                continue
            try:
                sent = 0
                for lo in range(0, len(lines), 100):
                    attempt.send_piece(b"".join(lines[lo:lo + 100]))
                    sent += 100
                    time.sleep(0.005)
                    if kill_this_attempt and sent >= kill_at:
                        server.kill9()
                        kills += 1
                        break
                else:
                    attempt.finish_send()
                    attempt.wait(30.0)
                    summary = attempt.summary
            except OSError:
                pass  # mid-kill socket death: the retry owns recovery
            finally:
                attempt.wait(2.0)
                for st in attempt.statuses:
                    lo = int(st.get("lineStart", 0))
                    n = int(st.get("received", 0))
                    if st.get("storageError") is None:
                        acked_chunk_ids.update(ids[lo:lo + n])
                attempt.close()
            if kill_this_attempt and kills:
                server = _ServerProc(env, port, extra_args=extra)
                server.wait_ready(cfg.startup_timeout_s)
        compactions = None
        if cfg.backend == "columnar" and summary is not None:
            # the side writer keeps the tail growing past the (tiny)
            # watermark; wait for the scheduler to actually fire so the
            # exactly-once verification below runs AGAINST a generation
            # bump, not merely next to a dormant thread
            stats_url = (
                f"http://127.0.0.1:{port}/stats.json?accessKey={_ACCESS_KEY}"
            )
            wait_until = time.monotonic() + 5.0
            while time.monotonic() < wait_until:
                try:
                    with urllib.request.urlopen(stats_url, timeout=5) as resp:
                        compactions = (
                            json.loads(resp.read())
                            .get("compaction", {})
                            .get("compactions")
                        )
                except Exception:
                    compactions = None
                if compactions:
                    break
                time.sleep(0.2)
        stop_side.set()
        side.join(timeout=10)
        stored = _fetch_all_events(port)
        counts: dict[str, int] = {}
        for evd in stored:
            eid = evd.get("eventId") or ""
            counts[eid] = counts.get(eid, 0) + 1
        bulk_lost = sorted(
            e for e in acked_chunk_ids if counts.get(e, 0) == 0
        )
        bulk_dups = sorted(
            e for e in counts
            if e.startswith(("bulk-", "bside-")) and counts[e] > 1
        )
        with side_lock:
            side_lost = sorted(
                e for e in side_acked if counts.get(e, 0) == 0
            )
        report.update(
            attempts=attempts,
            kills=kills,
            completed=summary is not None,
            summary=summary,
            ackedChunkEvents=len(acked_chunk_ids),
            ackedLost=len(bulk_lost),
            ackedLostIds=bulk_lost[:20],
            duplicates=len(bulk_dups),
            duplicateIds=bulk_dups[:20],
            sideAcked=len(side_acked),
            sideAckedLost=len(side_lost),
            schedulerCompactions=compactions,
            unquarantinedTornFiles=len(_unquarantined_torn_files(base)),
        )
    finally:
        stop_side.set()
        server.stop()
    report["ok"] = bool(
        report.get("completed")
        and report.get("kills", 0) >= 1
        and report.get("ackedLost") == 0
        and report.get("duplicates") == 0
        and report.get("sideAckedLost") == 0
        and report.get("unquarantinedTornFiles") == 0
        and (report.get("summary") or {}).get("stored", 0)
        + (report.get("summary") or {}).get("duplicates", 0)
        == cfg.bulk_events
        # columnar runs the background scheduler underneath the phase;
        # a run where it never fired proves nothing about coordination
        and (
            cfg.backend != "columnar"
            or bool(report.get("schedulerCompactions"))
        )
    )
    return report


def _partition_of(entity_type: str, entity_id: str, partitions: int) -> int:
    """Inline recomputation of the store's crc32 entity routing. The
    harness is stdlib-only by contract and must not import the storage
    layer it is auditing — an independent copy of the hash is the point:
    if the store ever drifts from it, the killed-partition catch-up
    check fails loudly."""
    return zlib.crc32(f"{entity_type}\x00{entity_id}".encode()) % partitions


def _acked_ids(status: dict, ids: list[str]) -> list[str]:
    """Event ids one bulk chunk status ACKED: every received line minus
    the per-line failures. A whole-chunk ``storageError`` or a truncated
    error list acks nothing — the bar is "no acked event may be lost",
    so under-counting acks is always the safe direction."""
    if status.get("storageError") is not None or status.get("errorsTruncated"):
        return []
    lo = int(status.get("lineStart", 0))
    n = int(status.get("received", 0))
    failed = {
        int(e.get("line", -1))
        for e in status.get("errors", ())
        if int(e.get("status", 0)) >= 400
    }
    return [ids[i] for i in range(lo, min(lo + n, len(ids))) if i not in failed]


def _get_json(port: int, path: str, timeout_s: float = 5.0):
    url = f"http://127.0.0.1:{port}{path}?accessKey={_ACCESS_KEY}"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return json.loads(resp.read())
    except Exception:
        return None


def _wait_http_status(
    port: int, path: str, want: int, timeout_s: float
) -> bool:
    """Poll ``path`` until it answers with status ``want``."""
    deadline = time.monotonic() + timeout_s
    url = f"http://127.0.0.1:{port}{path}"
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                code = resp.status
        except urllib.error.HTTPError as e:
            e.read()
            code = e.code
        except Exception:
            code = 0
        if code == want:
            return True
        time.sleep(0.1)
    return False


def _partitioned_env(base: str, cfg: ChaosConfig) -> tuple[dict, str]:
    """Columnar EVENTDATA env with PARTITIONS (and, when configured,
    REPLICATION/ACK_QUORUM) on a drill-private store dir — a partitioned
    store is sealed by its ``partitions.json`` marker and must never
    share a path with the plain store the other phases use."""
    env = _storage_env(base, "columnar")
    store_dir = os.path.join(base, "events_part")
    env["PIO_STORAGE_SOURCES_CHAOS_COL_PATH"] = store_dir
    env["PIO_STORAGE_SOURCES_CHAOS_COL_PARTITIONS"] = str(cfg.partitions)
    if cfg.replication:
        env["PIO_STORAGE_SOURCES_CHAOS_COL_REPLICATION"] = str(cfg.replication)
        env["PIO_STORAGE_SOURCES_CHAOS_COL_ACK_QUORUM"] = str(
            cfg.ack_quorum or cfg.replication // 2 + 1
        )
    return env, store_dir


def _partitioned_phase(cfg: ChaosConfig, rng: random.Random, base: str) -> dict:
    """The kill-one-partition drill (ISSUE 20). One bulk stream against a
    P-partition store whose busiest partition's appender is chaos-killed
    mid-stream (torn tail bytes, then every later append on it fails —
    the in-process kill-9 signature; a thread cannot be SIGKILLed alone),
    and, with replication on, one non-leader replica of a second
    partition is killed the same way so its quorum is lost. Then a real
    whole-server SIGKILL mid-retry, a clean-env restart, and retries of
    the WHOLE stream with the same ids until a clean summary.

    Verdict fields: zero acked loss, zero duplicates, surviving
    partitions stored rows in every faulted chunk (no stream-wide
    stall), the killed partition holds exactly its routed share after
    recovery, /readyz went degraded while quorum was lost, and every
    replica reports in-sync at the end."""
    P = cfg.partitions
    R = cfg.replication
    Q = (cfg.ack_quorum or R // 2 + 1) if R else 0
    env, store_dir = _partitioned_env(base, cfg)
    n = max(cfg.bulk_events, 400)
    ids = [f"part-e{i:05d}" for i in range(n)]
    entities = [f"pu{i % 101}" for i in range(n)]
    routed = [_partition_of("user", entities[i], P) for i in range(n)]
    lines = [
        json.dumps(
            {
                "eventId": ids[i],
                "event": "rate",
                "entityType": "user",
                "entityId": entities[i],
                "targetEntityType": "item",
                "targetEntityId": f"pi{i % 37}",
                "properties": {"rating": float(1 + i % 5)},
            }
        ).encode() + b"\n"
        for i in range(n)
    ]
    per_part = {p: routed.count(p) for p in range(P)}
    victim = max(per_part, key=lambda p: per_part[p])
    fault_env = dict(env)
    fault_env["PIO_CHAOS_KILL_PARTITION"] = (
        f"{victim}:{max(1, per_part[victim] * 2 // 5)}"
    )
    rvictim = rrep = None
    if R:
        others = sorted(
            (p for p in per_part if p != victim),
            key=lambda p: -per_part[p],
        )
        rvictim = others[0]
        rrep = (rvictim % R + 1) % R  # first non-leader replica
        fault_env["PIO_CHAOS_KILL_REPLICA"] = (
            f"{rvictim}:{rrep}:{max(1, per_part[rvictim] // 3)}"
        )
    report: dict[str, Any] = {
        "partitions": P,
        "replication": R,
        "ackQuorum": Q,
        "events": n,
        "killedPartition": victim,
        "killedReplica": f"{rvictim}:{rrep}" if R else None,
        "rowsPerPartition": {str(p): per_part[p] for p in sorted(per_part)},
    }
    port = _free_port()
    acked: set[str] = set()
    kills = 0
    summary = None
    server = _ServerProc(fault_env, port, extra_args=("--stats",))
    try:
        server.wait_ready(cfg.startup_timeout_s)
        # ---- stream 1: the appender (and replica) faults fire mid-stream
        attempt = _BulkStreamAttempt(port)
        try:
            for lo in range(0, len(lines), 100):
                attempt.send_piece(b"".join(lines[lo:lo + 100]))
                time.sleep(0.002)
            attempt.finish_send()
            attempt.wait(60.0)
        finally:
            attempt.close()
        fault_seen = False
        faulted_chunks = 0
        survivor_chunks = 0
        failed_lines = 0
        for st in attempt.statuses:
            acked.update(_acked_ids(st, ids))
            perr = st.get("partitionErrors") or {}
            if perr:
                fault_seen = True
                faulted_chunks += 1
                failed_lines += sum(
                    int(v.get("failed", 0)) for v in perr.values()
                )
                if int(st.get("stored", 0)) + int(st.get("duplicates", 0)) > 0:
                    survivor_chunks += 1
        report.update(
            stream1Completed=attempt.summary is not None,
            faultFired=fault_seen,
            faultFailedLines=failed_lines,
            faultedChunks=faulted_chunks,
            survivorProgressChunks=survivor_chunks,
            ackedAfterFault=len(acked),
        )
        # ---- degraded-mode surfaces while quorum is lost
        if R and Q >= 2:
            report["readyzDegradedSeen"] = _wait_http_status(
                port, "/readyz", 503, 15.0
            )
            stats = _get_json(port, "/stats.json") or {}
            repl = stats.get("replication") or []
            report["degradedPartitionsReported"] = sorted(
                part.get("partition") for part in repl
                if not part.get("quorumOk")
            )
        # ---- a real whole-server SIGKILL mid-retry stream
        try:
            attempt2 = _BulkStreamAttempt(port)
        except OSError:
            attempt2 = None
        if attempt2 is not None:
            try:
                kill_at = rng.uniform(0.3, 0.7) * len(lines)
                sent = 0
                for lo in range(0, len(lines), 100):
                    attempt2.send_piece(b"".join(lines[lo:lo + 100]))
                    sent += 100
                    time.sleep(0.002)
                    if sent >= kill_at:
                        server.kill9()
                        kills += 1
                        break
            except OSError:
                pass  # socket died under the kill: expected
            finally:
                attempt2.wait(2.0)
                for st in attempt2.statuses:
                    acked.update(_acked_ids(st, ids))
                attempt2.close()
        if not kills:
            server.kill9()
            kills += 1
        # ---- clean-env restart (recovery sweep quarantines the torn
        # tails; replicas reopen healthy) + retry until a clean summary
        server = _ServerProc(env, port, extra_args=("--stats",))
        recovery_s = server.wait_ready(cfg.startup_timeout_s)
        deadline = time.monotonic() + cfg.total_timeout_s / 2
        attempts = 0
        while summary is None and time.monotonic() < deadline:
            attempts += 1
            try:
                a = _BulkStreamAttempt(port)
            except OSError:
                time.sleep(0.2)
                continue
            try:
                for lo in range(0, len(lines), 100):
                    a.send_piece(b"".join(lines[lo:lo + 100]))
                a.finish_send()
                a.wait(60.0)
                for st in a.statuses:
                    acked.update(_acked_ids(st, ids))
                if a.summary is not None and not any(
                    st.get("storageError") is not None
                    or st.get("partitionErrors")
                    for st in a.statuses
                ):
                    summary = a.summary
            except OSError:
                pass
            finally:
                a.close()
        # ---- replication catch-up: every partition quorum-ok + in-sync
        replica_insync = None
        if R:
            replica_insync = False
            wait_until = time.monotonic() + 30.0
            while time.monotonic() < wait_until:
                stats = _get_json(port, "/stats.json") or {}
                repl = stats.get("replication") or []
                if repl and all(p.get("quorumOk") for p in repl) and all(
                    lag.get("inSync") and lag.get("healthy")
                    for p in repl
                    for lag in (p.get("lag") or {}).values()
                ):
                    replica_insync = True
                    break
                time.sleep(0.5)
        # ---- exactly-once + killed-partition catch-up verification
        stored = _fetch_all_events(port)
        counts: dict[str, int] = {}
        for evd in stored:
            eid = evd.get("eventId") or ""
            counts[eid] = counts.get(eid, 0) + 1
        lost = sorted(e for e in acked if counts.get(e, 0) == 0)
        dups = sorted(
            e for e in counts if e.startswith("part-") and counts[e] > 1
        )
        victim_expected = {ids[i] for i in range(n) if routed[i] == victim}
        victim_present = sum(
            1 for e in victim_expected if counts.get(e, 0) == 1
        )
        stats = _get_json(port, "/stats.json") or {}
        report.update(
            kills=kills,
            retryAttempts=attempts,
            completed=summary is not None,
            summary=summary,
            recoverySeconds=round(recovery_s, 3),
            acked=len(acked),
            ackedLost=len(lost),
            ackedLostIds=lost[:20],
            duplicates=len(dups),
            duplicateIds=dups[:20],
            killedPartitionExpected=len(victim_expected),
            killedPartitionPresent=victim_present,
            killedPartitionCaughtUp=victim_present == len(victim_expected),
            statsPartitionCount=(stats.get("partitions") or {}).get("count"),
            replicaCatchUp=replica_insync,
            unquarantinedTornFiles=len(_unquarantined_torn_files(store_dir)),
        )
    finally:
        server.stop()
    report["ok"] = bool(
        report.get("completed")
        and report.get("stream1Completed")
        and report.get("faultFired")
        and report.get("survivorProgressChunks", 0) > 0
        and report.get("survivorProgressChunks")
        == report.get("faultedChunks")
        and kills >= 1
        and report.get("ackedLost") == 0
        and report.get("duplicates") == 0
        and report.get("killedPartitionCaughtUp")
        and report.get("statsPartitionCount") == P
        and report.get("unquarantinedTornFiles") == 0
        and summary is not None
        and summary.get("stored", 0) + summary.get("duplicates", 0) == n
        and (
            not R
            or (
                report.get("replicaCatchUp")
                and (Q < 2 or report.get("readyzDegradedSeen"))
            )
        )
    )
    return report


def _drain_phase(env: dict, cfg: ChaosConfig, rng: random.Random) -> dict:
    """SIGTERM under load: a fresh server with ``--drain-deadline-s``
    gets concurrent writers, then SIGTERM mid-traffic. Verdict: exit 0
    within the deadline (+ grace), every response a 201 or a clean 503,
    zero raw 500s / dropped connections after the ack."""
    port = _free_port()
    server = _ServerProc(
        env, port, extra_args=("--drain-deadline-s", str(cfg.drain_deadline_s))
    )
    statuses: list[int] = []
    lock = threading.Lock()
    stop = threading.Event()

    def drain_writer(w: int) -> None:
        i = 0
        while not stop.is_set():
            i += 1
            payload = json.dumps(
                {
                    "eventId": f"drain-w{w}-e{i}",
                    "event": "rate",
                    "entityType": "user",
                    "entityId": f"d{w}",
                    "targetEntityType": "item",
                    "targetEntityId": f"i{i % 7}",
                }
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/events.json?accessKey={_ACCESS_KEY}",
                data=payload,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    status = resp.status
            except urllib.error.HTTPError as e:
                status = e.code
            except OSError:
                # listener already gone (post-drain) — not a protocol
                # violation, the request was never admitted
                break
            with lock:
                statuses.append(status)
            time.sleep(0.005)

    try:
        server.wait_ready(cfg.startup_timeout_s)
        writers = [
            threading.Thread(target=drain_writer, args=(w,), daemon=True)
            for w in range(cfg.writers)
        ]
        for t in writers:
            t.start()
        time.sleep(0.3 + rng.random() * 0.2)  # real traffic in flight
        t_term = time.monotonic()
        server.sigterm()
        try:
            exit_code = server.proc.wait(
                timeout=cfg.drain_deadline_s + cfg.startup_timeout_s
            )
        except subprocess.TimeoutExpired:
            server.stop()
            return {"exitCode": None, "error": "drain never exited"}
        exit_seconds = time.monotonic() - t_term
        stop.set()
        for t in writers:
            t.join(timeout=10)
    finally:
        stop.set()
        server.stop()
    with lock:
        counts = {str(s): statuses.count(s) for s in sorted(set(statuses))}
        raw_500s = sum(1 for s in statuses if s >= 500 and s != 503)
    return {
        "exitCode": exit_code,
        "exitSeconds": round(exit_seconds, 3),
        "withinDeadline": exit_seconds <= cfg.drain_deadline_s + 2.0,
        "responses": counts,
        "raw500s": raw_500s,
        "drainDeadlineSeconds": cfg.drain_deadline_s,
    }


def run_chaos_partitioned(cfg: ChaosConfig) -> dict:
    """Run ONLY the kill-one-partition drill on a fresh scratch dir (the
    bench's ``ingest_partitioned.chaos`` subfield and the partitioned CI
    test call this directly; :func:`run_chaos_ingest` wraps the same
    phase with the whole-server kill cycles, bulk and drain phases)."""
    if cfg.partitions <= 1 and not cfg.replication:
        raise ChaosError("run_chaos_partitioned needs partitions > 1")
    base = cfg.base_dir or tempfile.mkdtemp(prefix="pio_chaos_part_")
    os.makedirs(base, exist_ok=True)
    env = _storage_env(base, "columnar")
    try:
        _setup_app(env)
        report = _partitioned_phase(cfg, random.Random(cfg.seed), base)
    finally:
        if not cfg.keep_dir and cfg.base_dir is None:
            shutil.rmtree(base, ignore_errors=True)
    if cfg.keep_dir or cfg.base_dir is not None:
        report["storageDir"] = base
    return report


def run_chaos_ingest(cfg: ChaosConfig) -> dict:
    """Run the full harness; returns the report dict (``report["ok"]`` is
    the overall verdict — the CLI exit code and the bench smoke guard key
    off the individual invariants)."""
    base = cfg.base_dir or tempfile.mkdtemp(prefix="pio_chaos_")
    os.makedirs(base, exist_ok=True)
    env = _storage_env(base, cfg.backend)
    rng = random.Random(cfg.seed)
    injector = FaultInjector()
    t_start = time.monotonic()
    report: dict[str, Any] = {
        "backend": cfg.backend,
        "cycles": cfg.cycles,
        "writers": cfg.writers,
        "eventsPerWriter": cfg.events_per_writer,
        "seed": cfg.seed,
    }
    port = _free_port()
    server: _ServerProc | None = None
    stop = threading.Event()
    try:
        _setup_app(env)
        server = _ServerProc(env, port)
        cold_start = server.wait_ready(cfg.startup_timeout_s)
        writers = _Writers(
            port, cfg.writers, cfg.events_per_writer, injector, stop, cfg.seed
        )
        writers.start()
        recovery_s: list[float] = []
        kills = 0
        total = cfg.writers * cfg.events_per_writer
        for cycle in range(cfg.cycles):
            # kill points are keyed to writer PROGRESS, not wall time, so
            # every kill is guaranteed to land mid-stream (with work both
            # behind it — acked events that must survive — and ahead of
            # it — events whose retries must converge after restart). The
            # seeded jitter moves each point around its progress anchor.
            target = max(
                1,
                int(total * (cycle + 1) / (cfg.cycles + 1))
                - rng.randrange(max(1, total // (4 * cfg.cycles))),
            )
            while (
                writers.acked_count() < target
                and not writers.done()
                and time.monotonic() - t_start < cfg.total_timeout_s
            ):
                time.sleep(0.01)
            # abort a burst of in-flight writer calls client-side at the
            # exact kill point (deterministic via the injector schedule)
            # and put one torn half-request on the wire
            injector.fail_next(cfg.writers)
            _torn_request(port, f"torn-c{cycle}")
            server.kill9()
            kills += 1
            time.sleep(0.05 + rng.random() * 0.2)  # writers bang on a dead port
            server = _ServerProc(env, port)
            recovery_s.append(server.wait_ready(cfg.startup_timeout_s))
        # final convergence: writers finish acking everything
        budget = cfg.total_timeout_s - (time.monotonic() - t_start)
        finished = writers.join(max(5.0, budget))
        stop.set()

        expected = {
            f"w{w}-e{i:05d}"
            for w in range(cfg.writers)
            for i in range(cfg.events_per_writer)
        }
        acked = dict(writers.acked)
        stored = _fetch_all_events(port)
        stored_counts: dict[str, int] = {}
        for ev in stored:
            eid = ev.get("eventId") or ""
            stored_counts[eid] = stored_counts.get(eid, 0) + 1
        acked_lost = sorted(e for e in acked if stored_counts.get(e, 0) == 0)
        duplicates = sorted(
            e for e, n in stored_counts.items() if n > 1
        )
        torn_acked = [e for e in stored_counts if e.startswith("torn-")]
        torn_files = _unquarantined_torn_files(base)
        report.update(
            killCycles=kills,
            writersFinished=finished,
            ackedTotal=len(acked),
            ackedExpected=len(expected),
            ackedLost=len(acked_lost),
            ackedLostIds=acked_lost[:20],
            duplicates=len(duplicates),
            duplicateIds=duplicates[:20],
            duplicateAcksAbsorbed=writers.duplicate_acks,
            dedupViolations=writers.dedup_violations,
            tornRequestsStored=len(torn_acked),
            unquarantinedTornFiles=len(torn_files),
            unquarantinedTornFilePaths=torn_files[:20],
            coldStartSeconds=round(cold_start, 3),
            recoverySeconds=[round(s, 3) for s in recovery_s],
            meanRecoverySeconds=round(sum(recovery_s) / len(recovery_s), 3)
            if recovery_s
            else None,
            injector=injector.to_json(),
        )
    finally:
        stop.set()
        if server is not None:
            server.stop()
    if cfg.bulk_events > 0:
        report["bulk"] = _bulk_phase(env, cfg, rng, base)
    if cfg.partitions > 1 or cfg.replication:
        report["partitioned"] = _partitioned_phase(cfg, rng, base)
    report["drain"] = _drain_phase(env, cfg, rng)
    if not cfg.keep_dir and cfg.base_dir is None:
        shutil.rmtree(base, ignore_errors=True)
    else:
        report["storageDir"] = base
    drain = report["drain"]
    report["ok"] = bool(
        report.get("killCycles", 0) >= cfg.cycles
        and report.get("writersFinished")
        and report.get("ackedLost") == 0
        and report.get("duplicates") == 0
        and report.get("dedupViolations") == 0
        and report.get("tornRequestsStored") == 0
        and report.get("unquarantinedTornFiles") == 0
        and (cfg.bulk_events <= 0 or report.get("bulk", {}).get("ok"))
        and (
            (cfg.partitions <= 1 and not cfg.replication)
            or report.get("partitioned", {}).get("ok")
        )
        and drain.get("exitCode") == 0
        and drain.get("raw500s") == 0
        and drain.get("withinDeadline")
    )
    return report


# ---------------------------------------------------------------------------
# Serving-fleet chaos (``pio chaos-serve``; ISSUE 15)
# ---------------------------------------------------------------------------
#
# The ingest drill above proves writes survive a SIGKILL; this drill
# proves *reads never notice one*. It trains a tiny real model, deploys
# it as ``pio deploy --replicas N`` (router + replica subprocesses), and
# then, with >= 16 concurrent query clients that NEVER retry:
#
# 1. **throughput** — aggregate q/s at each fleet size (the bench's
#    q/s-vs-R curve; one core can't show scaling, so the report carries
#    cpuCount and a one-core note instead of a fake ratio);
# 2. **kill** — SIGKILL a replica mid-traffic. The router must route
#    around it within one probe interval and retry the in-flight
#    casualties on a peer, so every client request still answers 2xx
#    (zero failed queries), and tail latency must recover within one
#    breaker-reset interval. The supervisor respawns the replica and the
#    fleet heals to full strength;
# 3. **rolling** — ``POST /reload`` on the router rotates the fleet one
#    replica at a time while clients keep querying: zero failed queries,
#    zero cross-generation results for any one cache scope (each client
#    owns disjoint scopes, so per-scope generation monotonicity is exact,
#    not racy), and the fleet converges to one generation;
# 4. optionally one **sharded-replica** fleet (``--shard-factors`` inside
#    each replica over the 8-way virtual host mesh) — the R x S
#    composition point.
#
# Same contract as the ingest drill: stdlib-only, everything over the
# wire and the filesystem (the supervisor's fleet state file names the
# replica PIDs to kill); verdicts are asserted fields, never log lines.


@dataclasses.dataclass(frozen=True)
class ServeChaosConfig:
    """Knobs of one serving-fleet chaos run (CLI: ``pio chaos-serve``)."""

    replicas: int = 2
    clients: int = 16
    kills: int = 1
    phase_seconds: float = 6.0
    reloads: int = 1
    #: synthetic `rate` events the tiny model trains on
    train_events: int = 400
    train_users: int = 60
    train_items: int = 120
    rank: int = 8
    iterations: int = 2
    seed: int = 0
    #: fleet sizes of the aggregate-q/s sweep (the last one is reused
    #: for the kill/rolling phases when it matches ``replicas``)
    throughput_replicas: tuple[int, ...] = (1, 2)
    throughput_seconds: float = 3.0
    #: also measure one fleet whose replicas serve ``--shard-factors``
    sharded_point: bool = False
    #: run the drill AOT-on: ``pio train --aot`` exports the serving
    #: programs, replicas deploy ``--aot``, and the rolling phase
    #: additionally asserts ZERO serve-time compiles across the full
    #: rotation (every replica tier 1; docs/operations.md AOT runbook)
    aot: bool = False
    probe_interval_s: float = 0.25
    breaker_reset_s: float = 1.0
    query_timeout_s: float = 20.0
    startup_timeout_s: float = 180.0
    total_timeout_s: float = 900.0
    base_dir: str | None = None
    keep_dir: bool = False

    def __post_init__(self) -> None:
        if self.replicas < 1 or self.clients < 1:
            raise ValueError("replicas and clients must be >= 1")


def _run_pio(env: dict, args: list[str], timeout_s: float, what: str) -> str:
    proc = subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.tools.console", *args],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout_s,
    )
    if proc.returncode != 0:
        raise ChaosError(
            f"{what} failed rc={proc.returncode}: {proc.stderr[-800:]}"
        )
    return proc.stdout


class _FleetProc:
    """One ``pio deploy --replicas N`` subprocess tree (router +
    supervised replicas) plus the wire/file helpers the drill needs."""

    def __init__(
        self,
        env: dict,
        base: str,
        engine_json: str,
        replicas: int,
        cfg: ServeChaosConfig,
        extra_args: tuple[str, ...] = (),
        env_extra: dict | None = None,
    ):
        self.port = _free_port()
        self.base = base
        self.replicas = replicas
        run_env = dict(env)
        # the bench parent forces an 8-virtual-device XLA host platform
        # for its sharding sections; a plain replica must not inherit it
        # (the sharded point passes its own via env_extra)
        run_env.pop("XLA_FLAGS", None)
        if env_extra:
            run_env.update(env_extra)
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "predictionio_tpu.tools.console",
                "deploy",
                "--engine-json", engine_json,
                "--ip", "127.0.0.1",
                "--port", str(self.port),
                "--replicas", str(replicas),
                "--probe-interval-s", str(cfg.probe_interval_s),
                "--failover-retries", "1",
                "--fleet-breaker-threshold", "2",
                "--fleet-breaker-reset-s", str(cfg.breaker_reset_s),
                "--result-cache", "--coalesce",
                *extra_args,
            ],
            env=run_env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    @property
    def state_path(self) -> str:
        return os.path.join(
            self.base, "deployments", f"fleet-{self.port}.json"
        )

    def state(self) -> dict | None:
        try:
            with open(self.state_path) as f:
                doc = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError, OSError):
            return None
        return doc if isinstance(doc, dict) else None

    def status(self) -> dict | None:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{self.port}/", timeout=5
            ) as resp:
                return json.loads(resp.read())
        except Exception:
            return None

    def wait_all_ready(self, timeout_s: float) -> float:
        """Until EVERY replica is healthy at the router (throughput
        phases must start at full strength); returns seconds waited."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            if self.proc.poll() is not None:
                raise ChaosError(
                    f"fleet exited rc={self.proc.returncode} before ready"
                )
            status = self.status()
            if status is not None:
                reps = status.get("replicas", [])
                if reps and all(r.get("healthy") for r in reps):
                    return time.monotonic() - t0
            time.sleep(0.1)
        raise ChaosError(f"fleet not fully ready within {timeout_s:g}s")

    def kill_replica(self, index: int) -> tuple[str, int]:
        """SIGKILL replica ``index`` by the PID in the supervisor's state
        file; returns (replica id, pid killed)."""
        state = self.state()
        if state is None:
            raise ChaosError("no fleet state file to pick a victim from")
        reps = state.get("replicas", [])
        rep = reps[index % len(reps)]
        pid = rep.get("pid")
        if not pid:
            raise ChaosError(f"replica {rep.get('id')} has no pid on file")
        os.kill(int(pid), signal.SIGKILL)
        return str(rep.get("id")), int(pid)

    def wait_respawn(self, replica_id: str, old_pid: int, timeout_s: float) -> bool:
        """Until the supervisor has a NEW live pid for ``replica_id`` and
        the router reports it healthy again."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            state = self.state() or {}
            rep = next(
                (
                    r
                    for r in state.get("replicas", [])
                    if r.get("id") == replica_id
                ),
                None,
            )
            if rep and rep.get("alive") and rep.get("pid") != old_pid:
                status = self.status() or {}
                srep = next(
                    (
                        r
                        for r in status.get("replicas", [])
                        if r.get("id") == replica_id
                    ),
                    None,
                )
                if srep and srep.get("healthy"):
                    return True
            time.sleep(0.1)
        return False

    def reload(self, timeout_s: float) -> dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/reload",
            data=b"{}",
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read())
            except Exception:
                return {"ok": False, "error": f"HTTP {e.code}"}

    def router_stats(self, fanout: bool = False) -> dict | None:
        url = f"http://127.0.0.1:{self.port}/stats.json"
        if fanout:
            url += "?fanout=1"
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                return json.loads(resp.read())
        except Exception:
            return None

    def stop(self) -> None:
        """SIGTERM the supervisor (it takes its replicas down), escalate
        if needed, and reap any replica pid still on file."""
        pids: list[int] = []
        state = self.state()
        if state:
            pids = [
                int(r["pid"])
                for r in state.get("replicas", [])
                if r.get("pid")
            ]
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                try:
                    self.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        for pid in pids:  # belt-and-braces: no replica outlives the drill
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass


class _QueryClients:
    """Concurrent query clients that NEVER retry — zero failed queries
    means the ROUTER absorbed every fault, not the clients. Client ``i``
    queries only users ``u`` with ``u % clients == i``: disjoint cache
    scopes per client, so each scope's responses are observed strictly
    in order and per-scope generation monotonicity is exact."""

    def __init__(self, port: int, cfg: ServeChaosConfig):
        self.port = port
        self.cfg = cfg
        self.stop = threading.Event()
        self._lock = threading.Lock()
        #: (t_done_monotonic, latency_s, status, scope, generation)
        self.samples: list[tuple[float, float, int, str, int]] = []
        self.transport_errors = 0
        self._threads = [
            threading.Thread(
                target=self._run, args=(i,), daemon=True,
                name=f"chaos-serve-client-{i}",
            )
            for i in range(cfg.clients)
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def join(self, timeout_s: float = 30.0) -> None:
        self.stop.set()
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))

    def _run(self, cid: int) -> None:
        cfg = self.cfg
        users = [
            f"u{u}" for u in range(cfg.train_users) if u % cfg.clients == cid
        ] or [f"u{cid % cfg.train_users}"]
        rng = random.Random(cfg.seed * 7919 + cid)
        url = f"http://127.0.0.1:{self.port}/queries.json"
        while not self.stop.is_set():
            user = users[rng.randrange(len(users))]
            payload = json.dumps({"user": user, "num": 4}).encode()
            req = urllib.request.Request(
                url,
                data=payload,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            t0 = time.monotonic()
            status = 0
            generation = 0
            try:
                with urllib.request.urlopen(
                    req, timeout=cfg.query_timeout_s
                ) as resp:
                    resp.read()
                    status = resp.status
                    generation = int(
                        resp.headers.get("X-PIO-Generation", "0") or 0
                    )
            except urllib.error.HTTPError as e:
                e.read()
                status = e.code
            except Exception:
                with self._lock:
                    self.transport_errors += 1
                continue
            t1 = time.monotonic()
            with self._lock:
                self.samples.append((t1, t1 - t0, status, user, generation))

    # ----------------------------------------------------------- analysis
    def snapshot(self) -> list[tuple[float, float, int, str, int]]:
        with self._lock:
            return list(self.samples)

    @staticmethod
    def _p99(latencies: list[float]) -> float | None:
        if not latencies:
            return None
        lat = sorted(latencies)
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    def summarize(self, t_start: float, t_end: float) -> dict:
        samples = [s for s in self.snapshot() if t_start <= s[0] <= t_end]
        lat = sorted(s[1] for s in samples)
        failed = [s for s in samples if not 200 <= s[2] < 300]
        duration = max(1e-6, t_end - t_start)
        return {
            "requests": len(samples),
            "failed": len(failed),
            "failedStatuses": sorted({s[2] for s in failed}),
            "transportErrors": self.transport_errors,
            "qps": round(len(samples) / duration, 1),
            "p50Ms": round(lat[len(lat) // 2] * 1000, 3) if lat else None,
            "p99Ms": round(self._p99([s[1] for s in samples]) * 1000, 3)
            if lat
            else None,
        }

    def cross_generation_violations(self) -> int:
        """Per scope, the generation sequence (in completion order —
        exact, because scopes are client-disjoint) must never decrease:
        one cache key served by gen g+1 must never be served by gen g
        again."""
        last: dict[str, int] = {}
        violations = 0
        for _t, _lat, status, scope, gen in self.snapshot():
            if not 200 <= status < 300 or gen <= 0:
                continue
            if gen < last.get(scope, 0):
                violations += 1
            else:
                last[scope] = gen
        return violations


def _serve_setup(env: dict, base: str, cfg: ServeChaosConfig) -> str:
    """App + synthetic events + one trained instance; returns the
    engine.json path. All through real ``pio`` subprocesses — the drill
    exercises the product path end to end."""
    _setup_app(env)
    rng = random.Random(cfg.seed)
    events_path = os.path.join(base, "train-events.jsonl")
    with open(events_path, "w") as f:
        for i in range(cfg.train_events):
            u = i % cfg.train_users
            f.write(
                json.dumps(
                    {
                        "event": "rate",
                        "entityType": "user",
                        "entityId": f"u{u}",
                        "targetEntityType": "item",
                        "targetEntityId": f"i{rng.randrange(cfg.train_items)}",
                        "properties": {"rating": float(1 + rng.randrange(5))},
                        "eventTime": "2024-01-01T00:00:00.000Z",
                    }
                )
                + "\n"
            )
    _run_pio(
        env,
        ["import", "--appname", _APP_NAME, "--input", events_path],
        cfg.startup_timeout_s,
        "event import",
    )
    engine_json = os.path.join(base, "engine.json")
    with open(engine_json, "w") as f:
        json.dump(
            {
                "id": "fleet-chaos",
                "version": "1",
                "engineFactory": (
                    "predictionio_tpu.templates.recommendation:engine_factory"
                ),
                "datasource": {"params": {"appName": _APP_NAME}},
                "algorithms": [
                    {
                        "name": "als",
                        "params": {
                            "rank": cfg.rank,
                            "numIterations": cfg.iterations,
                            "lambda": 0.05,
                        },
                    }
                ],
            },
            f,
        )
    train_args = ["train", "--engine-json", engine_json, "--mesh", "none"]
    if getattr(cfg, "aot", False):
        train_args.append("--aot")
    _run_pio(
        env,
        train_args,
        cfg.startup_timeout_s * 2,  # first train pays the XLA compile
        "train",
    )
    return engine_json


def _warm_fleet(port: int, cfg: ServeChaosConfig, distinct_users: int = 8) -> None:
    """Sequential warm-up queries before any measured (or asserted)
    window: the first queries after a (re)deploy pay jit warm-up — on
    the sharded path tens of seconds of XLA compile — and 16 concurrent
    cold clients would read as timeouts, not as fleet behavior. Distinct
    users spread the warm-up across the hash ring so every replica gets
    touched."""
    for u in range(distinct_users):
        payload = json.dumps({"user": f"u{u}", "num": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json",
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        deadline = time.monotonic() + cfg.startup_timeout_s
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    req, timeout=cfg.startup_timeout_s
                ) as resp:
                    resp.read()
                break
            except urllib.error.HTTPError as e:
                e.read()
                break  # the fleet answered; warm enough for this user
            except Exception:
                time.sleep(0.2)


def _throughput_point(
    env: dict,
    base: str,
    engine_json: str,
    cfg: ServeChaosConfig,
    replicas: int,
    extra_args: tuple[str, ...] = (),
    env_extra: dict | None = None,
    keep_fleet: bool = False,
    clients_override: int | None = None,
) -> tuple[dict, "_FleetProc | None"]:
    """Measure aggregate q/s at one fleet size; optionally hand the live
    fleet back for the next phase instead of stopping it."""
    fleet = _FleetProc(
        env, base, engine_json, replicas, cfg,
        extra_args=extra_args, env_extra=env_extra,
    )
    if clients_override is not None:
        cfg = dataclasses.replace(cfg, clients=clients_override)
    try:
        ready_s = fleet.wait_all_ready(cfg.startup_timeout_s)
        _warm_fleet(fleet.port, cfg)
        clients = _QueryClients(fleet.port, cfg)
        clients.start()
        t0 = time.monotonic()
        time.sleep(cfg.throughput_seconds)
        t1 = time.monotonic()
        clients.join()
        point = dict(
            clients.summarize(t0, t1),
            replicas=replicas,
            clients=cfg.clients,
            readySeconds=round(ready_s, 2),
        )
    except BaseException:
        fleet.stop()
        raise
    if keep_fleet:
        return point, fleet
    fleet.stop()
    return point, None


def _kill_phase(fleet: "_FleetProc", cfg: ServeChaosConfig) -> dict:
    """SIGKILL replicas under load; zero failed queries, p99 recovery
    within one breaker reset, supervisor respawn back to full strength."""
    clients = _QueryClients(fleet.port, cfg)
    clients.start()
    t0 = time.monotonic()
    warm_s = max(0.5, cfg.phase_seconds * 0.25)
    time.sleep(warm_s)
    kill_records = []
    for k in range(cfg.kills):
        t_kill = time.monotonic()
        rid, pid = fleet.kill_replica(k % fleet.replicas)
        respawned = fleet.wait_respawn(
            rid, pid, timeout_s=cfg.startup_timeout_s
        )
        kill_records.append(
            {
                "replica": rid,
                "pid": pid,
                "tKill": t_kill,
                "respawned": respawned,
            }
        )
    # post-kill observation window: at least one breaker reset + probes
    recovery_budget = cfg.breaker_reset_s + 2 * cfg.probe_interval_s
    tail_s = max(cfg.phase_seconds - (time.monotonic() - t0), recovery_budget + 1.0)
    time.sleep(tail_s)
    t_end = time.monotonic()
    clients.join()
    overall = clients.summarize(t0, t_end)
    first_kill = kill_records[0]["tKill"] if kill_records else t0
    last_kill = kill_records[-1]["tKill"] if kill_records else t0
    baseline = clients.summarize(t0 + warm_s * 0.5, first_kill)
    recovered_window = clients.summarize(
        last_kill + recovery_budget, t_end
    )
    base_p99 = baseline.get("p99Ms")
    rec_p99 = recovered_window.get("p99Ms")
    # one-core honesty (and scheduler jitter generally): the recovery
    # claim uses a floor — "back under 3x the pre-kill p99, or under an
    # absolute 250 ms" — so a microsecond-fast baseline cannot turn
    # noise into a red verdict, while a breaker/probe regression (seconds
    # of stall) still fails loudly
    p99_recovered = (
        rec_p99 is not None
        and base_p99 is not None
        and (rec_p99 <= 3 * base_p99 or rec_p99 <= 250.0)
    )
    return {
        "kills": [
            {"replica": r["replica"], "respawned": r["respawned"]}
            for r in kill_records
        ],
        "killCount": len(kill_records),
        "allRespawned": all(r["respawned"] for r in kill_records),
        "overall": overall,
        "baselineWindow": baseline,
        "recoveredWindow": recovered_window,
        "recoveryBudgetSeconds": round(recovery_budget, 3),
        "p99Recovered": bool(p99_recovered),
        "failedQueries": overall["failed"] + overall["transportErrors"],
    }


def _rolling_phase(fleet: "_FleetProc", cfg: ServeChaosConfig) -> dict:
    """Rolling /reload under load: zero failed queries, zero
    cross-generation results per cache scope, fleet converges."""
    clients = _QueryClients(fleet.port, cfg)
    clients.start()
    t0 = time.monotonic()
    time.sleep(0.5)
    reload_reports = []
    for _ in range(max(1, cfg.reloads)):
        reload_reports.append(fleet.reload(timeout_s=cfg.startup_timeout_s))
    time.sleep(1.0)
    t_end = time.monotonic()
    clients.join()
    overall = clients.summarize(t0, t_end)
    stats = fleet.router_stats() or {}
    out = {
        "overall": overall,
        "reloads": reload_reports,
        "reloadsOk": all(r.get("ok") for r in reload_reports),
        "converged": all(r.get("converged") for r in reload_reports),
        "crossGenerationViolations": clients.cross_generation_violations(),
        "routerGenerationRegressions": (
            (stats.get("router") or {}).get("generationRegressions")
        ),
        "failedQueries": overall["failed"] + overall["transportErrors"],
    }
    if cfg.aot:
        # AOT rolling contract (docs/operations.md AOT runbook): after a
        # full rotation every replica must serve deserialized programs
        # (tier 1) and have witnessed ZERO compiles since its boot
        # finished — a rotation that recompiles is the regression this
        # drill exists to catch. Read through the router's stats fanout
        # so the drill stays wire-only.
        fan = fleet.router_stats(fanout=True) or {}
        per_replica: dict[str, Any] = {}
        total = 0
        tiers_ok = True
        for rid, rstats in (fan.get("replicaStats") or {}).items():
            aot_block = (
                rstats.get("aot") if isinstance(rstats, dict) else None
            ) or {}
            compiles = aot_block.get("serveTimeCompiles")
            per_replica[rid] = {
                "tier": aot_block.get("tier"),
                "serveTimeCompiles": compiles,
            }
            total += int(compiles or 0)
            if aot_block.get("tier") != 1:
                tiers_ok = False
        out["aot"] = {
            "perReplica": per_replica,
            "serveTimeCompiles": total,
            "allTier1": bool(per_replica) and tiers_ok,
        }
    return out


def run_chaos_serve(cfg: ServeChaosConfig) -> dict:
    """Run the full serving-fleet drill; returns the report dict
    (``report["ok"]`` is the overall verdict — the CLI exit code and the
    bench ``serving_fleet`` smoke guard key off the individual fields)."""
    base = cfg.base_dir or tempfile.mkdtemp(prefix="pio_chaos_serve_")
    os.makedirs(base, exist_ok=True)
    env = _storage_env(base, "sqlite")
    report: dict[str, Any] = {
        "replicas": cfg.replicas,
        "clients": cfg.clients,
        "seed": cfg.seed,
        "aot": cfg.aot,
        "cpuCount": os.cpu_count(),
    }
    aot_args = ("--aot",) if cfg.aot else ()
    fleet: _FleetProc | None = None
    t_start = time.monotonic()
    try:
        t0 = time.monotonic()
        engine_json = _serve_setup(env, base, cfg)
        report["setupSeconds"] = round(time.monotonic() - t0, 1)

        # ---- phase 1: aggregate q/s vs fleet size
        points: list[dict] = []
        for r in cfg.throughput_replicas:
            keep = r == cfg.replicas and r == cfg.throughput_replicas[-1]
            point, kept = _throughput_point(
                env, base, engine_json, cfg, r,
                extra_args=aot_args, keep_fleet=keep,
            )
            points.append(point)
            if kept is not None:
                fleet = kept
        by_r = {p["replicas"]: p for p in points}
        scaling = None
        if 1 in by_r and cfg.replicas in by_r and by_r[1]["qps"]:
            scaling = round(by_r[cfg.replicas]["qps"] / by_r[1]["qps"], 2)
        report["throughput"] = {
            "points": points,
            "scaling": scaling,
            "note": (
                "single-core host: replicas time-share one core, so "
                "aggregate q/s cannot scale with R here — the scaling "
                "claim applies to the multi-core path (see "
                "docs/operations.md)"
            )
            if (os.cpu_count() or 1) < 2
            else "multi-core host: q/s should scale with R until cores "
            "saturate",
        }

        # ---- phase 2: replica SIGKILL under load
        if fleet is None:
            fleet = _FleetProc(
                env, base, engine_json, cfg.replicas, cfg,
                extra_args=aot_args,
            )
            fleet.wait_all_ready(cfg.startup_timeout_s)
        report["kill"] = _kill_phase(fleet, cfg)

        # ---- phase 3: rolling reload under load
        if cfg.reloads > 0:
            report["rolling"] = _rolling_phase(fleet, cfg)
        fleet.stop()
        fleet = None

        # ---- phase 4: one sharded-replica composition point (R x S)
        if cfg.sharded_point:
            # ONE client by design: concurrent sharded queries on the
            # one-core virtual 8-device mesh starve each other's XLA:CPU
            # spin-wait collectives into multi-second stalls (measured:
            # p50 ~10 ms sequential, >20 s tails at concurrency 4), so
            # any concurrency here measures scheduler collapse, not the
            # R x S composition this point demonstrates. Real multi-chip
            # replicas have per-chip threads and no such cliff.
            point, _ = _throughput_point(
                env, base, engine_json, cfg,
                2,  # fixed-size composition point, independent of cfg.replicas
                extra_args=("--shard-factors",),
                env_extra={
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=8"
                },
                clients_override=1,
            )
            report["shardedReplica"] = point
        report["totalSeconds"] = round(time.monotonic() - t_start, 1)
    except (ChaosError, subprocess.TimeoutExpired) as e:
        report["error"] = str(e)[:800]
        report["ok"] = False
        return report
    finally:
        if fleet is not None:
            fleet.stop()
        if not cfg.keep_dir and cfg.base_dir is None:
            shutil.rmtree(base, ignore_errors=True)
        else:
            report["storageDir"] = base
    kill = report.get("kill", {})
    rolling = report.get("rolling", {"failedQueries": 0, "reloadsOk": True,
                                     "converged": True,
                                     "crossGenerationViolations": 0})
    tp = report["throughput"]
    multi_core = (os.cpu_count() or 1) >= 2
    report["ok"] = bool(
        all(p["failed"] == 0 and p["transportErrors"] == 0 for p in tp["points"])
        and kill.get("killCount", 0) >= cfg.kills
        and kill.get("failedQueries") == 0
        and kill.get("allRespawned")
        and kill.get("p99Recovered")
        and rolling.get("failedQueries") == 0
        and rolling.get("reloadsOk")
        and rolling.get("converged")
        and rolling.get("crossGenerationViolations") == 0
        # AOT rolling contract: a full rotation must land every replica
        # on tier 1 with zero serve-time compiles (the jit-witness gate,
        # asserted over the wire instead of in-process)
        and (
            not cfg.aot
            or cfg.reloads == 0
            or (
                rolling.get("aot", {}).get("serveTimeCompiles") == 0
                and rolling.get("aot", {}).get("allTier1")
            )
        )
        # q/s must scale on a multi-core host; a one-core host documents
        # the ceiling instead of faking the claim (memory: one-core boxes
        # wall every throughput-ratio assertion)
        and (not multi_core or tp["scaling"] is None or tp["scaling"] >= 1.5)
        and (
            not cfg.sharded_point
            or (
                report.get("shardedReplica", {}).get("failed") == 0
                and report.get("shardedReplica", {}).get("transportErrors") == 0
                and report.get("shardedReplica", {}).get("qps", 0) > 0
            )
        )
    )
    return report


# ---------------------------------------------------------------------------
# Cross-host elastic-fleet chaos (``pio chaos-fleet``; ISSUE 17)
# ---------------------------------------------------------------------------
#
# ``pio chaos-serve`` kills one replica behind one router; this drill
# kills a whole "host". Two independent ``pio deploy --replicas N``
# trees on SEPARATE storage basedirs (two hosts in miniature — separate
# supervisors, separate routers) share one endpoint-registry directory,
# so both routers see one 2N-replica consistent-hash ring. Then:
#
# 1. **host-kill** — SIGKILL host A's entire tree (every replica AND its
#    router/supervisor) under concurrent clients that never retry a
#    delivered answer but DO fail over between routers on transport
#    errors (the dead router never answered — the idempotent-read retry
#    is the client-visible router-HA contract). Verdict: zero failed
#    queries; the surviving router routes around the dead replicas
#    within its probe interval and evicts them on lease expiry; the
#    killed host, restarted, rejoins the ring through the registry with
#    no operator re-wiring.
# 2. **autoscale** — a 1-replica fleet with ``--autoscale 1:2`` under
#    watermark-crossing load must scale up (new replica binds port 0,
#    self-reports, joins the ring); when the load drops to a trickle it
#    must retire the extra replica drain-aware — the trickle (and the
#    full load before it) loses zero queries.
# 3. **stale-while-down** — a 1-replica fleet with
#    ``--stale-cache-ttl-s``: after its replica is SIGKILLed, a
#    previously-answered scope is served from the router's stale cache
#    (200 + ``X-PIO-Stale: true``), an unknown scope still gets a clean
#    503, and after respawn the scope is fresh again with no marker.
#    While any owner is alive the marker must never appear.
#
# Same contract as the other drills: stdlib-only, real subprocesses,
# verdicts as asserted fields. Feeds the bench ``fleet_elastic`` section
# and its smoke guard.


@dataclasses.dataclass(frozen=True)
class FleetChaosConfig:
    """Knobs of one elastic-fleet chaos run (CLI: ``pio chaos-fleet``)."""

    replicas_per_host: int = 1
    clients: int = 16
    phase_seconds: float = 6.0
    #: synthetic `rate` events the tiny model trains on
    train_events: int = 400
    train_users: int = 60
    train_items: int = 120
    rank: int = 8
    iterations: int = 2
    #: endpoint-registry lease TTL for the host-kill phase — the
    #: eviction clock the surviving router runs on
    lease_ttl_s: float = 1.0
    seed: int = 0
    autoscale_phase: bool = True
    stale_phase: bool = True
    probe_interval_s: float = 0.25
    breaker_reset_s: float = 1.0
    query_timeout_s: float = 20.0
    startup_timeout_s: float = 180.0
    total_timeout_s: float = 900.0
    base_dir: str | None = None
    keep_dir: bool = False

    def __post_init__(self) -> None:
        if self.replicas_per_host < 1 or self.clients < 1:
            raise ValueError("replicas_per_host and clients must be >= 1")
        if self.lease_ttl_s <= 0:
            raise ValueError("lease_ttl_s must be > 0")


class _HAQueryClients(_QueryClients):
    """Query clients with client-visible router failover: a transport
    error from one router (connection refused/reset — the router died
    before DELIVERING an answer) is retried once on the other router;
    an HTTP error from a live router is a failed query and is never
    retried. ``router_failovers`` counts recovered failovers;
    ``transport_errors`` keeps its parent meaning of an UNRECOVERED
    request (every router transport-failed) — still a failure."""

    def __init__(self, ports: list[int], cfg):
        super().__init__(ports[0], cfg)
        self.ports = list(ports)
        self.router_failovers = 0
        self._preferred = 0  # advisory: index of the last router that answered

    def _run(self, cid: int) -> None:
        cfg = self.cfg
        users = [
            f"u{u}" for u in range(cfg.train_users) if u % cfg.clients == cid
        ] or [f"u{cid % cfg.train_users}"]
        rng = random.Random(cfg.seed * 7919 + cid)
        while not self.stop.is_set():
            user = users[rng.randrange(len(users))]
            payload = json.dumps({"user": user, "num": 4}).encode()
            t0 = time.monotonic()
            status = 0
            generation = 0
            answered = False
            preferred = self._preferred
            order = [
                self.ports[(preferred + k) % len(self.ports)]
                for k in range(len(self.ports))
            ]
            for attempt, port in enumerate(order):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/queries.json",
                    data=payload,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                try:
                    with urllib.request.urlopen(
                        req, timeout=cfg.query_timeout_s
                    ) as resp:
                        resp.read()
                        status = resp.status
                        generation = int(
                            resp.headers.get("X-PIO-Generation", "0") or 0
                        )
                except urllib.error.HTTPError as e:
                    e.read()
                    status = e.code
                except Exception:
                    if attempt + 1 < len(order):
                        with self._lock:
                            self.router_failovers += 1
                    continue
                answered = True
                with self._lock:
                    self._preferred = self.ports.index(port)
                break
            if not answered:
                with self._lock:
                    self.transport_errors += 1
                time.sleep(0.05)
                continue
            t1 = time.monotonic()
            with self._lock:
                self.samples.append((t1, t1 - t0, status, user, generation))


def _elastic_host(base: str, seed_dir: str, name: str) -> tuple[str, dict]:
    """Clone the trained seed storage into a fresh per-"host" basedir —
    two hosts with independent supervisors/state files, one shared model
    lineage (the shared-filesystem deployment the registry targets)."""
    host_dir = os.path.join(base, name)
    shutil.copytree(seed_dir, host_dir)
    return host_dir, _storage_env(host_dir, "sqlite")


def _elastic_fleet(
    env: dict,
    host_dir: str,
    engine_json: str,
    reg_dir: str,
    cfg: FleetChaosConfig,
    replicas: int,
    extra_args: tuple[str, ...] = (),
) -> _FleetProc:
    return _FleetProc(
        env, host_dir, engine_json, replicas, cfg,
        extra_args=(
            "--endpoint-registry", reg_dir,
            "--lease-ttl-s", str(cfg.lease_ttl_s),
            "--drain-deadline-s", "5",
            *extra_args,
        ),
    )


def _wait_fleet_view(
    fleet: _FleetProc, expect: int, timeout_s: float, what: str
) -> float:
    """Until the router's ring holds EXACTLY ``expect`` healthy replicas
    (registry-joined fleets start with an empty ring and grow as
    replicas self-report); returns seconds waited."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if fleet.proc.poll() is not None:
            raise ChaosError(
                f"{what}: fleet exited rc={fleet.proc.returncode} before ready"
            )
        status = fleet.status()
        if status is not None:
            reps = status.get("replicas", [])
            if len(reps) == expect and all(r.get("healthy") for r in reps):
                return time.monotonic() - t0
        time.sleep(0.1)
    raise ChaosError(f"{what}: ring never reached {expect} healthy replicas")


def _host_kill_phase(
    base: str, seed_dir: str, engine_json: str, cfg: FleetChaosConfig
) -> dict:
    """SIGKILL one entire host's tree under HA clients; the surviving
    router absorbs, the restarted host rejoins through the registry."""
    reg_dir = os.path.join(base, "endpoints-hostkill")
    host_a, env_a = _elastic_host(base, seed_dir, "hostA")
    host_b, env_b = _elastic_host(base, seed_dir, "hostB")
    expect = 2 * cfg.replicas_per_host
    fleet_a = _elastic_fleet(env_a, host_a, engine_json, reg_dir, cfg,
                             cfg.replicas_per_host)
    fleet_b: _FleetProc | None = None
    fleet_a2: _FleetProc | None = None
    clients: _HAQueryClients | None = None
    try:
        fleet_b = _elastic_fleet(env_b, host_b, engine_json, reg_dir, cfg,
                                 cfg.replicas_per_host)
        ready_s = max(
            _wait_fleet_view(fleet_a, expect, cfg.startup_timeout_s, "hostA"),
            _wait_fleet_view(fleet_b, expect, cfg.startup_timeout_s, "hostB"),
        )
        _warm_fleet(fleet_a.port, cfg)
        _warm_fleet(fleet_b.port, cfg)
        clients = _HAQueryClients([fleet_a.port, fleet_b.port], cfg)
        clients.start()
        t0 = time.monotonic()
        time.sleep(max(0.5, cfg.phase_seconds * 0.25))

        # ---- SIGKILL every process of host A: replicas first, then the
        # router/supervisor itself — the whole host goes dark at once
        t_kill = time.monotonic()
        pids = [
            int(r["pid"])
            for r in (fleet_a.state() or {}).get("replicas", [])
            if r.get("pid")
        ]
        for pid in pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        fleet_a.proc.send_signal(signal.SIGKILL)
        fleet_a.proc.wait(timeout=30)

        # ---- surviving router: routed-around (unhealthy or gone) fast,
        # evicted from the ring on lease expiry
        absorb_s = None
        evict_s = None
        absorb_deadline = (
            t_kill + cfg.lease_ttl_s + 10 * cfg.probe_interval_s + 10.0
        )
        while time.monotonic() < absorb_deadline:
            status = fleet_b.status() or {}
            reps = status.get("replicas", [])
            dead_visible = [
                r for r in reps if not r.get("healthy")
            ]
            if absorb_s is None and len(reps) - len(dead_visible) == (
                cfg.replicas_per_host
            ):
                absorb_s = time.monotonic() - t_kill
            if len(reps) == cfg.replicas_per_host:
                evict_s = time.monotonic() - t_kill
                if absorb_s is None:  # evicted before a poll saw "unhealthy"
                    absorb_s = evict_s
                break
            time.sleep(0.05)

        time.sleep(max(1.0, cfg.phase_seconds * 0.25))

        # ---- restart host A: same basedir, same registry — it must
        # rejoin the ring with no re-wiring
        fleet_a2 = _elastic_fleet(env_a, host_a, engine_json, reg_dir, cfg,
                                  cfg.replicas_per_host)
        t_restart = time.monotonic()
        rejoin_s = None
        rejoin_deadline = t_restart + cfg.startup_timeout_s
        while time.monotonic() < rejoin_deadline:
            status = fleet_b.status() or {}
            reps = status.get("replicas", [])
            if len(reps) == expect and all(r.get("healthy") for r in reps):
                rejoin_s = time.monotonic() - t_restart
                break
            time.sleep(0.1)
        time.sleep(max(1.0, cfg.phase_seconds * 0.25))
        t_end = time.monotonic()
        clients.join()
        overall = clients.summarize(t0, t_end)
        failed = overall["failed"] + overall["transportErrors"]
        return {
            "replicasPerHost": cfg.replicas_per_host,
            "readySeconds": round(ready_s, 2),
            "killedPids": len(pids) + 1,  # replicas + the router tree
            "overall": overall,
            "routerFailovers": clients.router_failovers,
            "absorbSeconds": round(absorb_s, 3) if absorb_s is not None else None,
            "evictSeconds": round(evict_s, 3) if evict_s is not None else None,
            "rejoinSeconds": round(rejoin_s, 3) if rejoin_s is not None else None,
            "failedQueries": failed,
            "ok": bool(
                failed == 0
                and overall["requests"] > 0
                and absorb_s is not None
                and evict_s is not None
                and rejoin_s is not None
            ),
        }
    finally:
        if clients is not None:
            clients.stop.set()
        for f in (fleet_a, fleet_a2, fleet_b):
            if f is not None:
                f.stop()


def _autoscale_phase(
    base: str, seed_dir: str, engine_json: str, cfg: FleetChaosConfig
) -> dict:
    """Watermark scale-up under load, then drain-aware retirement under
    a trickle — zero queries lost across both transitions."""
    reg_dir = os.path.join(base, "endpoints-autoscale")
    host_dir, env = _elastic_host(base, seed_dir, "hostScale")
    # watermarks sized to the drill: 16 concurrent clients blow far past
    # 8 q/s per replica; the 1 q/s trickle sits far below 2 q/s per
    # replica once the trailing window drains
    fleet = _elastic_fleet(
        env, host_dir, engine_json, reg_dir, cfg, 1,
        extra_args=(
            "--autoscale", "1:2",
            "--scale-up-qps", "8",
            "--scale-down-qps", "2",
            "--scale-cooldown-s", "1",
        ),
    )
    clients: _QueryClients | None = None
    trickle_stop = threading.Event()
    trickle = {"requests": 0, "failed": 0, "statuses": []}
    trickle_lock = threading.Lock()

    def trickle_client() -> None:
        i = 0
        while not trickle_stop.is_set():
            i += 1
            payload = json.dumps(
                {"user": f"u{i % cfg.train_users}", "num": 4}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{fleet.port}/queries.json",
                data=payload,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            status = 0
            try:
                with urllib.request.urlopen(
                    req, timeout=cfg.query_timeout_s
                ) as resp:
                    resp.read()
                    status = resp.status
            except urllib.error.HTTPError as e:
                e.read()
                status = e.code
            except Exception:
                status = 0
            with trickle_lock:
                trickle["requests"] += 1
                if not 200 <= status < 300:
                    trickle["failed"] += 1
                    trickle["statuses"].append(status)
            trickle_stop.wait(1.0)

    try:
        _wait_fleet_view(fleet, 1, cfg.startup_timeout_s, "autoscale")
        _warm_fleet(fleet.port, cfg)
        clients = _QueryClients(fleet.port, cfg)
        clients.start()
        t0 = time.monotonic()

        # ---- scale-up: the ring must grow to 2 healthy replicas (cold
        # replica start pays the model load, hence the startup budget)
        scale_up_s = None
        deadline = t0 + cfg.startup_timeout_s
        while time.monotonic() < deadline:
            status = fleet.status() or {}
            reps = status.get("replicas", [])
            if len(reps) == 2 and all(r.get("healthy") for r in reps):
                scale_up_s = time.monotonic() - t0
                break
            time.sleep(0.1)
        t_load_end = time.monotonic()
        clients.join()
        load_summary = clients.summarize(t0, t_load_end)

        # ---- scale-down: drop to a trickle; the autoscaler must retire
        # one replica drain-aware (its registry entry withdrawn on clean
        # exit) without losing a single trickle query
        trickle_thread = threading.Thread(
            target=trickle_client, name="chaos-trickle", daemon=True
        )
        trickle_thread.start()
        scale_down_s = None
        if scale_up_s is not None:
            t1 = time.monotonic()
            deadline = t1 + cfg.startup_timeout_s
            while time.monotonic() < deadline:
                status = fleet.status() or {}
                reps = status.get("replicas", [])
                if len(reps) == 1 and all(r.get("healthy") for r in reps):
                    scale_down_s = time.monotonic() - t1
                    break
                time.sleep(0.1)
        # a couple more trickle beats AFTER the retirement settles —
        # the survivor must be serving alone
        trickle_stop.wait(2.0)
        trickle_stop.set()
        trickle_thread.join(timeout=10)
        with trickle_lock:
            trickle_out = dict(trickle)
        failed = (
            load_summary["failed"]
            + load_summary["transportErrors"]
            + trickle_out["failed"]
        )
        return {
            "scaleUpSeconds": round(scale_up_s, 2)
            if scale_up_s is not None
            else None,
            "scaleDownSeconds": round(scale_down_s, 2)
            if scale_down_s is not None
            else None,
            "loadWindow": load_summary,
            "trickle": {
                "requests": trickle_out["requests"],
                "failed": trickle_out["failed"],
                "failedStatuses": sorted(set(trickle_out["statuses"])),
            },
            "failedQueries": failed,
            "ok": bool(
                scale_up_s is not None
                and scale_down_s is not None
                and failed == 0
                and load_summary["requests"] > 0
                and trickle_out["requests"] > 0
            ),
        }
    finally:
        trickle_stop.set()
        if clients is not None:
            clients.stop.set()
        fleet.stop()


def _query_once(
    port: int, payload: bytes, timeout_s: float
) -> tuple[int, dict]:
    """One never-retried query; returns (status, lowercased headers)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/queries.json",
        data=payload,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            resp.read()
            return resp.status, {k.lower(): v for k, v in resp.headers.items()}
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, {k.lower(): v for k, v in e.headers.items()}


def _stale_phase(
    base: str, seed_dir: str, engine_json: str, cfg: FleetChaosConfig
) -> dict:
    """Stale-while-down: with every owner replica dead, a cached scope
    is served marked-stale, an uncached scope is a clean 503, and a
    healthy fleet never emits the marker."""
    reg_dir = os.path.join(base, "endpoints-stale")
    host_dir, env = _elastic_host(base, seed_dir, "hostStale")
    stale_cfg = dataclasses.replace(cfg, lease_ttl_s=10.0)  # outlive the outage
    fleet = _elastic_fleet(
        env, host_dir, engine_json, reg_dir, stale_cfg, 1,
        extra_args=("--stale-cache-ttl-s", "60"),
    )
    cached = json.dumps({"user": "u0", "num": 4}).encode()
    uncached = json.dumps({"user": "u59", "num": 4}).encode()
    report: dict[str, Any] = {}
    try:
        _wait_fleet_view(fleet, 1, cfg.startup_timeout_s, "stale")
        _warm_fleet(fleet.port, cfg, distinct_users=1)  # warms u0
        fresh_status, fresh_headers = _query_once(
            fleet.port, cached, cfg.query_timeout_s
        )
        report["freshStatus"] = fresh_status
        report["freshMarked"] = "x-pio-stale" in fresh_headers

        state = fleet.state() or {}
        rep = (state.get("replicas") or [{}])[0]
        rid, pid = str(rep.get("id")), int(rep.get("pid") or 0)
        if not pid:
            raise ChaosError("stale phase: no replica pid on file")
        os.kill(pid, signal.SIGKILL)
        try:
            stale_status, stale_headers = _query_once(
                fleet.port, cached, cfg.query_timeout_s
            )
        except OSError as e:
            stale_status, stale_headers = 0, {"error": str(e)}
        report["staleStatus"] = stale_status
        report["staleMarked"] = stale_headers.get("x-pio-stale") == "true"
        try:
            uncached_status, uncached_headers = _query_once(
                fleet.port, uncached, cfg.query_timeout_s
            )
        except OSError:
            uncached_status, uncached_headers = 0, {}
        report["uncachedStatus"] = uncached_status
        report["uncachedMarked"] = "x-pio-stale" in uncached_headers

        respawned = fleet.wait_respawn(rid, pid, cfg.startup_timeout_s)
        report["respawned"] = respawned
        after_status, after_marked = 0, True
        deadline = time.monotonic() + cfg.startup_timeout_s
        while time.monotonic() < deadline:
            try:
                after_status, after_headers = _query_once(
                    fleet.port, cached, cfg.query_timeout_s
                )
            except OSError:
                time.sleep(0.2)
                continue
            after_marked = "x-pio-stale" in after_headers
            if after_status == 200 and not after_marked:
                break
            time.sleep(0.2)
        report["freshAfterStatus"] = after_status
        report["freshAfterMarked"] = after_marked
    finally:
        fleet.stop()
    report["ok"] = bool(
        report.get("freshStatus") == 200
        and not report.get("freshMarked")
        and report.get("staleStatus") == 200
        and report.get("staleMarked")
        and report.get("uncachedStatus") == 503
        and not report.get("uncachedMarked")
        and report.get("respawned")
        and report.get("freshAfterStatus") == 200
        and not report.get("freshAfterMarked")
    )
    return report


def run_chaos_fleet(cfg: FleetChaosConfig) -> dict:
    """Run the full elastic-fleet drill; returns the report dict
    (``report["ok"]`` is the overall verdict — the CLI exit code and the
    bench ``fleet_elastic`` smoke guard key off the individual fields)."""
    base = cfg.base_dir or tempfile.mkdtemp(prefix="pio_chaos_fleet_")
    os.makedirs(base, exist_ok=True)
    seed_dir = os.path.join(base, "seed")
    os.makedirs(seed_dir, exist_ok=True)
    env = _storage_env(seed_dir, "sqlite")
    report: dict[str, Any] = {
        "replicasPerHost": cfg.replicas_per_host,
        "clients": cfg.clients,
        "leaseTtlSeconds": cfg.lease_ttl_s,
        "seed": cfg.seed,
        "cpuCount": os.cpu_count(),
    }
    t_start = time.monotonic()
    try:
        t0 = time.monotonic()
        engine_json = _serve_setup(env, seed_dir, cfg)
        report["setupSeconds"] = round(time.monotonic() - t0, 1)
        report["hostKill"] = _host_kill_phase(base, seed_dir, engine_json, cfg)
        if cfg.autoscale_phase:
            report["autoscale"] = _autoscale_phase(
                base, seed_dir, engine_json, cfg
            )
        if cfg.stale_phase:
            report["staleWhileDown"] = _stale_phase(
                base, seed_dir, engine_json, cfg
            )
        report["totalSeconds"] = round(time.monotonic() - t_start, 1)
    except (ChaosError, subprocess.TimeoutExpired) as e:
        report["error"] = str(e)[:800]
        report["ok"] = False
        return report
    finally:
        if not cfg.keep_dir and cfg.base_dir is None:
            shutil.rmtree(base, ignore_errors=True)
        else:
            report["storageDir"] = base
    report["ok"] = bool(
        report.get("hostKill", {}).get("ok")
        and (not cfg.autoscale_phase or report.get("autoscale", {}).get("ok"))
        and (not cfg.stale_phase or report.get("staleWhileDown", {}).get("ok"))
    )
    return report
