"""Deterministic fault injection for storage repos and RPC transports.

The resilience layer is only trustworthy if its failure paths are
*executed*, not just written: this harness wraps a callable, a
dispatch-protocol service, or a whole repository object and injects
error / latency / flap schedules on command, deterministically (no
randomness — schedules are by call index or by an injectable clock), so
``tests/test_resilience.py`` and the bench's ``resilience`` section can
stage a storage outage and measure recovery.

Typical shapes::

    inj = FaultInjector()
    svc = StorageRpcService(client=backing)
    server, _ = start_background(inj.wrap_dispatch(svc.dispatch))
    ...
    inj.fail_for(2.0)        # every call errors for the next 2 s
    inj.fail_next(3)         # exactly the next 3 calls error
    inj.delay_for(1.0, 500)  # +500 ms latency for 1 s
    inj.flap(period_s=0.2)   # alternate up/down windows (connection flaps)

Stdlib-only by contract (tests/test_ci_guards.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable

__all__ = ["FaultError", "FaultInjector"]


class FaultError(Exception):
    """The injected failure (dependency-down stand-in)."""


class FaultInjector:
    """Shared fault switchboard; every ``wrap_*`` product consults it.

    Thread-safe: load generators call through wrapped objects while the
    orchestrating thread flips schedules.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._fail_until = 0.0
        self._fail_next = 0
        self._delay_until = 0.0
        self._delay_next = 0
        self._delay_ms = 0.0
        self._flap_period_s = 0.0
        self._flap_started = 0.0
        self._script: list[str] = []
        # observability for tests/bench
        self.calls = 0
        self.injected_errors = 0
        self.injected_delays = 0

    # -------------------------------------------------------------- schedule
    def fail_for(self, seconds: float) -> None:
        """Every call within the next ``seconds`` raises (an outage)."""
        with self._lock:
            self._fail_until = self._clock() + seconds

    def fail_next(self, n: int = 1) -> None:
        """Exactly the next ``n`` calls raise (a transient blip)."""
        with self._lock:
            self._fail_next += n

    def delay_for(self, seconds: float, delay_ms: float) -> None:
        """Calls within ``seconds`` are slowed by ``delay_ms`` (brownout)."""
        with self._lock:
            self._delay_until = self._clock() + seconds
            self._delay_ms = delay_ms

    def delay_next(self, n: int, delay_ms: float) -> None:
        with self._lock:
            self._delay_next += n
            self._delay_ms = delay_ms

    def flap(self, period_s: float) -> None:
        """Alternate down/up windows of ``period_s`` each, starting down
        now; ``period_s=0`` stops flapping."""
        with self._lock:
            self._flap_period_s = period_s
            self._flap_started = self._clock()

    def script(self, steps: Iterable[str]) -> None:
        """Exact per-call schedule, consumed one step per call:
        ``"ok"`` | ``"error"`` | ``"delay:<ms>"``. After the script runs
        dry the timed/counted schedules above apply again."""
        with self._lock:
            self._script.extend(steps)

    def clear(self) -> None:
        """Back to healthy immediately (counters are kept)."""
        with self._lock:
            self._fail_until = 0.0
            self._fail_next = 0
            self._delay_until = 0.0
            self._delay_next = 0
            self._flap_period_s = 0.0
            self._script.clear()

    # ------------------------------------------------------------- injection
    def _decide(self) -> tuple[float, bool]:
        """(delay_ms, should_fail) for this call; mutates counters."""
        with self._lock:
            self.calls += 1
            if self._script:
                step = self._script.pop(0)
                if step == "error":
                    self.injected_errors += 1
                    return 0.0, True
                if step.startswith("delay:"):
                    self.injected_delays += 1
                    return float(step.split(":", 1)[1]), False
                return 0.0, False
            now = self._clock()
            delay = 0.0
            if self._delay_next > 0 or now < self._delay_until:
                if self._delay_next > 0:
                    self._delay_next -= 1
                delay = self._delay_ms
                self.injected_delays += 1
            fail = False
            if self._fail_next > 0:
                self._fail_next -= 1
                fail = True
            elif now < self._fail_until:
                fail = True
            elif self._flap_period_s > 0:
                phase = int((now - self._flap_started) / self._flap_period_s)
                fail = phase % 2 == 0  # starts down
            if fail:
                self.injected_errors += 1
            return delay, fail

    def before_call(self, label: str = "") -> None:
        """Apply the schedule to one call: maybe sleep, maybe raise."""
        delay_ms, fail = self._decide()
        if delay_ms > 0:
            time.sleep(delay_ms / 1000.0)
        if fail:
            raise FaultError(f"injected fault{f' ({label})' if label else ''}")

    # -------------------------------------------------------------- wrapping
    def wrap(self, fn: Callable[..., Any], label: str = "") -> Callable[..., Any]:
        """A callable that consults the schedule, then delegates."""

        def wrapped(*args: Any, **kwargs: Any) -> Any:
            self.before_call(label or getattr(fn, "__name__", ""))
            return fn(*args, **kwargs)

        return wrapped

    def wrap_dispatch(self, dispatch: Callable[..., Any]) -> Callable[..., Any]:
        """Wrap a service's ``dispatch`` for use behind ``api.http``: an
        injected error surfaces as the transport's generic 500, exactly
        what a crashing backend looks like to a remote client."""
        return self.wrap(dispatch, label="dispatch")

    def wrap_repo(self, repo: Any) -> Any:
        """Proxy every public method of a repository (or any object)
        through the schedule — for injecting faults below the SPI."""
        injector = self

        class _FaultyRepo:
            def __getattr__(self, name: str) -> Any:
                attr = getattr(repo, name)
                if name.startswith("_") or not callable(attr):
                    return attr
                return injector.wrap(attr, label=name)

            def __repr__(self) -> str:  # pragma: no cover - debugging aid
                return f"FaultyRepo({repo!r})"

        return _FaultyRepo()

    def to_json(self) -> dict:
        with self._lock:
            return {
                "calls": self.calls,
                "injectedErrors": self.injected_errors,
                "injectedDelays": self.injected_delays,
            }
