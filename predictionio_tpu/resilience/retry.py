"""Retry policy with exponential backoff + full jitter, and deadlines.

Parity rationale: the reference rides on HBase/JDBC client stacks that
retry transient faults internally (HBase's ``hbase.client.retries.number``
defaults to 35 attempts with bounded backoff); our stdlib RPC transport
has no such layer, so the framework provides one. Full jitter follows the
AWS Architecture Blog result ("Exponential Backoff and Jitter"): sleeping
``uniform(0, min(cap, base * 2**attempt))`` avoids the synchronized retry
waves that fixed backoff produces when many clients fail together.

A :class:`Deadline` is an *overall* per-request budget: every attempt's
timeout and every backoff sleep is clamped to the remaining budget, so a
retried call never exceeds what the caller was willing to wait in total.
The ambient deadline propagates via a :mod:`contextvars` scope
(:func:`deadline_scope`) so intermediate layers need no plumbing.

Stdlib-only by contract (tests/test_ci_guards.py) — this package is host
orchestration and must import neither jax nor any framework layer.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import random
import time
from typing import Any, Callable, Iterator

__all__ = [
    "Deadline",
    "DeadlineExceededError",
    "RetryPolicy",
    "current_deadline",
    "deadline_scope",
]


class DeadlineExceededError(Exception):
    """The overall per-request budget ran out (possibly across retries)."""


class Deadline:
    """An absolute point on the monotonic clock by which work must finish."""

    __slots__ = ("_expires_at", "_clock")

    def __init__(self, expires_at: float, clock: Callable[[], float] = time.monotonic):
        self._expires_at = expires_at
        self._clock = clock

    @classmethod
    def after(
        cls, seconds: float, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        return cls(clock() + seconds, clock)

    def remaining(self) -> float:
        """Seconds left; never negative."""
        return max(0.0, self._expires_at - self._clock())

    @property
    def expired(self) -> bool:
        return self._clock() >= self._expires_at

    def clamp(self, timeout: float) -> float:
        """``timeout`` reduced to the remaining budget."""
        return min(timeout, self.remaining())

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


_CURRENT_DEADLINE: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "pio_resilience_deadline", default=None
)


def current_deadline() -> Deadline | None:
    """The ambient deadline for this thread/context, or None."""
    return _CURRENT_DEADLINE.get()


@contextlib.contextmanager
def deadline_scope(seconds: float) -> Iterator[Deadline]:
    """Run a block under an overall time budget. Nested scopes keep the
    *tighter* deadline — an inner ``deadline_scope(60)`` cannot extend an
    outer 2-second budget."""
    outer = _CURRENT_DEADLINE.get()
    inner = Deadline.after(seconds)
    if outer is not None and outer.remaining() < inner.remaining():
        inner = outer
    token = _CURRENT_DEADLINE.set(inner)
    try:
        yield inner
    finally:
        _CURRENT_DEADLINE.reset(token)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How (and whether) to retry a failed call.

    ``max_attempts=1`` is the do-nothing policy: exactly one attempt, no
    sleeps — byte-for-byte today's single-attempt behavior, guarded by
    ``tests/test_ci_guards.py``. Idempotency is the *caller's* call:
    transports pass ``idempotent=False`` for writes, and those retry only
    when ``retry_writes`` was explicitly set.
    """

    max_attempts: int = 1
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    #: writes are retried only when the operator marked them safe (e.g.
    #: inserts with client-generated ids, idempotent upserts)
    retry_writes: bool = False

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")

    def backoff_s(
        self, attempt: int, rng: Callable[[], float] = random.random
    ) -> float:
        """Full-jitter backoff before retry number ``attempt`` (1-based):
        ``uniform(0, min(max_delay, base * 2**(attempt-1)))``."""
        cap = min(self.max_delay_s, self.base_delay_s * (2 ** max(0, attempt - 1)))
        return rng() * cap

    def run(
        self,
        fn: Callable[[], Any],
        *,
        retryable: tuple[type[BaseException], ...] = (Exception,),
        idempotent: bool = True,
        deadline: Deadline | None = None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Callable[[], float] = random.random,
        on_retry: Callable[[int, BaseException], None] | None = None,
    ) -> Any:
        """Call ``fn`` with up to ``max_attempts`` tries.

        Only exceptions in ``retryable`` are retried, and only when the
        call is ``idempotent`` (or ``retry_writes`` is set). The deadline
        budget is consumed across attempts: a backoff sleep is clamped to
        the remaining budget and an exhausted budget re-raises the last
        failure immediately (:class:`DeadlineExceededError` if no attempt
        ran at all).
        """
        may_retry = self.max_attempts > 1 and (idempotent or self.retry_writes)
        attempt = 0
        while True:
            if deadline is not None and deadline.expired:
                raise DeadlineExceededError(
                    f"deadline exhausted before attempt {attempt + 1}"
                )
            attempt += 1
            try:
                return fn()
            except retryable as e:
                if not may_retry or attempt >= self.max_attempts:
                    raise
                delay = self.backoff_s(attempt, rng)
                if deadline is not None:
                    # a backoff that would consume the whole remaining
                    # budget leaves no room for the retry itself — re-raise
                    # the REAL failure now instead of sleeping the budget
                    # away and reporting only "deadline exhausted"
                    remaining = deadline.remaining()
                    if remaining <= 0 or (delay > 0 and delay >= remaining):
                        raise
                    delay = min(delay, remaining)
                if on_retry is not None:
                    on_retry(attempt, e)
                if delay > 0:
                    sleep(delay)
