"""Serving runtime — cross-request dynamic micro-batching.

Sits between the HTTP transport and :class:`QueryService`: concurrent
``POST /queries.json`` requests are coalesced into one
``handle_batch`` call (one device dispatch per batch instead of one per
request). See :mod:`predictionio_tpu.serving.batcher`.

This package must stay importable without jax: the batcher is pure
threading/queue machinery, and tier-1 CI (JAX_PLATFORMS=cpu) guards
that no accelerator dependency creeps in
(``tests/test_ci_guards.py::test_serving_runtime_is_accelerator_free``).
"""

from predictionio_tpu.serving.batcher import (
    AdmissionPolicy,
    BatcherConfig,
    MicroBatcher,
)

__all__ = ["AdmissionPolicy", "BatcherConfig", "MicroBatcher"]
