"""Serving runtime — cross-request micro-batching, caching, coalescing.

Sits between the HTTP transport and :class:`QueryService`:

* :mod:`predictionio_tpu.serving.batcher` — concurrent
  ``POST /queries.json`` requests are coalesced into one
  ``handle_batch`` call (one device dispatch per batch instead of one
  per request);
* :mod:`predictionio_tpu.serving.cache` — result LRU with event-driven
  invalidation, singleflight dedup of identical in-flight queries, and
  the config surface for the device-resident model-state tier (which
  itself lives behind a lazy boundary in
  :mod:`predictionio_tpu.workflow.device_state`).

This package must stay importable without jax: batching and caching are
pure threading/queue/dict machinery, and tier-1 CI (JAX_PLATFORMS=cpu)
guards that no accelerator dependency creeps in (the layering manifest's
``predictionio_tpu/serving`` entry, asserted by
``tests/test_ci_guards.py``).
"""

from predictionio_tpu.serving.ann import AnnConfig
from predictionio_tpu.serving.batcher import (
    AdmissionPolicy,
    BatcherConfig,
    MicroBatcher,
)
from predictionio_tpu.serving.cache import (
    CacheConfig,
    CacheStats,
    ResultCache,
    Singleflight,
)

__all__ = [
    "AdmissionPolicy",
    "AnnConfig",
    "BatcherConfig",
    "CacheConfig",
    "CacheStats",
    "MicroBatcher",
    "ResultCache",
    "Singleflight",
]
