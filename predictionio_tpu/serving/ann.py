"""Approximate-retrieval configuration surface (``pio deploy --ann``).

Only the CONFIG lives here: the serving package must stay importable
without jax or numpy (layering manifest), so the IVF index build and the
two-stage query kernel live in :mod:`predictionio_tpu.ops.ivf` behind
the lazy boundary in :mod:`predictionio_tpu.workflow.device_state` —
the same split the ``pin_model`` cache tier uses. With ``enabled``
False (the default) nothing changes anywhere: the exact scoring path is
byte-identical to a build without this module, and ``ops.ivf`` is never
imported (both CI-guarded).
"""

from __future__ import annotations

import dataclasses

__all__ = ["AnnConfig"]


@dataclasses.dataclass(frozen=True)
class AnnConfig:
    """Knobs of the IVF retrieval stage (docs/performance.md has the
    sizing rule of thumb: ``nlist ~ sqrt(catalog)``, then raise
    ``nprobe`` until measured recall@K meets the product bar)."""

    #: route template top-K through the clustered index
    enabled: bool = False
    #: number of k-means clusters; 0 = auto (~sqrt(catalog items))
    nlist: int = 0
    #: clusters scored per query — the recall/latency dial. Per-query
    #: cost scales with ``nprobe * (catalog / nlist)``; ``nprobe >=
    #: nlist`` reproduces exact top-K bit-identically.
    nprobe: int = 8
    #: k-means seed (build is deterministic per (factors, seed))
    seed: int = 0
    #: Lloyd iterations after k-means++ seeding
    kmeans_iters: int = 8

    def __post_init__(self) -> None:
        if self.nprobe < 1:
            raise ValueError("nprobe must be >= 1")
        if self.kmeans_iters < 0:
            raise ValueError("kmeans_iters must be >= 0")

    @property
    def cache_mode(self) -> str:
        """Retrieval-mode tag mixed into result-cache/singleflight keys
        so exact and ANN entries can never serve each other — an ANN
        answer is a different (approximate) result for the same body."""
        if not self.enabled:
            return "exact"
        return f"ann[nlist={self.nlist},nprobe={self.nprobe}]"
