"""Dynamic cross-request micro-batcher for the online query path.

The per-request serving path (``api/http.py`` -> ``QueryService
.handle_query``) pays one full predict dispatch per HTTP request: under
concurrency the device serializes on single-query programs while
``handle_batch`` demonstrably amortizes the same work across a whole
batch (see ``docs/performance.md``). This module closes that gap the way
TPU serving stacks do (cf. ALX's batched matrix-factorization serving,
arxiv 2112.02194): requests from independent HTTP handler threads
enqueue into a bounded queue with a per-request completion event; a
single dispatcher thread drains up to ``max_batch_size`` requests or
waits ``max_batch_delay_ms`` past the oldest request (whichever comes
first), pads the batch up to a small set of **bucket sizes** so the
jitted predict programs compile once per bucket (warm-up at startup
pre-compiles all of them), routes the batch through the existing
``QueryService.handle_batch`` / ``batch_predict_base`` path — which
already guarantees per-item error isolation — and resolves each waiting
request with its own ``(status, payload)``.

Admission control is explicit: when the queue is full the configured
policy either rejects immediately (HTTP 429 + ``Retry-After``) or
blocks the caller up to ``block_timeout_ms`` (503 on timeout). Queue
depth, in-flight batch state, bucket hit/miss counts and a per-request
latency decomposition (queue wait / batch-form / handle time) are
recorded in :class:`predictionio_tpu.api.stats.ServingStats` and served
from the query server's ``GET /stats.json``.

No reference counterpart: the reference serves one query per spray
route invocation. This is the TPU-native replacement for that hot path.

NOTE: this module must not import jax (see package docstring) — batching
is host-side orchestration; the device work stays behind
``handle_batch``.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import queue
import threading
import time
from typing import Any, Callable, Sequence

from predictionio_tpu.api.stats import ServingStats

__all__ = ["AdmissionPolicy", "BatcherConfig", "MicroBatcher"]

logger = logging.getLogger(__name__)

#: a submit() whose dispatcher never answers (a bug, not a slow model)
#: must not hang the HTTP handler thread forever
_RESULT_TIMEOUT_S = 300.0


class AdmissionPolicy(str, enum.Enum):
    """What a full queue does to a new request."""

    REJECT = "reject"  # immediate 429 + Retry-After
    BLOCK = "block"  # wait up to block_timeout_ms for a slot, then 503


def _pow2_buckets(max_batch_size: int) -> tuple[int, ...]:
    """1, 2, 4, ... capped at (and always including) ``max_batch_size``."""
    sizes = [1]
    while sizes[-1] * 2 < max_batch_size:
        sizes.append(sizes[-1] * 2)
    if sizes[-1] != max_batch_size:
        sizes.append(max_batch_size)
    return tuple(sizes)


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Knobs of the micro-batcher (CLI: ``pio deploy --batching ...``).

    ``max_batch_delay_ms=0`` is a legal configuration: a lone request
    dispatches immediately (no added latency) and batching still happens
    opportunistically whenever multiple requests are already queued.
    """

    max_batch_size: int = 32
    #: how long the dispatcher waits past the OLDEST queued request for
    #: batchmates; the p99 latency a request can gain over the
    #: per-request path is bounded by ~2x this (one wait while queued +
    #: one batch in flight ahead of it)
    max_batch_delay_ms: float = 2.0
    #: bounded admission queue; full -> the admission policy applies
    max_queue: int = 256
    admission: AdmissionPolicy = AdmissionPolicy.REJECT
    #: BLOCK policy only: how long submit() may wait for a queue slot
    block_timeout_ms: float = 1000.0
    #: batch sizes jit programs are padded to; () = powers of two up to
    #: ``max_batch_size``. Every dispatched batch is padded UP to the
    #: smallest bucket >= its size, so after warm-up no new predict
    #: shapes (hence no recompiles) occur.
    buckets: tuple[int, ...] = ()
    #: sample query body used to pre-compile every bucket at startup
    #: (None = skip warm-up; the first live batch of each bucket pays
    #: the compile instead)
    warmup_body: Any = None

    def __post_init__(self) -> None:
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if self.max_batch_delay_ms < 0:
            raise ValueError("max_batch_delay_ms must be >= 0")
        # accept plain strings from CLI/JSON configs
        object.__setattr__(
            self, "admission", AdmissionPolicy(self.admission)
        )
        if self.buckets:
            raw = sorted(set(int(x) for x in self.buckets))
            if raw[0] < 1:
                raise ValueError("bucket sizes must be >= 1")
            # buckets beyond max_batch_size can never be filled — they
            # would only inflate padding (and compile a dead shape)
            b = tuple(x for x in raw if x <= self.max_batch_size)
            if not b or b[-1] < self.max_batch_size:
                # the largest bucket must fit a full batch or padding
                # would have to truncate
                b = b + (self.max_batch_size,)
            object.__setattr__(self, "buckets", b)

    def bucket_sizes(self) -> tuple[int, ...]:
        return self.buckets or _pow2_buckets(self.max_batch_size)


class _Pending:
    __slots__ = ("body", "enqueued_at", "done", "result", "drained")

    def __init__(self, body: Any):
        self.body = body
        self.enqueued_at = time.monotonic()
        self.done = threading.Event()
        self.result: tuple[int, Any] | None = None
        #: answered by a dead-queue drain (shutdown / dead dispatcher),
        #: not by a dispatched batch — kept out of the latency stats
        self.drained = False


class MicroBatcher:
    """Coalesces concurrent ``submit()`` calls into ``handle_batch`` calls.

    ``handle_batch`` is any ``Sequence[body] -> list[(status, payload)]``
    aligned with its input — in production,
    :meth:`QueryService.handle_batch`, which already provides per-item
    error isolation (one poisoned query gets its own 4xx/5xx; its
    batchmates still get answers).
    """

    def __init__(
        self,
        handle_batch: Callable[[Sequence[Any]], list[tuple[int, Any]]],
        config: BatcherConfig | None = None,
        stats: ServingStats | None = None,
    ):
        self.config = config or BatcherConfig()
        self.stats = stats or ServingStats()
        self._handle = handle_batch
        # handlers that understand padding (QueryService.handle_batch)
        # get told how many leading slots are real, so filler queries pay
        # only predict compute — no serve tail, plugins, feedback, or
        # query-count side effects
        try:
            import inspect

            self._wants_n_real = (
                "n_real" in inspect.signature(handle_batch).parameters
            )
        except (TypeError, ValueError):
            self._wants_n_real = False
        self._buckets = self.config.bucket_sizes()
        self._queue: "queue.Queue[_Pending | None]" = queue.Queue(
            maxsize=self.config.max_queue
        )
        # guards writes to _closed (shared with submit() on HTTP handler
        # threads; piolint PIO201 keeps every post-__init__ write under
        # it). Readers stay lock-free on purpose: the submit/close race
        # is resolved by submit()'s post-enqueue re-check plus the
        # idempotent _drain_dead_queue(), not by mutual exclusion
        self._lock = threading.Lock()
        self._closed = False
        if self.config.warmup_body is not None:
            self.warmup(self.config.warmup_body)
        self._thread = threading.Thread(
            target=self._loop, name="pio-microbatcher", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ client API
    def submit(self, body: Any) -> tuple[int, Any]:
        """Enqueue one query and block until its slice of a batch result
        is available. Returns ``(status, payload)`` exactly like
        ``QueryService.handle_query``."""
        cfg = self.config
        if self._closed:
            return 503, {"message": "Serving runtime is shut down."}
        if not self._thread.is_alive():
            # a dead dispatcher (a bug — the loop is defensive) must fail
            # fast with a clean 503, not park the HTTP thread for the
            # full result timeout; /readyz turns unready via
            # dispatcher_alive() so orchestrators restart the pod
            self.stats.record_rejected()
            return 503, {
                "message": "Serving runtime dispatcher is not running.",
                "retryAfterSeconds": self.retry_after_seconds(),
            }
        pending = _Pending(body)
        try:
            if cfg.admission is AdmissionPolicy.REJECT:
                self._queue.put_nowait(pending)
            else:
                self._queue.put(pending, timeout=cfg.block_timeout_ms / 1000.0)
        except queue.Full:
            retry_after = self.retry_after_seconds()
            if cfg.admission is AdmissionPolicy.REJECT:
                self.stats.record_rejected()
                return 429, {
                    "message": "Server busy: batching queue is full.",
                    "retryAfterSeconds": retry_after,
                }
            self.stats.record_block_timeout()
            return 503, {
                "message": "Server busy: no queue slot within "
                f"{cfg.block_timeout_ms:g} ms.",
                "retryAfterSeconds": retry_after,
            }
        self.stats.record_submitted(self._queue.qsize())
        if self._closed:
            # raced with close(): the dispatcher may already be past its
            # final drain, so this request could sit in a dead queue —
            # answer everything still enqueued ourselves (idempotent with
            # close()'s own post-join drain; done.set() is at-most-once
            # effective)
            self._drain_dead_queue()
        give_up_at = time.monotonic() + _RESULT_TIMEOUT_S
        while not pending.done.wait(timeout=1.0):
            if not self._thread.is_alive():
                # the dispatcher died while this request was queued:
                # answer every stranded request (ours included) instead
                # of letting them sit out the full result timeout
                self._drain_dead_queue(
                    "Serving runtime dispatcher died; request not processed."
                )
                if pending.done.is_set():
                    break
                # in-flight when the dispatcher died (not in the queue):
                # manufacture the same 503, and count it like every other
                # rejected response so /stats.json stays truthful during
                # the incident
                self.stats.record_rejected()
                return 503, {
                    "message": (
                        "Serving runtime dispatcher died; request not processed."
                    ),
                    "retryAfterSeconds": self.retry_after_seconds(),
                }
            if time.monotonic() >= give_up_at:
                return 500, {"message": "Batch dispatcher did not respond."}
        assert pending.result is not None
        if pending.drained:
            # a shutdown/dead-dispatcher 503, not a served request: keep
            # it out of the latency decomposition an operator reads
            # during exactly this kind of incident
            self.stats.record_rejected()
        else:
            self.stats.record_request(
                total_ms=(time.monotonic() - pending.enqueued_at) * 1e3
            )
        return pending.result

    def retry_after_seconds(self) -> int:
        """Backoff hint for admission-control responses (the 429
        ``Retry-After`` header / ``retryAfterSeconds`` field): worst-case
        time for a full queue to drain, using the MEASURED per-batch
        handle time — the batch-forming delay alone would claim ~1 s
        while a slow model really needs many."""
        cfg = self.config
        waves = -(-cfg.max_queue // cfg.max_batch_size)
        per_wave_ms = cfg.max_batch_delay_ms + self.stats.handle_p50_ms()
        return max(1, -(-int(waves * per_wave_ms) // 1000))

    def warmup(self, body: Any) -> None:
        """Pre-compile every bucket shape with ``body`` replicated, largest
        first (jit caches often make smaller related shapes cheaper after
        the big one). Warm-up traffic flows through the REAL batch path so
        the exact programs live traffic will hit are the ones compiled."""
        for size in sorted(self._buckets, reverse=True):
            t0 = time.monotonic()
            try:
                # n_real=0: every slot is padding — full predict compile,
                # zero serve-tail side effects (no plugin/feedback/count)
                self._call([body] * size, n_real=0)
            except Exception:
                # a bad warm-up body must not kill deploy; the bucket
                # simply compiles on first live traffic instead
                logger.exception("micro-batcher warm-up failed at size %d", size)
                continue
            self.stats.record_warmup(size, (time.monotonic() - t0) * 1e3)

    def dispatcher_alive(self) -> bool:
        """Is the dispatcher thread able to answer submissions? Feeds the
        query server's ``/readyz`` readiness probe."""
        return not self._closed and self._thread.is_alive()

    def close(self) -> None:
        """Stop the dispatcher. Requests already being drained are
        answered normally; anything still queued (or racing in) gets 503."""
        with self._lock:
            self._closed = True
        self._queue.put(None)  # wake the dispatcher even when idle
        self._thread.join(timeout=5.0)
        # a submit() that passed its _closed check concurrently with this
        # close may have enqueued after the dispatcher's final drain
        self._drain_dead_queue()

    def _drain_dead_queue(
        self, message: str = "Serving runtime is shut down."
    ) -> None:
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                item.drained = True
                item.result = (503, {"message": message})
                item.done.set()

    # ------------------------------------------------------------ dispatcher
    def _call(self, bodies: Sequence[Any], n_real: int) -> list[tuple[int, Any]]:
        if self._wants_n_real:
            return self._handle(bodies, n_real=n_real)
        return self._handle(bodies)

    def _bucket_for(self, n: int) -> int:
        for size in self._buckets:
            if size >= n:
                return size
        return self._buckets[-1]

    def _drain(self, first: _Pending) -> list[_Pending]:
        """Collect up to ``max_batch_size`` requests, waiting at most
        ``max_batch_delay_ms`` past the arrival of ``first``."""
        cfg = self.config
        batch = [first]
        deadline = first.enqueued_at + cfg.max_batch_delay_ms / 1000.0
        while len(batch) < cfg.max_batch_size:
            remaining = deadline - time.monotonic()
            try:
                if remaining <= 0:
                    # deadline passed: take whatever is already queued,
                    # but never wait for more
                    item = self._queue.get_nowait()
                else:
                    item = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if item is None:  # close() sentinel
                break
            batch.append(item)
        return batch

    def _loop(self) -> None:
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._closed:
                    break
                continue
            if first is None:
                if self._closed:
                    break
                continue
            batch = self._drain(first)
            self._dispatch(batch)
        # drain leftovers so no client hangs on shutdown
        self._drain_dead_queue()

    def _dispatch(self, batch: list[_Pending]) -> None:
        formed_at = time.monotonic()
        for p in batch:
            self.stats.record_queue_wait((formed_at - p.enqueued_at) * 1e3)
        bodies = [p.body for p in batch]
        bucket = self._bucket_for(len(bodies))
        # pad with a copy of the first body: identical query class and
        # shape guarantees, results beyond len(bodies) are discarded
        padded = bodies + [bodies[0]] * (bucket - len(bodies))
        self.stats.record_batch_start(self._queue.qsize())
        called_at = time.monotonic()
        try:
            results = self._call(padded, n_real=len(bodies))
            if len(results) < len(bodies):  # defensive: misaligned handler
                raise RuntimeError(
                    f"handle_batch returned {len(results)} results "
                    f"for {len(padded)} queries"
                )
        except Exception:
            # handle_batch isolates per-item errors itself; reaching this
            # means the batch MACHINERY failed — answer everyone rather
            # than hanging the HTTP threads. Generic message: exception
            # text can leak internals (details go to the log)
            logger.exception("micro-batch dispatch failed")
            results = [
                (500, {"message": "Batch dispatch failed; see server log."})
            ] * len(bodies)
        finished_at = time.monotonic()
        self.stats.record_batch(
            size=len(bodies),
            bucket=bucket,
            form_ms=(called_at - formed_at) * 1e3,
            handle_ms=(finished_at - called_at) * 1e3,
        )
        for p, result in zip(batch, results):
            p.result = result
            p.done.set()
