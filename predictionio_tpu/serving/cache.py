"""Query-path caching & request coalescing for the online serving path.

Real recommendation traffic is highly Zipf-skewed: a small set of hot
users/items dominates, yet the per-request path re-runs the full jitted
score+top-K for every query, and identical concurrent queries are even
scored redundantly side by side inside one micro-batch. This module
closes that gap with three cooperating, individually opt-in tiers (cf.
the redundant-recomputation findings of the Spark-ML serving study,
arxiv 1612.01437, and ALX's device-resident factor state, arxiv
2112.02194):

* **Singleflight coalescing** (:class:`Singleflight`) — identical
  in-flight queries (canonical-JSON key, per engine instance + model
  generation) collapse into ONE scored computation whose result fans
  out to every waiter. Composes with the micro-batcher upstream: only
  the flight leader submits, so a batch never contains duplicate work.
* **Result LRU cache** (:class:`ResultCache`) — bounded entries AND
  bytes, per-entry TTL, and *event-driven invalidation*: the query
  server's ``/reload`` and write hooks bump per-model / per-scope
  generation counters so stale entries die on write rather than only on
  TTL. Fills snapshot the generations they were computed under
  (:meth:`ResultCache.reserve`) and are dropped at commit time if an
  invalidation won the race — a slow fill can never resurrect a result
  the owner already invalidated.
* **Device-resident scoring state** — lives behind a lazy boundary in
  :mod:`predictionio_tpu.workflow.device_state` (this package must stay
  importable without jax; tier-1 CI guards that). Configured here via
  :attr:`CacheConfig.pin_model`, observable via
  :attr:`CacheStats.bytes_pinned`.

Everything surfaces on the query server's ``GET /stats.json`` through
:class:`CacheStats`. Defaults preserve today's behavior exactly: an
all-off :class:`CacheConfig` (or none at all) leaves the prior code
path byte-identical — CI-guarded like the batching and resilience
subsystems.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Iterable, Mapping

__all__ = [
    "CacheConfig",
    "CacheStats",
    "ResultCache",
    "Singleflight",
    "affinity_key",
    "canonical_key",
]

#: a follower waiting on a flight whose leader never answers (a bug, not
#: a slow model) must not hang the HTTP handler thread forever — same
#: contract as the micro-batcher's result timeout
_FLIGHT_TIMEOUT_S = 300.0


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Knobs of the query-path cache (CLI: ``pio deploy --result-cache
    --coalesce --pin-model ...``). Each tier is individually opt-in; the
    all-default config enables nothing."""

    #: serve repeated identical queries from an in-memory LRU
    result_cache: bool = False
    #: most entries the LRU holds (oldest evicted first)
    result_cache_entries: int = 4096
    #: seconds an entry may serve before it expires (<= 0: no TTL —
    #: entries die only by eviction or invalidation)
    result_cache_ttl_s: float = 30.0
    #: approximate payload-byte budget for the LRU (<= 0: unbounded)
    result_cache_max_bytes: int = 64 * 1024 * 1024
    #: collapse identical in-flight queries into one computation
    coalesce: bool = False
    #: pin model state (factor matrices, jitted score+top-K programs)
    #: device-resident across requests — see workflow/device_state.py
    pin_model: bool = False
    #: pin factor SHARDS per device instead of a replica (``pio deploy
    #: --shard-factors``): per-device factor memory drops to
    #: ``O(table / num_devices)`` so catalogs bigger than one device's
    #: memory serve; top-K stays tie-stable-identical to the replicated
    #: exact path (parallel/sharding.py). Implies device residency.
    shard_factors: bool = False
    #: serve factor tables (and IVF slabs under ``--ann``) as int8
    #: codes + per-row f32 scales (``pio deploy --quantize int8``,
    #: ops/quant.py): ~4x more catalog per device and ~4x less gather
    #: traffic, recall-guarded by the two-stage int8-coarse/f32-rescore
    #: kernels. None (default) serves f32 everywhere; composes
    #: multiplicatively with ``shard_factors``. Implies device
    #: residency.
    quantize: str | None = None
    #: query field whose value names the per-entity invalidation scope
    #: (``"user"`` for the recommendation templates); None disables
    #: per-scope invalidation (only full flushes apply)
    scope_field: str | None = "user"

    def __post_init__(self) -> None:
        if self.result_cache_entries < 1:
            raise ValueError("result_cache_entries must be >= 1")
        if self.quantize not in (None, "int8"):
            raise ValueError(
                f"unsupported quantize mode {self.quantize!r} (int8)"
            )

    @property
    def enabled(self) -> bool:
        """Does any tier change the serving path at all?"""
        return (
            self.result_cache
            or self.coalesce
            or self.pin_model
            or self.shard_factors
            or self.quantize is not None
        )


def canonical_key(body: Any) -> str | None:
    """Canonical-JSON cache key of a query body: stable across dict
    ordering, so ``{"user": "1", "num": 4}`` and ``{"num": 4, "user":
    "1"}`` coalesce. None for bodies that do not serialize (those bypass
    the cache and singleflight entirely)."""
    try:
        return json.dumps(
            body, sort_keys=True, separators=(",", ":"), allow_nan=False
        )
    except (TypeError, ValueError):
        return None


class CacheStats:
    """Thread-safe counters for every cache tier, serialized into the
    ``cache`` section of the query server's ``GET /stats.json``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.coalesced = 0  # followers served by another flight's result
        self.flights = 0  # singleflight leaders (distinct computations)
        self.evictions_entries = 0  # LRU-capacity evictions
        self.evictions_bytes = 0  # byte-budget evictions
        self.expirations = 0  # TTL deaths observed at get()
        self.invalidations_scope = 0  # per-scope generation bumps
        self.invalidations_full = 0  # full flushes (reload/degraded/all)
        self.stale_drops = 0  # fills dropped: invalidation won the race
        self.uncacheable = 0  # bodies canonical_key() rejected
        self.entries = 0  # gauge
        self.bytes = 0  # gauge (approximate payload bytes)
        self.bytes_pinned = 0  # gauge: device-resident model state
        #: gauge: per-dtype breakdown of bytes_pinned, read from the
        #: ACTUAL pinned arrays (f32 vs int8 codes vs their scales) —
        #: the bench asserts served truth here, not shape math
        self.bytes_by_dtype: dict = {}
        self.factor_shards = 0  # gauge: --shard-factors model-axis size
        self.model_generation = 0  # gauge

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + by)

    def set_gauge(self, name: str, value: int) -> None:
        with self._lock:
            setattr(self, name, value)

    def to_json(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "coalesced": self.coalesced,
                "flights": self.flights,
                "evictions": {
                    "entries": self.evictions_entries,
                    "bytes": self.evictions_bytes,
                },
                "expirations": self.expirations,
                "invalidations": {
                    "scope": self.invalidations_scope,
                    "full": self.invalidations_full,
                },
                "staleDrops": self.stale_drops,
                "uncacheable": self.uncacheable,
                "entries": self.entries,
                "bytes": self.bytes,
                "bytesPinned": self.bytes_pinned,
                "bytesByDtype": dict(self.bytes_by_dtype),
                "factorShards": self.factor_shards,
                "modelGeneration": self.model_generation,
            }


def _payload_nbytes(value: Any) -> int:
    """Approximate retained size of a cached ``(status, payload)``:
    JSON-serialized length is a good proxy for the dict/list/str graph
    and costs one dumps — exact ``getsizeof`` graph walks are slower and
    no more honest."""
    try:
        return len(json.dumps(value, default=str)) + 64
    except (TypeError, ValueError):
        return sys.getsizeof(value)


class _Entry:
    __slots__ = ("value", "expires_at", "model_gen", "scope", "scope_gen", "nbytes")

    def __init__(self, value, expires_at, model_gen, scope, scope_gen, nbytes):
        self.value = value
        self.expires_at = expires_at
        self.model_gen = model_gen
        self.scope = scope
        self.scope_gen = scope_gen
        self.nbytes = nbytes


@dataclasses.dataclass(frozen=True)
class FillToken:
    """Generation snapshot taken at miss time (:meth:`ResultCache
    .reserve`); :meth:`ResultCache.commit` stores the fill only if the
    generations are STILL current — the no-stale-resurrect guarantee."""

    key: str
    scope: str | None
    model_gen: int
    scope_gen: int


class ResultCache:
    """LRU + TTL + generation-invalidated result cache (thread-safe).

    Invalidation is generation-based, not key-scan-based: bumping a
    scope's (or the model's) generation makes every entry recorded under
    the old generation unservable immediately, in O(1), without knowing
    which keys belong to the scope. Dead entries are reaped lazily on
    ``get`` and by LRU/byte eviction; a full flush drops them eagerly.
    """

    def __init__(self, config: CacheConfig, stats: CacheStats | None = None):
        self.config = config
        self.stats = stats or CacheStats()
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self._model_gen = 0
        # per-scope generation counters, themselves LRU-bounded so a
        # scope-scan cannot grow the map without limit (piolint PIO205
        # lints exactly this class of leak)
        self._scope_gens: "OrderedDict[str, int]" = OrderedDict()
        self._max_scopes = max(16, config.result_cache_entries * 4)

    # ------------------------------------------------------------- internals
    def _scope_gen(self, scope: str | None) -> int:
        """Current generation of ``scope`` (0 = never invalidated).
        Caller holds the lock."""
        if scope is None:
            return 0
        gen = self._scope_gens.get(scope)
        if gen is None:
            return 0
        self._scope_gens.move_to_end(scope)
        return gen

    def _drop(self, key: str, entry: _Entry) -> int:
        """Remove ``key``; returns the entry's bytes so the CALLER (who
        holds the lock) adjusts ``self._bytes`` under it."""
        del self._entries[key]
        return entry.nbytes

    def _sync_gauges(self) -> None:
        """Caller holds the lock."""
        self.stats.set_gauge("entries", len(self._entries))
        self.stats.set_gauge("bytes", self._bytes)

    # ------------------------------------------------------------ public API
    def get(self, key: str):
        """``(hit, value)``. A TTL-expired or generation-stale entry is
        reaped here and reported as a miss. The entry's invalidation
        scope was recorded at :meth:`reserve` time — the lookup validates
        against that, so no scope argument is needed (or consulted)."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                if entry.expires_at is not None and now >= entry.expires_at:
                    self._bytes -= self._drop(key, entry)
                    self.stats.incr("expirations")
                    entry = None
                elif (
                    entry.model_gen != self._model_gen
                    or entry.scope_gen != self._scope_gen(entry.scope)
                ):
                    self._bytes -= self._drop(key, entry)
                    entry = None
                else:
                    self._entries.move_to_end(key)
            self._sync_gauges()
        if entry is None:
            self.stats.incr("misses")
            return False, None
        self.stats.incr("hits")
        return True, entry.value

    def reserve(self, key: str, scope: str | None = None) -> FillToken:
        """Snapshot the generations a fill is being computed under."""
        with self._lock:
            return FillToken(key, scope, self._model_gen, self._scope_gen(scope))

    def commit(self, token: FillToken, value: Any) -> bool:
        """Store a computed fill — unless an invalidation won the race
        since :meth:`reserve`, in which case the fill is dropped (a stale
        result must never resurrect past its invalidation). Returns
        whether the value was stored."""
        cfg = self.config
        # the KEY (the canonical query body) and scope are retained too —
        # excluding them would let large distinct query bodies blow past
        # the byte budget while it reads near-zero
        nbytes = (
            _payload_nbytes(value)
            + len(token.key)
            + len(token.scope or "")
        )
        expires_at = (
            time.monotonic() + cfg.result_cache_ttl_s
            if cfg.result_cache_ttl_s > 0
            else None
        )
        with self._lock:
            if (
                token.model_gen != self._model_gen
                or token.scope_gen != self._scope_gen(token.scope)
            ):
                self.stats.incr("stale_drops")
                return False
            old = self._entries.pop(token.key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[token.key] = _Entry(
                value, expires_at, token.model_gen, token.scope,
                token.scope_gen, nbytes,
            )
            self._bytes += nbytes
            while len(self._entries) > cfg.result_cache_entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self.stats.incr("evictions_entries")
            if cfg.result_cache_max_bytes > 0:
                while self._bytes > cfg.result_cache_max_bytes and self._entries:
                    _, evicted = self._entries.popitem(last=False)
                    self._bytes -= evicted.nbytes
                    self.stats.incr("evictions_bytes")
            self._sync_gauges()
        self.stats.incr("stores")
        return True

    def invalidate_scope(self, scope: str) -> None:
        """Write hook: a new event about ``scope`` (user/entity) makes
        every entry computed for it stale NOW, not at TTL."""
        with self._lock:
            self._scope_gens[scope] = self._scope_gens.get(scope, 0) + 1
            self._scope_gens.move_to_end(scope)
            while len(self._scope_gens) > self._max_scopes:
                # evicting a scope counter forgets its bump history; any
                # surviving entries of that scope read gen 0 and would
                # resurrect, so reap them eagerly first
                evicted_scope, _ = self._scope_gens.popitem(last=False)
                for key in [
                    k
                    for k, e in self._entries.items()
                    if e.scope == evicted_scope
                ]:
                    self._bytes -= self._drop(key, self._entries[key])
            self._sync_gauges()
        self.stats.incr("invalidations_scope")

    def invalidate_all(self) -> None:
        """Full flush — reload to a new model generation, entering
        degraded mode, or an operator-requested clear."""
        with self._lock:
            self._model_gen += 1
            self._entries.clear()
            self._scope_gens.clear()
            self._bytes = 0
            self._sync_gauges()
        # NB: the ``modelGeneration`` gauge is owned by the QueryService
        # (its reload counter), not by this internal generation counter
        self.stats.incr("invalidations_full")

    @property
    def model_generation(self) -> int:
        with self._lock:
            return self._model_gen

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class _Flight:
    __slots__ = ("done", "value", "exc")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.value: Any = None
        self.exc: BaseException | None = None


class Singleflight:
    """Per-key in-flight computation dedup (the Go ``singleflight``
    idiom). ``do(key, fn)`` runs ``fn`` once per key at a time: the
    first caller (leader) computes; concurrent callers with the same key
    (followers) block and receive the leader's result — or its raised
    exception, re-raised in each follower. Leaders and followers are
    reported via the ``led`` flag so the caller can count coalesced
    work."""

    def __init__(self, stats: CacheStats | None = None):
        self.stats = stats or CacheStats()
        self._lock = threading.Lock()
        self._flights: dict[str, _Flight] = {}

    def do(self, key: str, fn: Callable[[], Any]) -> tuple[Any, bool]:
        """Returns ``(value, led)``; re-raises the leader's exception in
        every waiter."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
            else:
                leader = False
        if not leader:
            if not flight.done.wait(timeout=_FLIGHT_TIMEOUT_S):
                raise TimeoutError(
                    f"singleflight leader did not answer within "
                    f"{_FLIGHT_TIMEOUT_S:g}s"
                )
            self.stats.incr("coalesced")
            if flight.exc is not None:
                raise flight.exc
            return flight.value, False
        self.stats.incr("flights")
        try:
            flight.value = fn()
        except BaseException as e:
            flight.exc = e
            raise
        finally:
            # unpublish BEFORE fan-out: a request arriving after the
            # result is set starts a fresh flight (it may be observing
            # newer state) instead of reading a completed one
            with self._lock:
                self._flights.pop(key, None)
            flight.done.set()
        return flight.value, True

    def inflight(self) -> int:
        with self._lock:
            return len(self._flights)


def extract_scope(body: Any, scope_field: str | None) -> str | None:
    """The invalidation scope named by a query body (e.g. its ``user``
    field), or None when the body has no usable scope."""
    if scope_field is None or not isinstance(body, Mapping):
        return None
    value = body.get(scope_field)
    if isinstance(value, str):
        return value
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return str(value)
    return None


def affinity_key(body: Any, scope_field: str | None = "user") -> str | None:
    """Scope→replica routing key of a query body, for the fleet router's
    consistent hash (``predictionio_tpu.fleet``): the query's
    invalidation SCOPE when it names one — so all of a scope's queries
    (and therefore its cached results) land on one replica and the
    fleet's aggregate cache shards instead of duplicating — else the
    whole canonical body (repeat identical scope-less queries still
    stick), else None (route by load). Prefixes keep the two key spaces
    from colliding with each other."""
    scope = extract_scope(body, scope_field)
    if scope is not None:
        return f"s:{scope}"
    key = canonical_key(body)
    return f"q:{key}" if key is not None else None


def scopes_from_events(
    bodies: Iterable[Any], entity_types: tuple[str, ...] = ("user",)
) -> set[str]:
    """Entity ids named by event-server-shaped event bodies — the bridge
    an ingest pipeline uses to turn observed writes into per-scope
    invalidations (``QueryService.cache_note_write``)."""
    scopes: set[str] = set()
    for body in bodies:
        if not isinstance(body, Mapping):
            continue
        if body.get("entityType") in entity_types:
            eid = body.get("entityId")
            if isinstance(eid, str) and eid:
                scopes.add(eid)
    return scopes
