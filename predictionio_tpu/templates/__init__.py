"""Engine templates — runnable engines shipped with the framework.

Parity: the reference's engine-template family (Recommendation,
Classification, Similar-Product, E-Commerce, Text-Classification), which
live in separate repos upstream but ship as ``examples/`` copies
(SURVEY.md section 3.7). Here they are first-class packages so
``engine.json`` files can name them directly, e.g.
``"engineFactory": "predictionio_tpu.templates.recommendation:engine_factory"``.
"""
