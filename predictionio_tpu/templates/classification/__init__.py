"""Classification engine template (Naive Bayes / Logistic Regression).

Capability parity with the reference's scala-parallel-classification
template: read ``$set`` user-attribute events, train a classifier over
numeric attributes, answer attribute queries with a predicted label.
"""

from predictionio_tpu.templates.classification.engine import (
    Accuracy,
    ClassificationDataSource,
    DataSourceParams,
    LRAlgorithm,
    LRParams,
    NaiveBayesAlgorithm,
    NaiveBayesParams,
    engine_factory,
)

__all__ = [
    "Accuracy",
    "ClassificationDataSource",
    "DataSourceParams",
    "LRAlgorithm",
    "LRParams",
    "NaiveBayesAlgorithm",
    "NaiveBayesParams",
    "engine_factory",
]
