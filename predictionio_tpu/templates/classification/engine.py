"""Classification engine: $set attribute events -> NB / LR -> label queries.

Parity map (reference scala-parallel-classification template):

* ``DataSource.scala`` — reads each user entity's current properties via
  ``aggregateProperties`` (attributes + label) ->
  :class:`ClassificationDataSource` over
  ``PEventStore.aggregate_properties``.
* ``NaiveBayesAlgorithm.scala`` (MLlib NaiveBayes, ``lambda``) ->
  :class:`NaiveBayesAlgorithm` over
  :func:`predictionio_tpu.ops.classify.train_naive_bayes`.
* the LR variant of the template -> :class:`LRAlgorithm`.
* Query ``{"attr0": 2, "attr1": 0, "attr2": 0}`` ->
  ``{"label": "..."}``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from predictionio_tpu.controller import (
    AverageMetric,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    JaxAlgorithm,
    Params,
    SanityCheck,
    WorkflowContext,
)
from predictionio_tpu.data.aggregator import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.classify import (
    logreg_predict_proba,
    nb_predict_log_proba,
    train_logreg,
    train_naive_bayes,
)

__all__ = [
    "DataSourceParams",
    "TrainingData",
    "ClassificationDataSource",
    "NaiveBayesParams",
    "NaiveBayesAlgorithm",
    "LRParams",
    "LRAlgorithm",
    "PredictedResult",
    "Accuracy",
    "engine_factory",
]


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: str
    confidence: float | None = None

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {"label": self.label}
        if self.confidence is not None:
            out["confidence"] = self.confidence
        return out


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    entity_type: str = "user"
    attributes: tuple = ("attr0", "attr1", "attr2")
    label: str = "plan"
    eval_k: int = 3
    json_aliases = {"appName": "app_name", "entityType": "entity_type", "evalK": "eval_k"}


@dataclasses.dataclass
class TrainingData(SanityCheck):
    x: np.ndarray  # [N, F]
    y: np.ndarray  # [N] int
    label_index: BiMap
    attributes: tuple

    def sanity_check(self) -> None:
        if len(self.x) == 0:
            raise ValueError("No labeled entities found — check appName/attributes")
        if len(self.x) != len(self.y):
            raise ValueError("features/labels misaligned")


class ClassificationDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_rows(self, ctx: WorkflowContext) -> list[tuple[tuple, str]]:
        p = self.params
        props = PEventStore.aggregate_properties(
            app_name=p.app_name,
            entity_type=p.entity_type,
            required=list(p.attributes) + [p.label],
        )
        rows = []
        for _entity_id, pm in sorted(props.items()):
            feats = tuple(float(pm.get_as(a, float)) for a in p.attributes)
            rows.append((feats, str(pm[p.label])))
        return rows

    @staticmethod
    def _to_training_data(rows: Sequence[tuple[tuple, str]], attributes: tuple) -> TrainingData:
        label_index = BiMap.string_index(label for _, label in rows)
        x = np.asarray([f for f, _ in rows], dtype=np.float32).reshape(
            len(rows), len(attributes)
        )
        y = np.fromiter((label_index[l] for _, l in rows), np.int64, len(rows))
        return TrainingData(x, y, label_index, attributes)

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        return self._to_training_data(self._read_rows(ctx), self.params.attributes)

    def read_eval(self, ctx: WorkflowContext):
        rows = self._read_rows(ctx)
        k = max(2, self.params.eval_k)
        folds = []
        for fold in range(k):
            train = [r for i, r in enumerate(rows) if i % k != fold]
            held = [r for i, r in enumerate(rows) if i % k == fold]
            td = self._to_training_data(train, self.params.attributes)
            qa = [
                (dict(zip(self.params.attributes, feats)), label)
                for feats, label in held
            ]
            folds.append((td, {"fold": fold}, qa))
        return folds


class _ClassifierBase(JaxAlgorithm):
    """Shared predict plumbing: query dict -> feature vector -> label."""

    def _features(self, model, query: Mapping[str, Any]) -> np.ndarray:
        attrs = model["attributes"]
        missing = [a for a in attrs if a not in query]
        if missing:
            raise ValueError(f"Query is missing attribute(s) {missing}")
        return np.asarray([[float(query[a]) for a in attrs]], dtype=np.float32)


@dataclasses.dataclass(frozen=True)
class NaiveBayesParams(Params):
    lambda_: float = 1.0
    json_aliases = {"lambda": "lambda_"}


class NaiveBayesAlgorithm(_ClassifierBase):
    params_class = NaiveBayesParams

    def __init__(self, params: NaiveBayesParams):
        super().__init__(params)

    def train(self, ctx: WorkflowContext, pd: TrainingData):
        model = train_naive_bayes(
            pd.x, pd.y, num_classes=len(pd.label_index), smoothing=self.params.lambda_
        )
        return {
            "nb": model,
            "label_index": pd.label_index,
            "attributes": tuple(pd.attributes),
        }

    def predict(self, model, query: Mapping[str, Any]) -> PredictedResult:
        x = self._features(model, query)
        logp = np.asarray(nb_predict_log_proba(model["nb"], jnp.asarray(x)))[0]
        idx = int(np.argmax(logp))
        # normalized posterior as confidence
        p = np.exp(logp - logp.max())
        p /= p.sum()
        return PredictedResult(
            label=model["label_index"].inverse(idx), confidence=float(p[idx])
        )


@dataclasses.dataclass(frozen=True)
class LRParams(Params):
    iterations: int = 200
    step_size: float = 1.0
    reg: float = 1e-4
    json_aliases = {"stepSize": "step_size"}


class LRAlgorithm(_ClassifierBase):
    params_class = LRParams

    def __init__(self, params: LRParams):
        super().__init__(params)

    def train(self, ctx: WorkflowContext, pd: TrainingData):
        # standardize features for GD conditioning; bake the transform
        # into the model so serving applies it identically
        mean = pd.x.mean(axis=0)
        std = pd.x.std(axis=0)
        std[std == 0] = 1.0
        xs = (pd.x - mean) / std
        model = train_logreg(
            xs, pd.y, num_classes=len(pd.label_index),
            iterations=self.params.iterations, lr=self.params.step_size,
            reg=self.params.reg,
        )
        return {
            "lr": model,
            "mean": mean,
            "std": std,
            "label_index": pd.label_index,
            "attributes": tuple(pd.attributes),
        }

    def predict(self, model, query: Mapping[str, Any]) -> PredictedResult:
        x = (self._features(model, query) - model["mean"]) / model["std"]
        proba = np.asarray(logreg_predict_proba(model["lr"], jnp.asarray(x)))[0]
        idx = int(np.argmax(proba))
        return PredictedResult(
            label=model["label_index"].inverse(idx), confidence=float(proba[idx])
        )


class Accuracy(AverageMetric):
    """Fraction of correct labels (parity: the template's Accuracy metric)."""

    def calculate_unit(self, query, predicted: PredictedResult, actual: str) -> float:
        return 1.0 if predicted.label == str(actual) else 0.0


def engine_factory() -> Engine:
    return Engine(
        datasource_class=ClassificationDataSource,
        preparator_class=IdentityPreparator,
        algorithms_class_map={"naive": NaiveBayesAlgorithm, "lr": LRAlgorithm},
        serving_class=FirstServing,
    )
