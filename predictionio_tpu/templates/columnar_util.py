"""Shared vectorized event aggregation for template datasources.

The count/weight-style templates (similar-product, e-commerce) reduce a
(user, item) event stream to one value per pair. On the columnar bulk
scan that is a grouped reduction over code arrays — no per-event Python
(the same move that makes the recommendation template's read keep up
with the TPU at 10^7+ events).
"""

from __future__ import annotations

import numpy as np

from predictionio_tpu.data.columns import EventColumns

__all__ = ["aggregate_pairs", "event_name_mask", "densify_pairs"]


def event_name_mask(cols: EventColumns, name: str) -> np.ndarray:
    """Boolean row mask for one event name. Exact-match lookup — makes
    no assumption that a driver's event_vocab is sorted."""
    hits = np.flatnonzero(cols.event_vocab == name)
    if hits.size == 0:
        return np.zeros(len(cols), dtype=bool)
    return cols.event_code == hits[0]


def densify_pairs(
    cols: EventColumns,
    u_sel: np.ndarray,
    i_sel: np.ndarray,
    extra_items=(),
):
    """Compact aggregated pair codes to dense 0..n-1 index spaces.

    Returns ``(rows, cols_idx, user_vocab, item_vocab)`` where the vocab
    lists cover exactly the surviving ids — plus ``extra_items`` (e.g.
    $set-only catalog entries) appended to the item vocabulary so
    serving-time filters can address unobserved items. bincount keeps
    the compaction O(N), unlike a sort-based unique."""
    used_u = np.flatnonzero(np.bincount(u_sel, minlength=cols.entity_vocab.size))
    user_vocab = cols.entity_vocab[used_u].tolist()
    u_lut = np.zeros(cols.entity_vocab.size, np.int64)
    u_lut[used_u] = np.arange(used_u.size)
    used_i = np.flatnonzero(np.bincount(i_sel, minlength=cols.target_vocab.size))
    item_vocab = cols.target_vocab[used_i].tolist()
    present = set(item_vocab)
    item_vocab += [x for x in extra_items if x not in present]
    i_lut = np.zeros(cols.target_vocab.size, np.int64)
    i_lut[used_i] = np.arange(used_i.size)
    return u_lut[u_sel], i_lut[i_sel], user_vocab, item_vocab


def aggregate_pairs(
    cols: EventColumns, weights: np.ndarray | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Group events by (entity, target) pair and sum their weights.

    Returns ``(entity_code, target_code, totals)`` — one row per distinct
    pair, codes in the columns' own vocab spaces. Rows without a target
    are dropped. ``weights=None`` counts events (weight 1 each).
    """
    keep = cols.target_code >= 0
    if keep.all():
        u_code, i_code = cols.entity_code, cols.target_code
        w = weights
    else:
        u_code, i_code = cols.entity_code[keep], cols.target_code[keep]
        w = None if weights is None else weights[keep]
    span = int(cols.entity_vocab.size) * (int(cols.target_vocab.size) + 1)
    pair_dt = np.uint32 if span < 2**32 else np.int64
    pair = u_code.astype(pair_dt) * pair_dt(
        cols.target_vocab.size + 1
    ) + i_code.astype(pair_dt)
    order = np.argsort(pair)
    ps = pair[order]
    n = ps.size
    last = np.flatnonzero(np.r_[ps[1:] != ps[:-1], n > 0])
    first = np.r_[0, last[:-1] + 1] if n else last
    if weights is None:
        totals = (last - first + 1).astype(np.float32)
    else:
        csum = np.r_[0.0, np.cumsum(w[order], dtype=np.float64)]
        totals = (csum[last + 1] - csum[first]).astype(np.float32)
    sel = order[last]
    return u_code[sel], i_code[sel], totals
