"""E-Commerce Recommendation engine template.

Capability parity with the reference's scala-parallel-ecommercerecommendation
template: implicit ALS over view/buy events with serving-time business
rules — seen-item filtering via LEventStore, item availability from
``$set``/``$unset`` constraint entities, category/whiteList/blackList
filters, and popularity fallback for unknown users.
"""

from predictionio_tpu.templates.ecommerce.engine import (
    ECommAlgorithm,
    ECommAlgorithmParams,
    DataSourceParams,
    ECommerceDataSource,
    Query,
    engine_factory,
)

__all__ = [
    "ECommAlgorithm",
    "ECommAlgorithmParams",
    "DataSourceParams",
    "ECommerceDataSource",
    "Query",
    "engine_factory",
]
