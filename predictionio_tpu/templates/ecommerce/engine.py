"""E-Commerce engine: view/buy events -> implicit ALS + business rules.

Parity map (reference scala-parallel-ecommercerecommendation template):

* ``DataSource.scala`` — ``view``/``buy`` events + ``$set`` item entities
  (``categories``) -> :class:`ECommerceDataSource`.
* ``ECommAlgorithm.scala`` — MLlib implicit ALS; at serving time it
  excludes items the user has already seen/bought (looked up through
  ``LEventStore`` per query — the low-latency local read path,
  SURVEY.md section 8.3), drops unavailable items (the
  ``constraint_unavailableItems`` ``$set`` entity), applies
  category/whiteList/blackList filters, and falls back to popularity
  ranking for unknown users -> :class:`ECommAlgorithm`.
* Query ``{"user": "u1", "num": 4, "categories"?, "whiteList"?,
  "blackList"?}`` -> ``{"itemScores": [...]}``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    JaxAlgorithm,
    Params,
    SanityCheck,
    WorkflowContext,
)
from predictionio_tpu.data.aggregator import BiMap
from predictionio_tpu.data.store import LEventStore, PEventStore
from predictionio_tpu.ops.als import ALSConfig, train_als
from predictionio_tpu.templates.results import ItemScore, PredictedResult

__all__ = [
    "Query",
    "DataSourceParams",
    "TrainingData",
    "ECommerceDataSource",
    "ECommAlgorithmParams",
    "ECommModel",
    "ECommAlgorithm",
    "engine_factory",
]


@dataclasses.dataclass(frozen=True)
class Query:
    user: str = ""
    num: int = 4
    categories: tuple | None = None
    white_list: tuple | None = None
    black_list: tuple | None = None
    json_aliases = {"whiteList": "white_list", "blackList": "black_list"}


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    view_event: str = "view"
    buy_event: str = "buy"
    item_entity_type: str = "item"
    json_aliases = {
        "appName": "app_name",
        "viewEvent": "view_event",
        "buyEvent": "buy_event",
    }


@dataclasses.dataclass
class TrainingData(SanityCheck):
    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray  # weighted counts (buys weigh more than views)
    user_index: BiMap
    item_index: BiMap
    categories: dict  # item id -> tuple of categories
    popularity: np.ndarray  # [I] view+buy counts

    def sanity_check(self) -> None:
        if self.rows.size == 0:
            raise ValueError("No view/buy events found — check appName")


class ECommerceDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_categories(self) -> dict[str, tuple]:
        categories: dict[str, tuple] = {}
        for item_id, pm in PEventStore.aggregate_properties(
            app_name=self.params.app_name,
            entity_type=self.params.item_entity_type,
        ).items():
            categories[item_id] = tuple(
                str(c) for c in pm.opt("categories", list, [])
            )
        return categories

    def _read_training_columnar(self, ctx: WorkflowContext) -> TrainingData:
        """Vectorized single-host read: columnar bulk scan + grouped
        weighted sums (a buy is a much stronger signal than a view) —
        no per-event Python at 10^7+ events."""
        from predictionio_tpu.templates.columnar_util import (
            aggregate_pairs,
            densify_pairs,
            event_name_mask,
        )

        p = self.params
        cols_batch = PEventStore.find_columns(
            app_name=p.app_name, event_names=[p.view_event, p.buy_event]
        )
        weights = np.ones(len(cols_batch), np.float32)
        weights[event_name_mask(cols_batch, p.buy_event)] = 5.0
        u_sel, i_sel, vals = aggregate_pairs(cols_batch, weights)
        categories = self._read_categories()
        rows, cols_idx, user_vocab, item_vocab = densify_pairs(
            cols_batch, u_sel, i_sel, extra_items=categories
        )
        item_index = BiMap.string_index(item_vocab)
        popularity = np.zeros(len(item_index), dtype=np.float32)
        np.add.at(popularity, cols_idx, vals)
        return TrainingData(
            rows,
            cols_idx,
            vals,
            BiMap.string_index(user_vocab),
            item_index,
            categories,
            popularity,
        )

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        p = self.params
        if ctx.num_hosts == 1:
            return self._read_training_columnar(ctx)
        counts: dict[tuple[str, str], float] = {}
        for e in PEventStore.find(
            app_name=p.app_name,
            event_names=[p.view_event, p.buy_event],
            shard_index=ctx.host_index,
            num_shards=ctx.num_hosts,
        ):
            if e.target_entity_id is None:
                continue
            # a buy is a much stronger signal than a view
            weight = 5.0 if e.event == p.buy_event else 1.0
            key = (e.entity_id, e.target_entity_id)
            counts[key] = counts.get(key, 0.0) + weight
        categories = self._read_categories()
        # cross-host coherence (round-1 advisor high finding): merge
        # per-host weighted counts, build identical global BiMaps, and
        # sum popularity across hosts
        import operator

        from predictionio_tpu.parallel.exchange import global_sum_array, global_vocab, merge_keyed

        counts = merge_keyed(counts, combine=operator.add)
        user_index = BiMap.string_index(global_vocab(u for u, _ in counts))
        item_index = BiMap.string_index(
            global_vocab(list(i for _, i in counts) + list(categories))
        )
        n = len(counts)
        rows = np.fromiter((user_index[u] for u, _ in counts), np.int64, n)
        cols = np.fromiter((item_index[i] for _, i in counts), np.int64, n)
        vals = np.fromiter(counts.values(), np.float32, n)
        popularity = np.zeros(len(item_index), dtype=np.float32)
        np.add.at(popularity, cols, vals)
        popularity = global_sum_array(popularity)
        return TrainingData(
            rows, cols, vals, user_index, item_index, categories, popularity
        )


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    app_name: str = ""  # for serving-time LEventStore lookups
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = 3
    #: exclude items of these recent user events at serving time
    unseen_only: bool = True
    seen_events: tuple = ("view", "buy")
    json_aliases = {
        "appName": "app_name",
        "numIterations": "num_iterations",
        "lambda": "lambda_",
        "unseenOnly": "unseen_only",
        "seenEvents": "seen_events",
    }


@dataclasses.dataclass
class ECommModel:
    user_factors: Any
    item_factors: Any
    user_index: BiMap
    item_index: BiMap
    categories: dict
    popularity: Any  # [I]


class ECommAlgorithm(JaxAlgorithm):
    params_class = ECommAlgorithmParams
    query_class = Query

    def __init__(self, params: ECommAlgorithmParams):
        super().__init__(params)

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> ECommModel:
        p = self.params
        factors = train_als(
            pd.rows, pd.cols, pd.vals,
            num_users=len(pd.user_index), num_items=len(pd.item_index),
            config=ALSConfig(
                rank=p.rank, iterations=p.num_iterations, reg=p.lambda_,
                implicit=True, alpha=p.alpha, seed=0 if p.seed is None else p.seed,
            ),
            mesh=ctx.mesh,
        )
        return ECommModel(
            user_factors=np.asarray(factors.user),
            item_factors=np.asarray(factors.item),
            user_index=pd.user_index,
            item_index=pd.item_index,
            categories=pd.categories,
            popularity=pd.popularity,
        )

    # ------------------------------------------------------------- serving
    def _seen_items(self, user: str) -> set:
        """Items of the user's recent view/buy events, via the serving-time
        LEventStore path (parity: ECommAlgorithm's seen-events lookup)."""
        if not self.params.unseen_only or not self.params.app_name:
            return set()
        try:
            events = LEventStore.find_by_entity(
                app_name=self.params.app_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.seen_events),
                limit=None,
            )
        except Exception:
            return set()
        return {e.target_entity_id for e in events if e.target_entity_id}

    def _unavailable_items(self) -> set:
        """Current ``$set`` properties of the ``constraint_unavailableItems``
        entity (parity: the template's availability constraint)."""
        if not self.params.app_name:
            return set()
        try:
            pm = LEventStore.aggregate_properties_of_entity(
                app_name=self.params.app_name,
                entity_type="constraint",
                entity_id="unavailableItems",
            )
        except Exception:
            return set()
        if pm is None:
            return set()
        return set(pm.opt("items", list, []))

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        n = model.item_factors.shape[0]
        uidx = model.user_index.get(query.user)
        if uidx is not None:
            scores = model.item_factors @ np.asarray(model.user_factors[uidx])
        else:
            # cold start: popularity ranking (parity: the template's
            # fallback to recent/popular items)
            scores = np.asarray(model.popularity, dtype=np.float64).copy()
        allowed = np.ones(n, dtype=bool)
        for item in self._seen_items(query.user) | self._unavailable_items():
            idx = model.item_index.get(item)
            if idx is not None:
                allowed[idx] = False
        if query.white_list:
            allowed &= np.isin(
                np.arange(n), [model.item_index.get(i, -1) for i in query.white_list]
            )
        if query.black_list:
            for item in query.black_list:
                idx = model.item_index.get(item)
                if idx is not None:
                    allowed[idx] = False
        if query.categories:
            wanted = set(query.categories)
            for idx in np.nonzero(allowed)[0]:
                cats = model.categories.get(model.item_index.inverse(int(idx)), ())
                if not wanted.intersection(cats):
                    allowed[idx] = False
        scores = np.where(allowed, scores, -np.inf)
        k = min(int(query.num), int(allowed.sum()))
        if k <= 0:
            return PredictedResult(())
        from predictionio_tpu.ops.topk import top_k_host

        top, _ = top_k_host(scores, k)  # shared tie rule (ops/topk.py)
        return PredictedResult(
            tuple(
                ItemScore(item=model.item_index.inverse(int(i)), score=float(scores[i]))
                for i in top
                if np.isfinite(scores[i])
            )
        )


def engine_factory() -> Engine:
    return Engine(
        datasource_class=ECommerceDataSource,
        preparator_class=IdentityPreparator,
        algorithms_class_map={"ecomm": ECommAlgorithm},
        serving_class=FirstServing,
    )
