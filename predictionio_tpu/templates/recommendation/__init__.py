"""Recommendation engine template (TPU ALS).

Capability parity with the reference's scala-parallel-recommendation
template (``examples/scala-parallel-recommendation/``; MLlib explicit-ALS
based): read ``rate``/``buy`` events, train matrix factors, answer
``{"user": "1", "num": 4}`` queries with
``{"itemScores": [{"item": "...", "score": ...}, ...]}``.
"""

from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    ALSModel,
    DataSourceParams,
    PredictedResult,
    Query,
    RecommendationDataSource,
    TrainingData,
    engine_factory,
)

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "ALSModel",
    "DataSourceParams",
    "PredictedResult",
    "Query",
    "RecommendationDataSource",
    "TrainingData",
    "engine_factory",
]
