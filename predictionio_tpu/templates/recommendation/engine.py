"""Recommendation engine: event store -> TPU ALS -> top-N item queries.

Parity map (reference scala-parallel-recommendation template):

* ``DataSource.scala`` -> :class:`RecommendationDataSource` — reads
  ``rate`` events (explicit rating property) and ``buy`` events (implicit
  rating 4.0), latest event per (user, item) wins.
* ``ALSAlgorithm.scala`` (MLlib ``ALS.train``) ->
  :class:`ALSAlgorithm` over :func:`predictionio_tpu.ops.als.train_als`.
* ``Serving.scala`` -> framework :class:`FirstServing`.
* engine.json params are byte-compatible: ``rank``, ``numIterations``,
  ``lambda``, ``seed`` (+ ``implicitPrefs``/``alpha`` extensions).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    JaxAlgorithm,
    OptionAverageMetric,
    Params,
    SanityCheck,
    WorkflowContext,
)
from predictionio_tpu.data.aggregator import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.als import ALSConfig, top_k_items, train_als

__all__ = [
    "Query",
    "ItemScore",
    "Actual",
    "PredictedResult",
    "DataSourceParams",
    "TrainingData",
    "RecommendationDataSource",
    "ALSAlgorithmParams",
    "ALSModel",
    "ALSAlgorithm",
    "PrecisionAtK",
    "engine_factory",
]


# --------------------------------------------------------------------- query
@dataclasses.dataclass(frozen=True)
class Query:
    """``{"user": "1", "num": 4}`` (wire-compatible with the reference)."""

    user: str
    num: int = 4


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class Actual:
    """Ground truth for one eval query: held-out positive items plus the
    items the user already rated in the training split (skipped — not
    penalized — by :class:`PrecisionAtK`)."""

    items: tuple = ()
    seen: tuple = ()


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple = ()

    def to_json(self) -> dict[str, Any]:
        return {
            "itemScores": [{"item": s.item, "score": s.score} for s in self.item_scores]
        }


# ---------------------------------------------------------------- datasource
@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    #: events read as explicit ratings (property ``rating``)
    rate_event: str = "rate"
    #: events read as implicit positive signal with this rating value
    buy_event: str = "buy"
    buy_rating: float = 4.0
    #: eval folds for read_eval
    eval_k: int = 3
    json_aliases = {"appName": "app_name", "evalK": "eval_k"}


@dataclasses.dataclass
class TrainingData(SanityCheck):
    """COO ratings + the entity-id <-> dense-index BiMaps."""

    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray
    user_index: BiMap
    item_index: BiMap

    def sanity_check(self) -> None:
        if self.rows.size == 0:
            raise ValueError(
                "TrainingData is empty — no rate/buy events found; "
                "check appName and imported events"
            )
        if not (self.rows.size == self.cols.size == self.vals.size):
            raise ValueError("TrainingData arrays are misaligned")


class RecommendationDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_ratings(self, ctx: WorkflowContext) -> list[tuple[str, str, float]]:
        p = self.params
        ratings: dict[tuple[str, str], tuple[Any, float]] = {}
        events = PEventStore.find(
            app_name=p.app_name,
            entity_type="user",
            event_names=[p.rate_event, p.buy_event],
            shard_index=ctx.host_index,
            num_shards=ctx.num_hosts,
        )
        for e in events:
            if e.target_entity_id is None:
                continue
            if e.event == p.buy_event:
                rating = p.buy_rating
            else:
                rating = float(e.properties.get_as("rating", float))
            key = (e.entity_id, e.target_entity_id)
            prev = ratings.get(key)
            # latest event per (user, item) wins; equal timestamps break
            # toward the higher rating — an order-independent rule, so
            # single-host and multi-host reads agree (the multi-host merge
            # below folds the same (event_time, rating) max)
            if prev is None or (e.event_time, rating) >= prev:
                ratings[key] = (e.event_time, rating)
        if ctx.num_hosts > 1:
            # cross-host coherence (round-1 advisor high finding): events of
            # one (user, item) pair may land in different hosts' shards; the
            # bounded exchange re-partitions by user and applies the SAME
            # latest-wins rule globally. The COO stays host-local.
            from predictionio_tpu.parallel.exchange import merge_keyed

            ratings = merge_keyed(ratings, combine=max)
        return [(u, i, r) for (u, i), (_, r) in ratings.items()]

    @staticmethod
    def _to_training_data(
        triples: Sequence[tuple[str, str, float]],
        ctx: WorkflowContext | None = None,
    ) -> TrainingData:
        if ctx is not None and ctx.num_hosts > 1:
            # every host must build IDENTICAL global BiMaps (the advisor's
            # round-1 high finding: per-host index spaces break the sharded
            # device_put); only the sorted vocabularies are all-gathered
            from predictionio_tpu.parallel.exchange import global_vocab

            user_index = BiMap.string_index(global_vocab(u for u, _, _ in triples))
            item_index = BiMap.string_index(global_vocab(i for _, i, _ in triples))
        else:
            user_index = BiMap.string_index(u for u, _, _ in triples)
            item_index = BiMap.string_index(i for _, i, _ in triples)
        rows = np.fromiter((user_index[u] for u, _, _ in triples), np.int64, len(triples))
        cols = np.fromiter((item_index[i] for _, i, _ in triples), np.int64, len(triples))
        vals = np.fromiter((r for _, _, r in triples), np.float32, len(triples))
        return TrainingData(rows, cols, vals, user_index, item_index)

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        return self._to_training_data(self._read_ratings(ctx), ctx)

    def read_eval(self, ctx: WorkflowContext):
        """K-fold split by stable hash of (user, item): train on k-1 folds,
        query each held-out user for top-N, actual = held-out items
        (parity: the template's ``readEval`` + e2 ``splitData``)."""
        triples = self._read_ratings(ctx)
        k = max(2, self.params.eval_k)
        folds = []
        import zlib

        def fold_of(u: str, i: str) -> int:
            return zlib.crc32(f"{u}\x00{i}".encode()) % k

        num_items = len({i for _, i, _ in triples})
        for fold in range(k):
            train = [t for t in triples if fold_of(t[0], t[1]) != fold]
            held = [t for t in triples if fold_of(t[0], t[1]) == fold]
            td = self._to_training_data(train, ctx)
            seen_by_user: dict[str, set] = {}
            for u, i, _ in train:
                seen_by_user.setdefault(u, set()).add(i)
            by_user: dict[str, list[str]] = {}
            for u, i, r in held:
                if r >= 3.5:  # positively-rated held-out items
                    by_user.setdefault(u, []).append(i)
            # Query the full ranking; the metric scores precision among
            # UNSEEN items (Actual carries the user's training items so
            # already-rated recommendations are skipped, not penalized).
            qa = [
                (
                    Query(user=u, num=num_items),
                    Actual(items=tuple(items), seen=tuple(seen_by_user.get(u, ()))),
                )
                for u, items in by_user.items()
                if items
            ]
            folds.append((td, {"fold": fold}, qa))
        return folds


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: int | None = 3
    implicit_prefs: bool = False
    alpha: float = 1.0
    #: serve top-N from the accelerator instead of host numpy. Host serving
    #: wins below ~10^6 items (one small GEMV); device serving wins for
    #: huge catalogs or when queries are batched — and avoids it when the
    #: TPU sits behind a network tunnel where each dispatch pays an RTT.
    serve_on_device: bool = False
    #: guardrail for serve_on_device: a deploy-time probe measures real
    #: per-query device latency and falls back to host serving (with a
    #: warning) when the median exceeds this budget — a remote/tunneled
    #: accelerator pays an RTT per dispatch that silently blows the
    #: reference's <10 ms serving target otherwise. <= 0 disables the
    #: probe (always trust serve_on_device).
    device_latency_budget_ms: float = 10.0
    json_aliases = {
        "numIterations": "num_iterations",
        "lambda": "lambda_",
        "implicitPrefs": "implicit_prefs",
        "serveOnDevice": "serve_on_device",
        "deviceLatencyBudgetMs": "device_latency_budget_ms",
    }


@dataclasses.dataclass
class ALSModel:
    """Factor matrices + id maps; arrays live on host in blobs and on
    device while serving."""

    user_factors: Any  # [U, K]
    item_factors: Any  # [I, K]
    user_index: BiMap
    item_index: BiMap


class ALSAlgorithm(JaxAlgorithm):
    params_class = ALSAlgorithmParams
    query_class = Query

    def __init__(self, params: ALSAlgorithmParams):
        super().__init__(params)

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> ALSModel:
        p = self.params
        factors = train_als(
            pd.rows,
            pd.cols,
            pd.vals,
            num_users=len(pd.user_index),
            num_items=len(pd.item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                implicit=p.implicit_prefs,
                alpha=p.alpha,
                seed=0 if p.seed is None else p.seed,
            ),
            mesh=ctx.mesh,
        )
        return ALSModel(
            user_factors=np.asarray(factors.user),
            item_factors=np.asarray(factors.item),
            user_index=pd.user_index,
            item_index=pd.item_index,
        )

    def prepare_model_for_serving(self, model: ALSModel) -> ALSModel:
        if self.params.serve_on_device:
            import jax

            model.user_factors = jax.device_put(np.asarray(model.user_factors))
            model.item_factors = jax.device_put(np.asarray(model.item_factors))
            if len(model.user_index):
                probe = Query(user=model.user_index.keys()[0], num=4)
                self.predict(model, probe)  # compile warm-up
                budget = self.params.device_latency_budget_ms
                if budget > 0:
                    import time

                    lat = []
                    for _ in range(5):
                        t0 = time.perf_counter()
                        self.predict(model, probe)
                        lat.append((time.perf_counter() - t0) * 1e3)
                    p50 = sorted(lat)[len(lat) // 2]
                    if p50 > budget:
                        logging.getLogger(__name__).warning(
                            "serveOnDevice probe: median device query "
                            "latency %.1f ms exceeds the %.1f ms budget "
                            "(remote/tunneled accelerator?) — falling "
                            "back to host serving. Set "
                            "deviceLatencyBudgetMs <= 0 to force device.",
                            p50, budget,
                        )
                        model.user_factors = np.asarray(model.user_factors)
                        model.item_factors = np.asarray(model.item_factors)
            return model
        model.user_factors = np.ascontiguousarray(model.user_factors)
        model.item_factors = np.ascontiguousarray(model.item_factors)
        # warm-up so the first real query pays no compile / cache fill
        # (parity: CreateServer's deploy-time warm-up)
        if len(model.user_index):
            self.predict(model, Query(user=model.user_index.keys()[0], num=4))
        return model

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        uidx = model.user_index.get(query.user)
        if uidx is None:
            return PredictedResult(())
        k = min(int(query.num), len(model.item_index))
        if k <= 0:
            return PredictedResult(())
        if isinstance(model.item_factors, np.ndarray):
            # host path: one GEMV + argpartition, microseconds at catalog
            # sizes below ~10^6 items
            scores = model.item_factors @ np.asarray(model.user_factors[uidx])
            part = np.argpartition(scores, -k)[-k:]
            top = part[np.argsort(scores[part])[::-1]]
            pairs = [(int(i), float(scores[i])) for i in top]
        else:
            idx, scores = top_k_items(model.user_factors[uidx], model.item_factors, k)
            pairs = [(int(i), float(s)) for i, s in zip(np.asarray(idx), np.asarray(scores))]
        return PredictedResult(
            tuple(
                ItemScore(item=model.item_index.inverse(i), score=s) for i, s in pairs
            )
        )


class PrecisionAtK(OptionAverageMetric):
    """Fraction of recommended items that are in the held-out positives
    (parity: the eval metric of the reference recommendation template)."""

    def __init__(self, k: int = 10):
        self.k = k

    def header(self) -> str:
        return f"Precision@{self.k}"

    def calculate_unit(self, query, predicted: PredictedResult, actual) -> float | None:
        if not predicted.item_scores:
            return None
        if isinstance(actual, Actual):
            positives, seen = set(actual.items), set(actual.seen)
        else:  # plain iterable of positive items
            positives, seen = set(actual), set()
        top = [s.item for s in predicted.item_scores if s.item not in seen][: self.k]
        if not top:
            return None
        hits = sum(1 for i in top if i in positives)
        return hits / len(top)


def engine_factory() -> Engine:
    return Engine(
        datasource_class=RecommendationDataSource,
        preparator_class=IdentityPreparator,
        algorithms_class_map={"als": ALSAlgorithm},
        serving_class=FirstServing,
    )
