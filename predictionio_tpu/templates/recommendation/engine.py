"""Recommendation engine: event store -> TPU ALS -> top-N item queries.

Parity map (reference scala-parallel-recommendation template):

* ``DataSource.scala`` -> :class:`RecommendationDataSource` — reads
  ``rate`` events (explicit rating property) and ``buy`` events (implicit
  rating 4.0), latest event per (user, item) wins.
* ``ALSAlgorithm.scala`` (MLlib ``ALS.train``) ->
  :class:`ALSAlgorithm` over :func:`predictionio_tpu.ops.als.train_als`.
* ``Serving.scala`` -> framework :class:`FirstServing`.
* engine.json params are byte-compatible: ``rank``, ``numIterations``,
  ``lambda``, ``seed`` (+ ``implicitPrefs``/``alpha`` extensions).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Mapping, Sequence

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    JaxAlgorithm,
    OptionAverageMetric,
    Params,
    SanityCheck,
    WorkflowContext,
)
from predictionio_tpu.data.aggregator import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.templates.serving_util import TOPK_CHUNK
# re-exported (see __all__): the ranked-result wire types are shared by
# the similarproduct and ecommerce templates via templates/results.py
from predictionio_tpu.templates.results import ItemScore, PredictedResult
from predictionio_tpu.ops.als import ALSConfig, train_als

__all__ = [
    "Query",
    "ItemScore",
    "Actual",
    "PredictedResult",
    "DataSourceParams",
    "TrainingData",
    "RecommendationDataSource",
    "ALSAlgorithmParams",
    "ALSModel",
    "ALSAlgorithm",
    "PrecisionAtK",
    "engine_factory",
]


# --------------------------------------------------------------------- query
@dataclasses.dataclass(frozen=True)
class Query:
    """``{"user": "1", "num": 4}`` (wire-compatible with the reference)."""

    user: str
    num: int = 4


@dataclasses.dataclass(frozen=True)
class Actual:
    """Ground truth for one eval query: held-out positive items plus the
    items the user already rated in the training split (skipped — not
    penalized — by :class:`PrecisionAtK`)."""

    items: tuple = ()
    seen: tuple = ()


# ---------------------------------------------------------------- datasource
@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    #: events read as explicit ratings (property ``rating``)
    rate_event: str = "rate"
    #: events read as implicit positive signal with this rating value
    buy_event: str = "buy"
    buy_rating: float = 4.0
    #: eval folds for read_eval
    eval_k: int = 3
    #: on an append-only columnar event store, repeat trains read only
    #: the segments/tail added since the cached previous read (the
    #: incremental re-index of SURVEY §8.3); safe fallback to a full
    #: read whenever the cache is stale or the store is not columnar
    incremental: bool = True
    json_aliases = {"appName": "app_name", "evalK": "eval_k"}


@dataclasses.dataclass
class TrainingData(SanityCheck):
    """COO ratings + the entity-id <-> dense-index BiMaps."""

    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray
    user_index: BiMap
    item_index: BiMap

    def sanity_check(self) -> None:
        if self.rows.size == 0:
            raise ValueError(
                "TrainingData is empty — no rate/buy events found; "
                "check appName and imported events"
            )
        if not (self.rows.size == self.cols.size == self.vals.size):
            raise ValueError("TrainingData arrays are misaligned")


class RecommendationDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_ratings(self, ctx: WorkflowContext) -> list[tuple[str, str, float]]:
        if ctx.num_hosts == 1:
            # columnar fast path (read_eval's input): the vectorized read
            # dedups over code arrays, so the remaining Python is
            # O(distinct pairs), not O(events)
            td = self._read_training_columnar(ctx)
            users = td.user_index.keys()
            items = td.item_index.keys()
            return [
                (users[r], items[c], float(v))
                for r, c, v in zip(
                    td.rows.tolist(), td.cols.tolist(), td.vals.tolist()
                )
            ]
        return self._read_ratings_stream(ctx)

    def _read_ratings_stream(
        self, ctx: WorkflowContext
    ) -> list[tuple[str, str, float]]:
        """The per-event reference path (multi-host coherence, and the
        behavioral oracle the columnar path is tested against)."""
        p = self.params
        ratings: dict[tuple[str, str], tuple[Any, float]] = {}
        events = PEventStore.find(
            app_name=p.app_name,
            entity_type="user",
            event_names=[p.rate_event, p.buy_event],
            shard_index=ctx.host_index,
            num_shards=ctx.num_hosts,
        )
        for e in events:
            if e.target_entity_id is None:
                continue
            if e.event == p.buy_event:
                rating = p.buy_rating
            else:
                rating = float(e.properties.get_as("rating", float))
            key = (e.entity_id, e.target_entity_id)
            prev = ratings.get(key)
            # latest event per (user, item) wins; equal timestamps break
            # toward the higher rating — an order-independent rule, so
            # single-host and multi-host reads agree (the multi-host merge
            # below folds the same (event_time, rating) max)
            if prev is None or (e.event_time, rating) >= prev:
                ratings[key] = (e.event_time, rating)
        if ctx.num_hosts > 1:
            # cross-host coherence (round-1 advisor high finding): events of
            # one (user, item) pair may land in different hosts' shards; the
            # bounded exchange re-partitions by user and applies the SAME
            # latest-wins rule globally. The COO stays host-local.
            from predictionio_tpu.parallel.exchange import merge_keyed

            ratings = merge_keyed(ratings, combine=max)
        # float32, matching training precision AND the columnar fast path
        # (a float64 here could land on the other side of read_eval's 3.5
        # positives cutoff than the same rating read columnar)
        return [
            (u, i, float(np.float32(r))) for (u, i), (_, r) in ratings.items()
        ]

    @staticmethod
    def _to_training_data(
        triples: Sequence[tuple[str, str, float]],
        ctx: WorkflowContext | None = None,
    ) -> TrainingData:
        if ctx is not None and ctx.num_hosts > 1:
            # every host must build IDENTICAL global BiMaps (the advisor's
            # round-1 high finding: per-host index spaces break the sharded
            # device_put); only the sorted vocabularies are all-gathered
            from predictionio_tpu.parallel.exchange import global_vocab

            user_index = BiMap.string_index(global_vocab(u for u, _, _ in triples))
            item_index = BiMap.string_index(global_vocab(i for _, i, _ in triples))
        else:
            user_index = BiMap.string_index(u for u, _, _ in triples)
            item_index = BiMap.string_index(i for _, i, _ in triples)
        rows = np.fromiter((user_index[u] for u, _, _ in triples), np.int64, len(triples))
        cols = np.fromiter((item_index[i] for _, i, _ in triples), np.int64, len(triples))
        vals = np.fromiter((r for _, _, r in triples), np.float32, len(triples))
        return TrainingData(rows, cols, vals, user_index, item_index)

    def _extract_ratings_arrays(self, cols):
        """EventColumns -> (u_code, i_code, event_time_us, rating) in the
        columns' own vocab space; validates that rate events carry a
        numeric rating (same error semantics as the event-stream path)."""
        from predictionio_tpu.data.event import EventValidationError

        from predictionio_tpu.templates.columnar_util import event_name_mask

        p = self.params
        # exact-match lookup: a third-party driver's event_vocab need not
        # be sorted (the EventColumns contract doesn't promise it)
        is_buy = event_name_mask(cols, p.buy_event)
        if is_buy.any():
            vals = np.where(is_buy, np.float32(p.buy_rating), cols.prop)
        else:
            vals = cols.prop
        keep = cols.target_code >= 0  # events without a target are skipped
        bad = keep & ~is_buy & np.isnan(vals)
        if bad.any():
            n_bad = int(bad.sum())
            u = cols.entity_vocab[cols.entity_code[np.argmax(bad)]]
            raise EventValidationError(
                f"{n_bad} '{p.rate_event}' event(s) lack a numeric 'rating' "
                f"property (first offender: entity {u!r})"
            )
        if keep.all():
            return (
                cols.entity_code,
                cols.target_code,
                cols.event_time_us,
                vals.astype(np.float32, copy=False),
            )
        return (
            cols.entity_code[keep],
            cols.target_code[keep],
            cols.event_time_us[keep],
            vals[keep].astype(np.float32, copy=False),
        )

    @staticmethod
    def _assemble_training_data(
        u_code, i_code, t_arr, v, user_vocab, item_vocab
    ):
        """Dedup (latest wins, ties -> higher rating) + vocabulary
        compaction; returns (TrainingData, cache_payload). One argsort
        groups the pairs; only rows inside duplicate groups (usually a
        tiny fraction) pay the 3-key lexsort. The pair key uses the
        narrowest dtype that fits — halves the sort's memory traffic on
        the (single-core) host for typical catalogs."""
        span = int(user_vocab.size) * (int(item_vocab.size) + 1)
        pair_dt = np.uint32 if span < 2**32 else np.int64
        pair = u_code.astype(pair_dt) * pair_dt(
            item_vocab.size + 1
        ) + i_code.astype(pair_dt)
        # stability is irrelevant: duplicate groups are re-ranked below by
        # (time, rating), so the faster introsort wins over kind="stable"
        order = np.argsort(pair)
        ps = pair[order]
        n = ps.size
        last = np.flatnonzero(np.r_[ps[1:] != ps[:-1], n > 0])
        first = np.r_[0, last[:-1] + 1] if n else last
        sizes = last - first + 1
        sel = order[last]
        dup_groups = np.flatnonzero(sizes > 1)
        if dup_groups.size:
            rows_d = order[np.repeat(sizes > 1, sizes)]
            dsizes = sizes[dup_groups]
            group_of = np.repeat(np.arange(dup_groups.size), dsizes)
            o2 = np.lexsort((v[rows_d], t_arr[rows_d], group_of))
            sel[dup_groups] = rows_d[o2[np.cumsum(dsizes) - 1]]
        u_sel = u_code[sel]
        i_sel = i_code[sel]
        v_sel = v[sel]
        t_sel = t_arr[sel]
        # compact the vocabularies to ids that survived (bincount is O(N),
        # unlike a sort-based unique)
        u_hist = np.bincount(u_sel, minlength=user_vocab.size)
        i_hist = np.bincount(i_sel, minlength=item_vocab.size)
        used_u = np.flatnonzero(u_hist)
        used_i = np.flatnonzero(i_hist)
        u_lut = np.zeros(user_vocab.size, np.int64)
        u_lut[used_u] = np.arange(used_u.size)
        i_lut = np.zeros(item_vocab.size, np.int64)
        i_lut[used_i] = np.arange(used_i.size)
        rows = u_lut[u_sel]
        cols_idx = i_lut[i_sel]
        uv_arr = user_vocab[used_u]
        iv_arr = item_vocab[used_i]
        user_list = uv_arr.tolist()
        item_list = iv_arr.tolist()
        td = TrainingData(
            rows=rows,
            cols=cols_idx,
            vals=v_sel,
            user_index=BiMap.string_index(user_list),
            item_index=BiMap.string_index(item_list),
        )
        cache_payload = {
            "u_code": rows.astype(np.int32),
            "i_code": cols_idx.astype(np.int32),
            "t_us": t_sel.astype(np.int64),
            "vals": v_sel,
            "user_vocab": uv_arr,
            "item_vocab": iv_arr,
        }
        return td, cache_payload

    # ---------------------------------------------------- incremental cache
    def _cache_paths(self) -> tuple[str, str]:
        import re
        import zlib

        from predictionio_tpu.data.storage import Storage

        # the readable prefix is sanitized; the crc suffix keeps distinct
        # app names (e.g. "my/app" vs "my_app") from sharing a cache file
        name = self.params.app_name
        safe = re.sub(r"[^A-Za-z0-9_-]", "_", name)
        tag = f"{safe}-{zlib.crc32(name.encode()):08x}"
        base = os.path.join(Storage.base_dir(), "train_cache")
        return (
            os.path.join(base, f"{tag}.npz"),
            os.path.join(base, f"{tag}.json"),
        )

    def _cache_manifest(self) -> dict:
        p = self.params
        return {
            "version": 1,
            "app": p.app_name,
            "rate_event": p.rate_event,
            "buy_event": p.buy_event,
            "buy_rating": p.buy_rating,
        }

    def _try_incremental(self, pe, app_id) -> TrainingData | None:
        """Delta re-index on an append-only columnar store (SURVEY §8.3
        "incremental re-index on new events"): if a previous train's
        cache is still a valid prefix of the store (its segments all
        exist, no new tombstones, tail only appended), read ONLY the
        segments/tail lines added since, merge with the cached deduped
        matrix, and re-dedup. The reference gets the same effect from
        Spark RDD caching; here the cache is an explicit on-disk
        artifact that survives processes."""
        import json

        npz_path, json_path = self._cache_paths()
        try:
            with open(json_path) as f:
                meta = json.load(f)
        except (FileNotFoundError, ValueError):
            return None
        if meta.get("manifest") != self._cache_manifest():
            return None
        state = pe.scan_state(app_id)
        cached_segments = set(meta.get("segments", ()))
        if (
            meta.get("stream_id") != state.get("stream_id")
            or not meta.get("stream_id")
            or meta.get("tombstones") != state["tombstones"]
            or not cached_segments.issubset(set(state["segments"]))
            or meta.get("tail_lines", 0) > state["tail_lines"]
            # a compaction CONSUMED the recorded tail lines; once the
            # tail regrows past the recorded length, tail_skip would
            # silently skip genuinely new events — the generation
            # counter makes any pre-compaction manifest stale
            or meta.get("compactions", 0) != state.get("compactions", 0)
        ):
            return None
        new_segments = [
            s for s in state["segments"] if s not in cached_segments
        ]
        import zipfile

        try:
            with np.load(npz_path, allow_pickle=False) as z:
                cache = {k: z[k] for k in z.files}
        except (FileNotFoundError, ValueError, EOFError, OSError,
                zipfile.BadZipFile):
            # a truncated/empty payload (crash between replace and disk
            # flush) invalidates the cache — fall back to a full rebuild
            return None
        # manifest and payload must be from the SAME save (advisor r4:
        # concurrent trains can interleave the two atomic replaces)
        if str(cache.pop("__payload_id__", "")) != meta.get("payload_id"):
            return None
        p = self.params
        delta = pe.find_columns(
            app_id,
            entity_type="user",
            event_names=[p.rate_event, p.buy_event],
            prop="rating",
            segments=new_segments,
            tail_skip=int(meta.get("tail_lines", 0)),
        )
        # TOCTOU guard: a compaction landing between the scan_state above
        # and this delta read moves the uncached tail lines into a
        # segment that is NOT in new_segments while emptying the tail —
        # the delta would silently miss them. Each storage call is
        # snapshot-consistent on its own; the two-call sequence is only
        # valid if the generation did not move underneath it.
        if pe.scan_state(app_id).get("compactions", 0) != state.get(
            "compactions", 0
        ):
            return None
        du, di, dt_us, dv = self._extract_ratings_arrays(delta)
        if du.size == 0:
            # nothing new: the cache IS the training data — skip the
            # merge/dedup entirely (the common retrain-without-new-events
            # case, e.g. a hyperparameter retrain). Still advance the
            # manifest when rating-free segments/tail appeared, so they
            # are not re-scanned next time.
            if (
                meta.get("segments") != state["segments"]
                or meta.get("tail_lines") != state["tail_lines"]
            ):
                self._save_cache(dict(cache), state)
            user_list = cache["user_vocab"].tolist()
            item_list = cache["item_vocab"].tolist()
            logging.getLogger(__name__).info(
                "Incremental re-index: store unchanged, reusing %d cached "
                "ratings", cache["vals"].size,
            )
            return TrainingData(
                rows=cache["u_code"].astype(np.int64),
                cols=cache["i_code"].astype(np.int64),
                vals=cache["vals"],
                user_index=BiMap.string_index(user_list),
                item_index=BiMap.string_index(item_list),
            )
        # unify vocabularies (cache vocab is exactly its used ids; du is
        # non-empty past the early return above, so the delta vocabs are
        # non-empty too)
        user_vocab = np.unique(
            np.concatenate([cache["user_vocab"], delta.entity_vocab])
        )
        item_vocab = np.unique(
            np.concatenate([cache["item_vocab"], delta.target_vocab])
        )
        cu = np.searchsorted(user_vocab, cache["user_vocab"]).astype(np.int64)[
            cache["u_code"]
        ]
        ci = np.searchsorted(item_vocab, cache["item_vocab"]).astype(np.int64)[
            cache["i_code"]
        ]
        du = np.searchsorted(user_vocab, delta.entity_vocab).astype(np.int64)[du]
        di = np.searchsorted(item_vocab, delta.target_vocab).astype(np.int64)[di]
        td, payload = self._assemble_training_data(
            np.concatenate([cu, du]),
            np.concatenate([ci, di]),
            np.concatenate([cache["t_us"], dt_us]),
            np.concatenate([cache["vals"], dv]).astype(np.float32),
            user_vocab,
            item_vocab,
        )
        self._save_cache(payload, state)
        logging.getLogger(__name__).info(
            "Incremental re-index: merged %d cached ratings with %d delta "
            "events (%d new segments, %d new tail lines)",
            cache["vals"].size, dv.size, len(new_segments),
            state["tail_lines"] - int(meta.get("tail_lines", 0)),
        )
        return td

    def _save_cache(self, payload: dict, state: dict) -> None:
        import json
        import uuid

        npz_path, json_path = self._cache_paths()
        os.makedirs(os.path.dirname(npz_path), exist_ok=True)
        # the same id is stored INSIDE both files: two concurrent trains
        # interleaving their two atomic replaces could otherwise pair one
        # run's manifest with the other's payload (advisor r4), and the
        # manifest would then bless the wrong cached ratings as valid
        payload_id = uuid.uuid4().hex
        tmp = npz_path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, __payload_id__=np.array(payload_id), **payload)
        # the cache is a pure optimization: a torn/absent file fails the
        # payload_id pairing check on load and the next train re-indexes
        # from the event store, so fsync latency here buys nothing
        os.replace(tmp, npz_path)  # piolint: waive=PIO501 -- rebuildable cache: torn files fail payload_id validation and trigger a full re-index; no acked data rides on this rename
        tmp = json_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "manifest": self._cache_manifest(),
                    "payload_id": payload_id,
                    **state,
                },
                f,
            )
        os.replace(tmp, json_path)

    def _read_training_columnar(self, ctx: WorkflowContext) -> TrainingData:
        """Vectorized single-host read: the columnar bulk scan
        (``PEventStore.find_columns``) plus numpy dedup/BiMap — no
        per-event Python, which is what lets the FULL product path
        (event store → template → ALS) keep up with the TPU at 10^7+
        events (VERDICT r3 next-round #1). Semantics are identical to
        :meth:`_read_ratings_stream` (the per-event oracle the
        equivalence tests compare against): latest event per (user,
        item) wins, ties
        break toward the higher rating, rate events must carry a numeric
        ``rating`` property. On an append-only columnar store, repeat
        trains read only the NEW segments/tail (see
        :meth:`_try_incremental`)."""
        from predictionio_tpu.data.storage import Storage
        from predictionio_tpu.data.store import resolve_app

        p = self.params
        pe = Storage.get_p_events()
        # cache only whole-store reads: a sharded (multi-host) read would
        # record the full manifest against one shard's data and poison
        # later single-host trains
        incremental_capable = (
            p.incremental and hasattr(pe, "scan_state") and ctx.num_hosts == 1
        )
        if incremental_capable:
            app_id, _ = resolve_app(p.app_name)
            try:
                td = self._try_incremental(pe, app_id)
                if td is not None:
                    return td
            except Exception:
                logging.getLogger(__name__).warning(
                    "Incremental re-index failed; falling back to a full "
                    "read", exc_info=True,
                )
        if incremental_capable:
            state = pe.scan_state(app_id)  # BEFORE the read: a concurrent
            # append between read and state snapshot must invalidate, not
            # silently count as already-consumed
        cols = PEventStore.find_columns(
            app_name=p.app_name,
            entity_type="user",
            event_names=[p.rate_event, p.buy_event],
            prop="rating",
            shard_index=ctx.host_index,
            num_shards=ctx.num_hosts,
        )
        u_code, i_code, t_arr, v = self._extract_ratings_arrays(cols)
        td, payload = self._assemble_training_data(
            u_code, i_code, t_arr, v, cols.entity_vocab, cols.target_vocab
        )
        if incremental_capable:
            try:
                self._save_cache(payload, state)
            except OSError:
                logging.getLogger(__name__).warning(
                    "Could not persist the training cache", exc_info=True
                )
        return td

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        if ctx.num_hosts > 1:
            # the multi-host path needs the cross-host latest-wins merge
            # and globally identical BiMaps — stays on the keyed exchange
            return self._to_training_data(self._read_ratings(ctx), ctx)
        return self._read_training_columnar(ctx)

    def read_eval(self, ctx: WorkflowContext):
        """K-fold split by stable hash of (user, item): train on k-1 folds,
        query each held-out user for top-N, actual = held-out items
        (parity: the template's ``readEval`` + e2 ``splitData``)."""
        triples = self._read_ratings(ctx)
        k = max(2, self.params.eval_k)
        folds = []
        import zlib

        def fold_of(u: str, i: str) -> int:
            return zlib.crc32(f"{u}\x00{i}".encode()) % k

        num_items = len({i for _, i, _ in triples})
        for fold in range(k):
            train = [t for t in triples if fold_of(t[0], t[1]) != fold]
            held = [t for t in triples if fold_of(t[0], t[1]) == fold]
            td = self._to_training_data(train, ctx)
            seen_by_user: dict[str, set] = {}
            for u, i, _ in train:
                seen_by_user.setdefault(u, set()).add(i)
            by_user: dict[str, list[str]] = {}
            for u, i, r in held:
                if r >= 3.5:  # positively-rated held-out items
                    by_user.setdefault(u, []).append(i)
            # Query the full ranking; the metric scores precision among
            # UNSEEN items (Actual carries the user's training items so
            # already-rated recommendations are skipped, not penalized).
            qa = [
                (
                    Query(user=u, num=num_items),
                    Actual(items=tuple(items), seen=tuple(seen_by_user.get(u, ()))),
                )
                for u, items in by_user.items()
                if items
            ]
            folds.append((td, {"fold": fold}, qa))
        return folds


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: int | None = 3
    implicit_prefs: bool = False
    alpha: float = 1.0
    #: serve top-N from the accelerator instead of host numpy. Host serving
    #: wins below ~10^6 items (one small GEMV); device serving wins for
    #: huge catalogs or when queries are batched — and avoids it when the
    #: TPU sits behind a network tunnel where each dispatch pays an RTT.
    serve_on_device: bool = False
    #: guardrail for serve_on_device: a deploy-time probe measures real
    #: per-query device latency and falls back to host serving (with a
    #: warning) when the median exceeds this budget — a remote/tunneled
    #: accelerator pays an RTT per dispatch that silently blows the
    #: reference's <10 ms serving target otherwise. <= 0 disables the
    #: probe (always trust serve_on_device).
    device_latency_budget_ms: float = 10.0
    json_aliases = {
        "numIterations": "num_iterations",
        "lambda": "lambda_",
        "implicitPrefs": "implicit_prefs",
        "serveOnDevice": "serve_on_device",
        "deviceLatencyBudgetMs": "device_latency_budget_ms",
    }


@dataclasses.dataclass
class ALSModel:
    """Factor matrices + id maps; arrays live on host in blobs and on
    device while serving."""

    user_factors: Any  # [U, K]
    item_factors: Any  # [I, K]
    user_index: BiMap
    item_index: BiMap


class ALSAlgorithm(JaxAlgorithm):
    params_class = ALSAlgorithmParams
    query_class = Query

    def __init__(self, params: ALSAlgorithmParams):
        super().__init__(params)

    @staticmethod
    def _aligned_init(old_factors, old_index, new_index, rank, seed):
        """See serving_util.aligned_factor_init (shared with two-tower)."""
        from predictionio_tpu.templates.serving_util import aligned_factor_init

        return aligned_factor_init(old_factors, old_index, new_index, rank, seed)

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> ALSModel:
        p = self.params
        init_user = init_item = None
        warm = ctx.warm_model
        if isinstance(warm, ALSModel):
            seed = 0 if p.seed is None else p.seed
            init_user, n_u = self._aligned_init(
                warm.user_factors, warm.user_index, pd.user_index, p.rank, seed
            )
            init_item, n_i = self._aligned_init(
                warm.item_factors, warm.item_index, pd.item_index, p.rank,
                seed + 1,
            )
            logging.getLogger(__name__).info(
                "Warm start: carried %d/%d user and %d/%d item vectors",
                n_u, len(pd.user_index), n_i, len(pd.item_index),
            )
        factors = train_als(
            pd.rows,
            pd.cols,
            pd.vals,
            num_users=len(pd.user_index),
            num_items=len(pd.item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                implicit=p.implicit_prefs,
                alpha=p.alpha,
                seed=0 if p.seed is None else p.seed,
            ),
            mesh=ctx.mesh,
            init_user=init_user,
            init_item=init_item,
        )
        return ALSModel(
            user_factors=np.asarray(factors.user),
            item_factors=np.asarray(factors.item),
            user_index=pd.user_index,
            item_index=pd.item_index,
        )

    def prepare_model_for_serving(self, model: ALSModel) -> ALSModel:
        if self.params.serve_on_device:
            import jax

            from predictionio_tpu.templates.serving_util import device_latency_ok

            model.user_factors = jax.device_put(np.asarray(model.user_factors))
            model.item_factors = jax.device_put(np.asarray(model.item_factors))
            if len(model.user_index):
                probe = Query(user=model.user_index.keys()[0], num=4)
                if not device_latency_ok(
                    lambda: self.predict(model, probe),
                    self.params.device_latency_budget_ms,
                ):
                    model.user_factors = np.asarray(model.user_factors)
                    model.item_factors = np.asarray(model.item_factors)
            return model
        model.user_factors = np.ascontiguousarray(model.user_factors)
        model.item_factors = np.ascontiguousarray(model.item_factors)
        # warm-up so the first real query pays no compile / cache fill
        # (parity: CreateServer's deploy-time warm-up)
        if len(model.user_index):
            self.predict(model, Query(user=model.user_index.keys()[0], num=4))
        return model

    # ------------------------------------------------------ pinned serving
    def pin_model_for_serving(self, model: ALSModel) -> tuple[ALSModel, int]:
        """``--pin-model`` cache tier (workflow/device_state.py):
        ``device_put`` the factor matrices once per model generation so
        every request scores against resident buffers — no per-request
        host->device staging — and predict/batch_predict flip onto the
        existing jitted device path (bucket-keyed static-``k`` score+
        top-K programs). Returns the pinned model
        and the device bytes it holds (``bytesPinned`` on /stats.json).
        Idempotent: re-pinning an already-pinned model re-uses it."""
        import jax

        user = model.user_factors
        item = model.item_factors
        if isinstance(user, np.ndarray):
            user = jax.device_put(user)
        if isinstance(item, np.ndarray):
            item = jax.device_put(item)
        model.user_factors = user
        model.item_factors = item
        model._pio_pinned = True
        nbytes = int(user.size) * user.dtype.itemsize
        nbytes += int(item.size) * item.dtype.itemsize
        model._pio_bytes_by_dtype = {"float32": nbytes}
        return model, nbytes

    # ------------------------------------------------------ sharded serving
    def shard_model_for_serving(self, model: ALSModel) -> tuple[ALSModel, int]:
        """``--shard-factors`` tier (workflow/device_state.py): pin
        factor SHARDS per device — each of the ``S`` local devices holds
        a ``[rows/S, K]`` slice of each table instead of a replica, so
        per-device factor memory is ``O((U+I)·K / S)`` and the largest
        servable catalog scales with the mesh (the ALX layout training
        already uses, extended to the query path). Top-K routes through
        the shard_map kernel in ``parallel/sharding.py``, which is
        tie-stable-identical to the replicated exact path. Falls back to
        plain pinning on a single-device host."""
        from predictionio_tpu.parallel import sharding

        mesh = sharding.serving_mesh()
        if mesh is None:
            logging.getLogger(__name__).warning(
                "--shard-factors requested but only one device is "
                "visible; falling back to --pin-model replication"
            )
            return self.pin_model_for_serving(model)
        user = sharding.shard_table(np.asarray(model.user_factors), mesh)
        item = sharding.shard_table(np.asarray(model.item_factors), mesh)
        info = sharding.ShardInfo(
            mesh=mesh,
            rows={
                "user": int(np.asarray(model.user_factors).shape[0]),
                "item": int(np.asarray(model.item_factors).shape[0]),
            },
        )
        model.user_factors = user
        model.item_factors = item
        model._pio_shards = info
        model._pio_pinned = True
        nbytes = int(user.size) * user.dtype.itemsize
        nbytes += int(item.size) * item.dtype.itemsize
        model._pio_bytes_by_dtype = {"float32": nbytes}
        return model, nbytes

    # ---------------------------------------------------- quantized serving
    def quantize_model_for_serving(
        self, model: ALSModel, mode: str = "int8", shard: bool = False
    ) -> tuple[ALSModel, int]:
        """``--quantize int8`` tier (workflow/device_state.py): pin the
        factor tables as int8 codes + per-row f32 scales (ops/quant.py's
        one rounding rule) so the served catalog costs ``rank + 4``
        bytes per row instead of ``4·rank``. Serving routes through the
        recall-guarded two-stage kernel (int8 coarse scan over-fetching
        ``max(4k, k+64)``, f32 rescore of only the gathered candidates,
        shared tie rule). ``shard=True`` composes with
        ``--shard-factors``: codes and scales shard over the model mesh,
        so per-device bytes are ``catalog·(rank+4)/S`` — the tiers
        multiply. Returns ``(model, real pinned bytes)``; the per-dtype
        ledger lands on ``model._pio_bytes_by_dtype``."""
        from predictionio_tpu.ops import quant

        user_f = np.asarray(model.user_factors, np.float32)
        item_f = np.asarray(model.item_factors, np.float32)
        mesh = None
        if shard:
            from predictionio_tpu.parallel import sharding

            mesh = sharding.serving_mesh()
            if mesh is None:
                logging.getLogger(__name__).warning(
                    "--shard-factors requested but only one device is "
                    "visible; quantized tables pin replicated"
                )
        if mesh is not None:
            from predictionio_tpu.parallel import sharding

            user = sharding.shard_quantized_table(user_f, mesh)
            item = sharding.shard_quantized_table(item_f, mesh)
            model._pio_shards = sharding.ShardInfo(
                mesh=mesh,
                rows={
                    "user": int(user_f.shape[0]),
                    "item": int(item_f.shape[0]),
                },
            )
        else:
            user = quant.quantize_table(user_f)
            item = quant.quantize_table(item_f)
        model.user_factors = user
        model.item_factors = item
        model._pio_pinned = True
        breakdown = {
            "int8": user.nbytes_codes + item.nbytes_codes,
            "scalesFloat32": user.nbytes_scales + item.nbytes_scales,
        }
        model._pio_bytes_by_dtype = breakdown
        model._pio_quant = quant.QuantRuntime(
            mode=mode,
            bytes_by_dtype=breakdown,
            bytes_f32=user_f.nbytes + item_f.nbytes,
            # item-side error is what reorders results; one pass at
            # load time, reported on /stats.json quant
            error=quant.quantization_error(
                item_f,
                np.asarray(item.codes)[: item_f.shape[0]],
                np.asarray(item.scales)[: item_f.shape[0]],
            ),
        )
        return model, sum(breakdown.values())

    def release_pinned_model(self, model: ALSModel) -> None:
        """Drop a superseded generation's pinned buffers (hot reload must
        not accumulate one catalog of device memory per swap). For a
        SHARDED generation this must drop every device's shard handles —
        not just device 0's — so the host-gather strips the even-shard
        padding and the ShardInfo goes with the buffers. Quantized
        tables dequantize back to host f32 (np.asarray reads through the
        codes), and the QuantRuntime goes with them."""
        shards = getattr(model, "_pio_shards", None)
        quantized = getattr(model, "_pio_quant", None) is not None
        # the AOT runtime is per-generation (its programs are lowered
        # against this generation's table shapes) — it retires with the
        # pinned buffers
        if getattr(model, "_pio_aot", None) is not None:
            model._pio_aot = None
        if shards is not None:
            model.user_factors = np.asarray(model.user_factors)[
                : shards.rows["user"]
            ]
            model.item_factors = np.asarray(model.item_factors)[
                : shards.rows["item"]
            ]
            model._pio_shards = None
            model._pio_pinned = False
            model._pio_quant = None
            return
        if getattr(model, "_pio_pinned", False) or quantized:
            model.user_factors = np.asarray(model.user_factors)
            model.item_factors = np.asarray(model.item_factors)
            model._pio_pinned = False
            model._pio_quant = None

    # --------------------------------------------------- ANN retrieval
    def build_ann_for_serving(self, model: ALSModel, ann) -> tuple[ALSModel, dict]:
        """``--ann`` retrieval tier (workflow/device_state.py): cluster
        the item factors into an on-device IVF index once per model
        generation; predict/batch_predict then score only ``nprobe``
        cluster slabs per query instead of the whole catalog. Returns
        the model (with ``model._pio_ann`` attached) and the build info
        for ``/stats.json``."""
        from predictionio_tpu.ops import ivf

        shards = getattr(model, "_pio_shards", None)
        # np.asarray dequantizes a --quantize table; k-means runs on the
        # f32 values either way, and the SERVED slabs re-quantize below
        items = np.asarray(model.item_factors)
        if shards is not None:
            # sharded tables carry even-shard padding rows — the index
            # must cluster only the LOGICAL catalog
            items = items[: shards.rows["item"]]
        index, info = ivf.build_ivf(
            items,
            nlist=ann.nlist, seed=ann.seed, iters=ann.kmeans_iters,
            # --quantize composition: slabs stored int8 + per-lane
            # scales, so per-probe gather bytes drop ~4x (the centroid
            # stage stays f32)
            quantize=getattr(model, "_pio_quant", None) is not None,
        )
        model._pio_ann = ivf.AnnRuntime(index, ann.nprobe, info)
        if shards is not None:
            # --shard-factors composition: the cluster-major slabs shard
            # over the same model axis as the factor tables
            info = dict(info, **ivf.shard_runtime(model._pio_ann, shards.mesh))
        info = dict(info, algorithm=type(self).__name__,
                    nprobe=model._pio_ann.nprobe)
        return model, info

    def release_ann_state(self, model: ALSModel) -> None:
        """Drop a superseded generation's IVF index (same contract as
        release_pinned_model: a hot-reloading server must not accumulate
        one index of device memory per swap)."""
        if getattr(model, "_pio_ann", None) is not None:
            model._pio_ann = None

    # --------------------------------------------------- AOT serving export
    def aot_export_for_serving(self, model: ALSModel, buckets: list) -> dict:
        """``--aot`` tier (workflow/aot.py): lower + serialize the pinned
        exact serving programs per pow2 k-bucket, so replicas boot by
        DESERIALIZING instead of tracing — zero serve-time compiles.

        The export mirrors the JIT path's deliberate program split —
        k-independent ``predict_scores`` plus per-bucket ``top_k_scores``
        (and the batch GEMM+top-k per chunk/bucket) — rather than fusing
        score+select into one program, so bit-identity with the jitted
        path holds by construction: same jaxprs, same rounding, same tie
        order. Sharded/quantized/ANN generations export nothing — their
        kernels close over live runtime objects (mesh, codes, index) and
        serve through their own budgeted paths."""
        if getattr(model, "_pio_shards", None) is not None:
            return {}
        if getattr(model, "_pio_quant", None) is not None:
            return {}
        import jax
        from jax import export as jax_export

        from predictionio_tpu.ops.als import predict_scores, top_k_items_batch
        from predictionio_tpu.ops.topk import top_k_scores

        n_users, rank = (int(d) for d in model.user_factors.shape)
        n_items = int(model.item_factors.shape[0])
        f32 = np.dtype(np.float32)
        vec = jax.ShapeDtypeStruct((rank,), f32)
        users = jax.ShapeDtypeStruct((n_users, rank), f32)
        items = jax.ShapeDtypeStruct((n_items, rank), f32)
        chunk = self.BATCH_PREDICT_CHUNK
        idx_chunk = jax.ShapeDtypeStruct((chunk,), np.dtype(np.int32))
        out = {"predict_scores": jax_export.export(predict_scores)(vec, items)}
        for kb in buckets:
            # bind the static k through a jitted closure — jax.export
            # lowers concrete avals, static_argnames stay host-side
            out[f"top_k_scores_b{kb}"] = jax_export.export(
                jax.jit(lambda s, _k=kb: top_k_scores(s, _k))
            )(jax.ShapeDtypeStruct((n_items,), f32))
            out[f"top_k_items_batch_c{chunk}_b{kb}"] = jax_export.export(
                jax.jit(
                    lambda u, um, im, _k=kb: top_k_items_batch(u, um, im, _k)
                )
            )(idx_chunk, users, items)
        return out

    def aot_warm_serving(self, model: ALSModel) -> None:
        """Warm the pinned predict path's eager GLUE at boot: the
        ``user_factors[uidx]`` row gather (dynamic_slice + squeeze) is
        index-operand cached by jax, so one call here compiles the
        executables every user's query will reuse — without it the
        first query after an AOT boot still witnesses two compiles."""
        if getattr(model, "_pio_pinned", False):
            _ = model.user_factors[0]
    @staticmethod
    def _online_state(model: ALSModel, max_entities: int) -> dict:
        """Per-model online rating accumulator (LRU-bounded per side):
        the follower only sees events since deploy, so each touched
        entity's re-solve uses its accumulated ONLINE history anchored
        to its trained row (online/foldin.py). Dies with the model on a
        full /reload — by then a retrain owns the history."""
        state = getattr(model, "_pio_online", None)
        if state is None:
            from collections import OrderedDict

            state = {
                "users": OrderedDict(),
                "items": OrderedDict(),
                "max": max_entities,
            }
            model._pio_online = state
        return state

    @staticmethod
    def _remember(side: "Any", key: str, other: str, t_us: int,
                  rating: float, cap: int) -> None:
        hist = side.get(key)
        if hist is None:
            hist = side[key] = {}
        side.move_to_end(key)
        prev = hist.get(other)
        if prev is None or (t_us, rating) >= prev:
            hist[other] = (t_us, rating)
        while len(side) > cap:
            side.popitem(last=False)

    def online_foldin(self, model: ALSModel, deltas, ds_params, config):
        """Compute re-solved rows for the users/items a delta batch
        touched — fixed opposite-side factors, ALS-WR objective, prior
        anchor (see online/foldin.py). Read-only: runs outside the
        serving lock; ``apply_online_update`` swaps the rows in."""
        from predictionio_tpu.online.foldin import foldin_rows, gram_yty
        from predictionio_tpu.online.types import OnlineUpdate, latest_wins

        p = self.params
        rate_event = ds_params.get("rate_event", ds_params.get("rateEvent", "rate"))
        buy_event = ds_params.get("buy_event", ds_params.get("buyEvent", "buy"))
        buy_rating = float(
            ds_params.get("buy_rating", ds_params.get("buyRating", 4.0))
        )
        # map the event mix to ratings, then collapse with the shared
        # latest-wins rule (one source of truth with the training read)
        rated = latest_wins(
            [
                dataclasses.replace(d, rating=buy_rating)
                if d.event == buy_event
                else d
                for d in deltas
                if d.event in (rate_event, buy_event)
            ]
        )
        if not rated:
            return None
        state = self._online_state(model, config.max_entities)
        for (u, i), (t_us, r) in rated.items():
            self._remember(state["users"], u, i, t_us, r, state["max"])
            self._remember(state["items"], i, u, t_us, r, state["max"])
        touched_users = sorted({u for u, _ in rated})
        touched_items = sorted({i for _, i in rated})
        implicit = p.implicit_prefs
        yty_item = yty_user = None
        if implicit:
            # the implicit objective's Gramian over the opposite factors,
            # computed ONCE per model object (it dies with the model on
            # /reload, when a retrain re-anchors everything). Folds move
            # a few rows so the cached YtY drifts slightly — the same
            # approximation MLlib's fold-in makes by using the
            # training-time Gramian; recomputing O(N*K^2) per fold would
            # turn the delta-cost fold into a full-catalog pass
            if "yty_item" not in state:
                state["yty_item"] = gram_yty(np.asarray(model.item_factors))
                state["yty_user"] = gram_yty(np.asarray(model.user_factors))
            yty_item = state["yty_item"]
            yty_user = state["yty_user"]

        def solve_side(touched, side_hist, own_factors, own_index,
                       opp_index, opp_factors, yty):
            ids, entries, prior_rows = [], [], []
            n_own = int(own_factors.shape[0])
            for ent in touched:
                hist = side_hist.get(ent, {})
                pairs = [
                    (idx, r)
                    for other, (_, r) in hist.items()
                    if (idx := opp_index.get(other)) is not None
                ]
                if not pairs:
                    continue  # nothing resolvable yet (opposite unseen)
                row = own_index.get(ent)
                ids.append(ent)
                entries.append(([ix for ix, _ in pairs], [r for _, r in pairs]))
                # -1 = cold start: pure fold-in from first events
                prior_rows.append(
                    row if row is not None and row < n_own else -1
                )
            if not ids:
                return [], None
            # gather ONLY the touched prior rows — for a pinned (device)
            # table this is one on-device gather + a len(ids)-row
            # transfer, never the whole table host-side per fold
            prior_rows = np.asarray(prior_rows, np.int64)
            known = prior_rows >= 0
            if n_own:
                gathered = np.asarray(
                    own_factors[np.where(known, prior_rows, 0)], np.float32
                )
            else:
                gathered = np.zeros(
                    (len(ids), int(own_factors.shape[1])), np.float32
                )
            priors = np.where(known[:, None], gathered, 0.0).astype(np.float32)
            weights = np.where(known, config.prior_weight, 0.0).astype(
                np.float32
            )
            rows = foldin_rows(
                opp_factors,
                entries,
                reg=p.lambda_,
                priors=priors,
                prior_weights=weights,
                implicit=implicit,
                alpha=p.alpha,
                yty=yty,
            )
            return ids, rows

        user_ids, user_rows = solve_side(
            touched_users, state["users"], model.user_factors,
            model.user_index, model.item_index, model.item_factors, yty_item,
        )
        item_ids, item_rows = solve_side(
            touched_items, state["items"], model.item_factors,
            model.item_index, model.user_index, model.user_factors, yty_user,
        )
        if not user_ids and not item_ids:
            return None
        return OnlineUpdate(
            user_ids=user_ids,
            user_rows=user_rows,
            item_ids=item_ids,
            item_rows=item_rows,
            # every user who RATED in this batch sees changed results
            # even when only the item side of their pair moved (e.g. a
            # brand-new item they just rated) — their cached entries
            # must die with the swap
            extra_scopes=sorted({u for u, _ in rated}),
            info={"ratings": len(rated)},
        )

    def apply_online_update(self, model: ALSModel, upd) -> dict:
        """Swap the computed rows into the live model — called UNDER the
        query service's generation lock, so it must stay cheap: row
        scatters (on-device for pinned state), id-map extension for
        cold starts, and the incremental IVF index update."""
        from predictionio_tpu.workflow import device_state

        info = {"usersUpdated": 0, "itemsUpdated": 0,
                "usersAdded": 0, "itemsAdded": 0}
        if upd.user_ids:
            info["usersUpdated"], info["usersAdded"] = (
                device_state.swap_side_rows(
                    model, upd.user_ids, upd.user_rows,
                    "user_factors", "user_index", rows_before_index=True,
                )
            )
        if upd.item_ids:
            info["itemsUpdated"], info["itemsAdded"] = (
                device_state.swap_side_rows(
                    model, upd.item_ids, upd.item_rows,
                    "item_factors", "item_index", rows_before_index=False,
                )
            )
            if info["itemsAdded"]:
                # the batchpredict fast path caches per-item JSON
                # prefixes by index — a grown catalog invalidates them
                model._item_json_prefix = None
            ann_info = device_state.update_ann_items(
                model, upd.item_ids, upd.item_rows
            )
            if ann_info is not None:
                info["ann"] = ann_info
        return info

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        uidx = model.user_index.get(query.user)
        if uidx is None:
            return PredictedResult(())
        k = min(int(query.num), len(model.item_index))
        if k <= 0:
            return PredictedResult(())
        ann = getattr(model, "_pio_ann", None)
        shards = getattr(model, "_pio_shards", None)
        quantrt = getattr(model, "_pio_quant", None)
        if ann is not None:
            from predictionio_tpu.ops import ivf

            if quantrt is not None:
                # quantized user table: __getitem__ dequantizes only the
                # requested row (sharded or not)
                qvec = np.asarray(
                    model.user_factors[np.asarray([uidx], np.int64)]
                )[0]
            elif shards is not None:
                from predictionio_tpu.parallel import sharding

                qvec = np.asarray(
                    sharding.gather_rows(
                        np.asarray([uidx], np.int32),
                        model.user_factors, shards.mesh,
                    )
                )[0]
            else:
                qvec = np.asarray(model.user_factors[uidx])
            ids, scores = ivf.query_topk(ann, qvec, k)
            pairs = list(zip(ids, scores))
        elif quantrt is not None:
            # quantized exact: int8 coarse scan with over-fetch, f32
            # rescore of the gathered candidates (ops/quant.py); routes
            # through the shard_map kernel under --shard-factors
            from predictionio_tpu.ops import quant

            ids_b, scores_b = quant.topk_users(
                quantrt, model.user_factors, model.item_factors,
                [uidx], k, shards=shards,
            )
            pairs = [
                (int(i), float(s)) for i, s in zip(ids_b[0], scores_b[0])
            ]
        elif shards is not None:
            # sharded exact: one dispatch, each device scores its item
            # shard, only the S*k finalists cross the interconnect
            from predictionio_tpu.parallel import sharding

            ids_b, scores_b = sharding.topk_users(
                shards, model.user_factors, model.item_factors, [uidx], k
            )
            pairs = [
                (int(i), float(s)) for i, s in zip(ids_b[0], scores_b[0])
            ]
        elif isinstance(model.item_factors, np.ndarray):
            # host path: one GEMV + partial sort, microseconds at catalog
            # sizes below ~10^6 items (shared tie rule: ops/topk.py)
            from predictionio_tpu.ops.topk import top_k_host

            scores = model.item_factors @ np.asarray(model.user_factors[uidx])
            top, vals = top_k_host(scores, k)
            pairs = [(int(i), float(s)) for i, s in zip(top, vals)]
        else:
            # pinned-device path: k buckets to a power of two (floor 16)
            # so the jitted selection compiles once per bucket — raw
            # query.num would key the jit cache at request cardinality
            # (piolint PIO306; same idiom as ivf.query_topk). Scoring is
            # a SEPARATE k-independent program (predict_scores) so the
            # GEMV's float rounding — and therefore tie order vs the
            # host path — cannot drift with the chosen bucket
            from predictionio_tpu.ops.als import predict_scores
            from predictionio_tpu.ops.topk import bucket_k, top_k_scores

            kb = bucket_k(k, int(model.item_factors.shape[0]))
            idx = scores = None
            aot = getattr(model, "_pio_aot", None)
            if aot is not None:
                # --aot tier 1: the SAME two programs, deserialized at
                # boot instead of traced here; any call-time failure
                # (e.g. shape drift after an online catalog grow)
                # disables the key and the jitted path takes over
                score_fn = aot.get("predict_scores")
                topk_fn = aot.get(f"top_k_scores_b{kb}")
                if score_fn is not None and topk_fn is not None:
                    try:
                        dev_scores = score_fn(
                            model.user_factors[uidx], model.item_factors
                        )
                        idx, scores = topk_fn(dev_scores)
                    except Exception as e:  # noqa: BLE001 - degrade, don't 500
                        aot.disable("predict_scores", str(e))
                        aot.disable(f"top_k_scores_b{kb}", str(e))
                        idx = scores = None
            if idx is None:
                dev_scores = predict_scores(
                    model.user_factors[uidx], model.item_factors
                )
                idx, scores = top_k_scores(dev_scores, kb)
            pairs = [
                (int(i), float(s))
                for i, s in zip(np.asarray(idx)[:k], np.asarray(scores)[:k])
            ]
        return PredictedResult(
            tuple(
                ItemScore(item=model.item_index.inverse(i), score=s) for i, s in pairs
            )
        )

    #: queries per device dispatch / host GEMM (shared tuning constant —
    #: see serving_util.TOPK_CHUNK; kept as a class attribute so tests
    #: can shrink it to force multi-chunk coverage)
    BATCH_PREDICT_CHUNK = TOPK_CHUNK

    def batch_predict(
        self, model: ALSModel, queries: Sequence[tuple[int, Query]]
    ) -> list[tuple[int, PredictedResult]]:
        """Batch-amortized prediction (ref ``BatchPredict.scala``
        ``batchPredictBase``): instead of a GEMV (or worse, a device round
        trip) per query, score whole chunks with one ``[B,K]@[K,I]`` GEMM
        and one top-k — on device via :func:`top_k_items_batch` (a single
        dispatch + one small transfer per chunk), on host via one numpy
        GEMM + row-wise argpartition."""
        n_items = len(model.item_index)
        results: list[tuple[int, PredictedResult]] = []
        valid: list[tuple[int, int, int]] = []  # (orig idx, uidx, k)
        for idx, q in queries:
            uidx = model.user_index.get(q.user)
            k = min(int(q.num), n_items)
            if uidx is None or k <= 0:
                results.append((idx, PredictedResult(())))
            else:
                valid.append((idx, uidx, k))
        if not valid:
            return results
        inverse = model.item_index.inverse
        for part, idx_l, score_l in self._topk_staged(model, valid):
            for (oi, _, k), ids, scs in zip(part, idx_l, score_l):
                results.append((
                    oi,
                    PredictedResult(tuple(
                        ItemScore(item=inverse(i), score=s)
                        for i, s in zip(ids[:k], scs[:k])
                    )),
                ))
        return results

    def _topk_staged(self, model: ALSModel, valid: list):
        """Chunked top-k over ``valid = [(slot, uidx, k), ...]`` — see
        :func:`predictionio_tpu.templates.serving_util.chunked_topk`.
        With ``--ann`` state attached the chunks route through the IVF
        kernel (only ``nprobe`` cluster slabs scored per query)."""
        from predictionio_tpu.templates.serving_util import chunked_topk

        return chunked_topk(
            model.user_factors, model.item_factors, valid,
            chunk=self.BATCH_PREDICT_CHUNK,
            ann=getattr(model, "_pio_ann", None),
            shards=getattr(model, "_pio_shards", None),
            quant=getattr(model, "_pio_quant", None),
            aot=getattr(model, "_pio_aot", None),
        )

    def batch_predict_json(
        self, model: ALSModel, bodies: Sequence[Any]
    ) -> list[str | None]:
        """Vectorized bulk scoring straight to JSON payload strings (the
        ``pio batchpredict`` fast path — see
        ``QueryService.handle_batch_jsonlines``). Only bodies that would
        bind trivially (``{"user": str, "num"?: int}``) are answered;
        anything else returns ``None`` in its slot so the caller routes
        it through the exact slow path. Output strings are precisely
        ``PredictedResult.to_json`` serialized — same scores, same order
        — minus ~10 us/query of dataclass+json overhead, which is the
        difference between 15k and 50k+ queries/sec on one core."""
        n_items = len(model.item_index)
        get_u = model.user_index.get
        out: list[str | None] = [None] * len(bodies)
        valid: list[tuple[int, int, int]] = []
        for j, b in enumerate(bodies):
            if not isinstance(b, Mapping) or set(b) - {"user", "num"}:
                continue  # slow path replicates exact bind/error behavior
            user = b.get("user")
            num = b.get("num", 4)
            if not isinstance(user, str) or type(num) is not int:
                continue
            uidx = get_u(user)
            k = min(num, n_items)
            if uidx is None or k <= 0:
                out[j] = '{"itemScores": []}'
            else:
                valid.append((j, uidx, k))
        if not valid:
            return out
        # per-item prefix strings ('{"item": "<escaped>", "score": '),
        # computed once per model and cached on it: json.dumps (or even
        # %-formatting) per emitted item would dominate the fast path
        pre = getattr(model, "_item_json_prefix", None)
        if pre is None:
            # built by INDEX order (inverse), not iteration order — a
            # BiMap constructed from a dict out of index order would
            # silently mislabel items if we zipped keys() positionally
            inverse = model.item_index.inverse
            pre = [
                '{"item": %s, "score": ' % json.dumps(inverse(i))
                for i in range(n_items)
            ]
            model._item_json_prefix = pre
        for part, idx_l, score_l in self._topk_staged(model, valid):
            for (j, _, k), ids, scs in zip(part, idx_l, score_l):
                out[j] = (
                    '{"itemScores": ['
                    + ", ".join(
                        pre[i] + repr(s) + "}"
                        for i, s in zip(ids[:k], scs[:k])
                    )
                    + "]}"
                )
        return out


class PrecisionAtK(OptionAverageMetric):
    """Fraction of recommended items that are in the held-out positives
    (parity: the eval metric of the reference recommendation template)."""

    def __init__(self, k: int = 10):
        self.k = k

    def header(self) -> str:
        return f"Precision@{self.k}"

    def calculate_unit(self, query, predicted: PredictedResult, actual) -> float | None:
        if not predicted.item_scores:
            return None
        if isinstance(actual, Actual):
            positives, seen = set(actual.items), set(actual.seen)
        else:  # plain iterable of positive items
            positives, seen = set(actual), set()
        top = [s.item for s in predicted.item_scores if s.item not in seen][: self.k]
        if not top:
            return None
        hits = sum(1 for i in top if i in positives)
        return hits / len(top)


def engine_factory() -> Engine:
    return Engine(
        datasource_class=RecommendationDataSource,
        preparator_class=IdentityPreparator,
        algorithms_class_map={"als": ALSAlgorithm},
        serving_class=FirstServing,
    )
