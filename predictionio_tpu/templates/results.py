"""Shared ranked-result wire types for the item-ranking templates.

``recommendation``, ``similarproduct`` and ``ecommerce`` all answer
queries with the same reference wire shape::

    {"itemScores": [{"item": "i1", "score": 4.2}, ...]}

These dataclasses used to live in ``recommendation/engine.py`` and the
other two templates imported them from there — a template-to-template
dependency that breaks the copy-out contract of ``pio template get``
(and is now rejected by piolint's sibling-isolation rule, PIO103).
Shared helper modules directly under ``templates/`` are the sanctioned
home for cross-template code (see ``serving_util``/``columnar_util``);
``recommendation/engine.py`` re-exports both names so existing engine
code and tests keep working.
"""

from __future__ import annotations

import dataclasses
from typing import Any

__all__ = ["ItemScore", "PredictedResult"]


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple = ()

    def to_json(self) -> dict[str, Any]:
        return {
            "itemScores": [{"item": s.item, "score": s.score} for s in self.item_scores]
        }
