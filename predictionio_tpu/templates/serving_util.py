"""Shared serving-path helpers for engine templates."""

from __future__ import annotations

import logging
import time
from typing import Callable, Iterator, Sequence

import numpy as np

__all__ = ["device_latency_ok", "chunked_topk", "aligned_factor_init"]

logger = logging.getLogger(__name__)

#: queries per device dispatch / host GEMM in :func:`chunked_topk` — one
#: compiled shape, so every chunk (the last padded up) reuses the same
#: XLA program
TOPK_CHUNK = 2048


def _drain_staged(
    staged: list, n_items: int, chunk: int
) -> Iterator[tuple[list, list, list]]:
    """Drain chunk-staged device results with ONE link crossing: concat
    all chunks' ids/scores on device, transfer once, then trim each
    row's sentinel padding (id >= n_items at -inf) before any consumer
    sees it — shared by the ANN and quantized staging paths."""
    import jax.numpy as jnp

    if len(staged) > 1:
        idx_all = np.asarray(
            jnp.concatenate([i for _, i, _ in staged], axis=0)
        )
        score_all = np.asarray(
            jnp.concatenate([s for _, _, s in staged], axis=0)
        )
    else:
        idx_all = np.asarray(staged[0][1])
        score_all = np.asarray(staged[0][2])
    off = 0
    for part, _, _ in staged:
        ids_l, scores_l = [], []
        for r in range(len(part)):
            keep = idx_all[off + r] < n_items
            ids_l.append(idx_all[off + r][keep].tolist())
            scores_l.append(score_all[off + r][keep].tolist())
        yield part, ids_l, scores_l
        off += chunk


def chunked_topk(
    user_mat, item_mat, valid: Sequence[tuple], chunk: int = TOPK_CHUNK,
    ann=None, shards=None, quant=None, aot=None,
) -> Iterator[tuple[list, list, list]]:
    """Chunked batch top-k over ``valid = [(slot, uidx, k), ...]``;
    yields ``(part, ids, scores)`` with ids/scores as Python lists — the
    shared engine-template core of batch-amortized serving (ref
    ``BatchPredict.scala`` ``batchPredictBase``).

    k buckets to the next power of two (floor 16): the jitted kernel's k
    is static, so raw ``max(num)`` would recompile per distinct value — a
    bounded bucket set keeps one XLA program per bucket; each query trims
    its own k from the padded result. On device, dispatches stay async
    across chunks and ALL results concatenate on device to cross the
    link in ONE transfer (per-chunk transfers pay a full link round trip
    each — measured ~88 ms through a tunneled chip). ``tolist()``
    converts whole chunks to Python scalars at C speed.

    ``ann`` (an :class:`predictionio_tpu.ops.ivf.AnnRuntime`) routes the
    scoring through the two-stage IVF kernel instead of the full-catalog
    GEMM: only ``nprobe`` cluster slabs are scored per query, so chunk
    cost scales with ``nprobe * (catalog / nlist)`` instead of the
    catalog. Queries whose ``k`` includes a filter over-fetch keep their
    guarantee — the merge returns ``k`` real candidates whenever the
    probed clusters hold that many (sentinel-padded rows are trimmed
    here, before any consumer sees them).

    ``shards`` (a :class:`predictionio_tpu.parallel.sharding.ShardInfo`,
    the ``--shard-factors`` tier) means both tables are model-sharded:
    the exact path routes through the shard_map kernel (each device
    scores only its ``[B,K]@[K,I/S]`` slice; tie-stable-identical
    results), and the ANN path resolves query rows through the sharded
    gather before the cluster-sharded probe kernel.

    ``aot`` (a :class:`predictionio_tpu.workflow.aot.AotRuntime`, the
    ``--aot`` tier) serves the exact on-device branch through the
    generation's DESERIALIZED batch program (same jaxpr as
    ``top_k_items_batch``, so results are bit-identical) instead of the
    jitted one — zero serve-time compiles; a call-time failure disables
    the program key and the very next chunk takes the jitted path.

    ``quant`` (a :class:`predictionio_tpu.ops.quant.QuantRuntime`, the
    ``--quantize int8`` tier) means both tables are int8 codes + per-row
    scales: the exact path runs the two-stage kernel (int8 coarse scan
    over-fetching ``max(4k, k+64)``, f32 rescore of only the gathered
    candidates), composing with ``shards`` through the shard_map
    variant; the ANN path dequantizes only the chunk's query rows and
    probes the (int8-slabbed) index as usual."""
    if not valid:
        return
    # under --shard-factors the physical table is padded to a multiple
    # of the mesh axis; the LOGICAL catalog lives on the ShardInfo
    n_items = (
        int(shards.rows["item"]) if shards is not None
        else int(item_mat.shape[0])
    )
    from predictionio_tpu.ops.topk import bucket_k

    k_max = bucket_k(max(k for _, _, k in valid), n_items)
    if ann is not None:
        import jax.numpy as jnp

        from predictionio_tpu.ops import ivf

        user_on_device = not isinstance(user_mat, np.ndarray)
        user_quantized = getattr(user_mat, "is_quantized", False)
        ann_staged: list = []
        for lo in range(0, len(valid), chunk):
            part = list(valid[lo : lo + chunk])
            uidx_arr = np.fromiter((u for _, u, _ in part), np.int32, len(part))
            if user_quantized:
                # --quantize: dequantize ONLY the chunk's user rows (the
                # f32 queries the probe stage scores with); the probed
                # slabs themselves stay int8 inside the index. The rows
                # stay ON DEVICE — a host round trip here would
                # serialize the chunk dispatches
                padded = np.zeros(chunk, np.int32)
                padded[: len(part)] = uidx_arr
                qv = user_mat[jnp.asarray(padded)]
                if shards is not None:
                    from predictionio_tpu.parallel import sharding

                    idx_b, score_b = sharding.sharded_ivf_topk(
                        qv, ann.index, k_max, ann.nprobe, shards.mesh
                    )
                else:
                    idx_b, score_b = ivf.ivf_topk_batch(
                        qv, ann.index, k_max, ann.nprobe
                    )
            elif shards is not None:
                from predictionio_tpu.parallel import sharding

                padded = np.zeros(chunk, np.int32)
                padded[: len(part)] = uidx_arr
                qv = sharding.gather_rows(padded, user_mat, shards.mesh)
                idx_b, score_b = sharding.sharded_ivf_topk(
                    qv, ann.index, k_max, ann.nprobe, shards.mesh
                )
            elif user_on_device:
                padded = np.zeros(chunk, np.int32)
                padded[: len(part)] = uidx_arr
                idx_b, score_b = ivf.ivf_topk_users(
                    padded, user_mat, ann.index, k_max, ann.nprobe
                )
            else:
                # unpinned model: gather the chunk's user rows on host so
                # each dispatch uploads [chunk, K] — NOT the whole user
                # table, which would dwarf the nprobe savings per call
                qv = np.zeros((chunk, user_mat.shape[1]), np.float32)
                qv[: len(part)] = np.asarray(user_mat)[uidx_arr]
                idx_b, score_b = ivf.ivf_topk_batch(
                    jnp.asarray(qv), ann.index, k_max, ann.nprobe
                )
            ann.note_queries(len(part))
            ann_staged.append((part, idx_b, score_b))
        # same staging discipline as the exact device path below: keep
        # dispatches async across chunks, cross the link ONCE
        yield from _drain_staged(ann_staged, n_items, chunk)
        return
    if quant is not None:
        from predictionio_tpu.ops import quant as quant_ops

        q_staged: list = []
        for lo in range(0, len(valid), chunk):
            part = list(valid[lo : lo + chunk])
            padded = np.zeros(chunk, np.int32)
            padded[: len(part)] = np.fromiter(
                (u for _, u, _ in part), np.int32, len(part)
            )
            idx_b, score_b = quant_ops.run_topk(
                quant, user_mat, item_mat, padded, k_max, shards=shards
            )
            q_staged.append((part, idx_b, score_b))
        yield from _drain_staged(q_staged, n_items, chunk)
        return
    on_device = not isinstance(item_mat, np.ndarray)
    staged: list[tuple[list, object, object]] = []
    for lo in range(0, len(valid), chunk):
        part = list(valid[lo : lo + chunk])
        uidx_arr = np.fromiter((u for _, u, _ in part), np.int32, len(part))
        if shards is not None:
            from predictionio_tpu.parallel import sharding

            padded = np.zeros(chunk, np.int32)
            padded[: len(part)] = uidx_arr
            idx_b, score_b = sharding.sharded_topk_users(
                padded, user_mat, item_mat, k_max, n_items, shards.mesh
            )
        elif on_device:
            from predictionio_tpu.ops.als import top_k_items_batch

            padded = np.zeros(chunk, np.int32)
            padded[: len(part)] = uidx_arr
            aot_key = f"top_k_items_batch_c{chunk}_b{k_max}"
            fn = aot.get(aot_key) if aot is not None else None
            if fn is not None:
                try:
                    idx_b, score_b = fn(padded, user_mat, item_mat)
                except Exception as e:  # noqa: BLE001 - degrade, don't 500
                    aot.disable(aot_key, str(e))
                    fn = None
            if fn is None:
                idx_b, score_b = top_k_items_batch(
                    padded, user_mat, item_mat, k_max
                )
        else:
            from predictionio_tpu.ops.topk import top_k_host

            scores = (
                np.asarray(user_mat)[uidx_arr] @ np.asarray(item_mat).T
            )  # [B, I]
            # descending score, ties broken by ascending item index —
            # the same rule lax.top_k uses, so host and device paths
            # agree wherever the float scores do (shared helper:
            # ops/topk.py)
            idx_b, score_b = top_k_host(scores, k_max)
        staged.append((part, idx_b, score_b))
    if on_device and len(staged) > 1:
        import jax.numpy as jnp

        idx_all = np.asarray(jnp.concatenate([i for _, i, _ in staged], axis=0))
        score_all = np.asarray(
            jnp.concatenate([s for _, _, s in staged], axis=0)
        )
        off = 0
        for part, _, _ in staged:
            yield (
                part,
                idx_all[off : off + len(part)].tolist(),
                score_all[off : off + len(part)].tolist(),
            )
            off += chunk
        return
    for part, idx_b, score_b in staged:
        yield (
            part,
            np.asarray(idx_b)[: len(part)].tolist(),
            np.asarray(score_b)[: len(part)].tolist(),
        )


def device_latency_ok(
    predict_once: Callable[[], None],
    budget_ms: float,
    samples: int = 5,
) -> bool:
    """Deploy-time guardrail for ``serveOnDevice``: measure the real
    per-query device latency and report whether its median fits the
    budget. A remote/tunneled accelerator pays an RTT per dispatch that
    silently blows the reference's <10 ms serving target otherwise.
    ``budget_ms <= 0`` disables the probe (always trust the caller).
    The first call is a warm-up (compile) and is not measured."""
    predict_once()
    if budget_ms <= 0:
        return True
    lat = []
    for _ in range(samples):
        t0 = time.perf_counter()
        predict_once()
        lat.append((time.perf_counter() - t0) * 1e3)
    p50 = sorted(lat)[len(lat) // 2]
    if p50 > budget_ms:
        logger.warning(
            "serveOnDevice probe: median device query latency %.1f ms "
            "exceeds the %.1f ms budget (remote/tunneled accelerator?) — "
            "falling back to host serving. Set the budget <= 0 to force "
            "device.",
            p50,
            budget_ms,
        )
        return False
    return True


def aligned_factor_init(
    old_factors: np.ndarray,
    old_index,
    new_index,
    rank: int,
    seed: int,
    fresh: Callable | None = None,
) -> tuple[np.ndarray, int]:
    """Carry a previous model's factor/embedding rows over to a new id
    space: entities present in both keep their vectors (overlapping
    columns when the rank changed); new entities get the standard
    abs(normal)/sqrt(rank) draw. This is what makes a warm retrain start
    near the previous optimum even as the catalog shifts (SURVEY §8.3;
    shared by the ALS and two-tower templates). Returns (init matrix,
    number of carried rows).

    ``fresh(rng, shape)`` draws the init for NON-carried rows; the
    default is ALS's nonnegative abs(normal)/sqrt(rank). Templates whose
    cold init differs (e.g. the two-tower's signed normal) must pass
    their own draw, or new entities would start in the wrong
    distribution — for towers, all in the positive orthant with pairwise
    cosine ~0.64 instead of ~0."""
    rng = np.random.default_rng(seed)
    shape = (len(new_index), rank)
    if fresh is None:
        out = (np.abs(rng.standard_normal(shape)) / np.sqrt(rank)).astype(
            np.float32
        )
    else:
        out = np.asarray(fresh(rng, shape), np.float32)
        if out.shape != shape:
            raise ValueError(f"fresh draw returned {out.shape}, want {shape}")
    old = np.asarray(old_factors)
    k = min(rank, old.shape[1])
    old_d, new_d = old_index.to_dict(), new_index.to_dict()
    if not old_d or not new_d:
        return out, 0
    # vectorized key intersection — a per-key Python loop would cost
    # minutes at catalog scale (review finding)
    old_keys = np.asarray(list(old_d), dtype=np.str_)
    old_rows = np.fromiter(old_d.values(), np.int64, len(old_d))
    new_keys = np.asarray(list(new_d), dtype=np.str_)
    new_rows = np.fromiter(new_d.values(), np.int64, len(new_d))
    o_sort = np.argsort(old_keys)
    pos = np.searchsorted(old_keys, new_keys, sorter=o_sort)
    pos_c = np.minimum(pos, old_keys.size - 1)
    hit = old_keys[o_sort[pos_c]] == new_keys
    src = old_rows[o_sort[pos_c[hit]]]
    ok = src < old.shape[0]
    out[new_rows[hit][ok], :k] = old[src[ok], :k]
    return out, int(ok.sum())
