"""Shared serving-path helpers for engine templates."""

from __future__ import annotations

import logging
import time
from typing import Callable

__all__ = ["device_latency_ok"]

logger = logging.getLogger(__name__)


def device_latency_ok(
    predict_once: Callable[[], None],
    budget_ms: float,
    samples: int = 5,
) -> bool:
    """Deploy-time guardrail for ``serveOnDevice``: measure the real
    per-query device latency and report whether its median fits the
    budget. A remote/tunneled accelerator pays an RTT per dispatch that
    silently blows the reference's <10 ms serving target otherwise.
    ``budget_ms <= 0`` disables the probe (always trust the caller).
    The first call is a warm-up (compile) and is not measured."""
    predict_once()
    if budget_ms <= 0:
        return True
    lat = []
    for _ in range(samples):
        t0 = time.perf_counter()
        predict_once()
        lat.append((time.perf_counter() - t0) * 1e3)
    p50 = sorted(lat)[len(lat) // 2]
    if p50 > budget_ms:
        logger.warning(
            "serveOnDevice probe: median device query latency %.1f ms "
            "exceeds the %.1f ms budget (remote/tunneled accelerator?) — "
            "falling back to host serving. Set the budget <= 0 to force "
            "device.",
            p50,
            budget_ms,
        )
        return False
    return True
