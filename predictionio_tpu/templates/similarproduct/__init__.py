"""Similar-Product engine template (implicit ALS item similarity).

Capability parity with the reference's scala-parallel-similarproduct
template: ``view`` events + ``$set`` item properties -> implicit-ALS item
factors -> "items similar to these" queries with category / whiteList /
blackList business rules.
"""

from predictionio_tpu.templates.similarproduct.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    DataSourceParams,
    Query,
    SimilarProductDataSource,
    engine_factory,
)

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "DataSourceParams",
    "Query",
    "SimilarProductDataSource",
    "engine_factory",
]
