"""Similar-Product engine: view events -> implicit ALS -> similar items.

Parity map (reference scala-parallel-similarproduct template):

* ``DataSource.scala`` — ``view`` events (user->item) + ``$set`` item
  entities carrying ``categories`` -> :class:`SimilarProductDataSource`.
* ``ALSAlgorithm.scala`` — MLlib implicit ``ALS.trainImplicit``; similar
  items ranked by cosine similarity against the *sum of the query items'
  factor vectors*, excluding the query items, with ``categories`` /
  ``whiteList`` / ``blackList`` filters -> :class:`ALSAlgorithm` over
  :func:`predictionio_tpu.ops.als.train_als`.
* Query ``{"items": ["i1"], "num": 4, "categories"?: [...],
  "whiteList"?: [...], "blackList"?: [...]}`` -> ``{"itemScores": [...]}``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    JaxAlgorithm,
    Params,
    SanityCheck,
    WorkflowContext,
)
from predictionio_tpu.data.aggregator import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.als import ALSConfig, train_als
from predictionio_tpu.templates.results import ItemScore, PredictedResult

__all__ = [
    "Query",
    "DataSourceParams",
    "TrainingData",
    "SimilarProductDataSource",
    "ALSAlgorithmParams",
    "ALSAlgorithm",
    "engine_factory",
]


@dataclasses.dataclass(frozen=True)
class Query:
    items: tuple = ()
    num: int = 4
    categories: tuple | None = None
    white_list: tuple | None = None
    black_list: tuple | None = None
    json_aliases = {"whiteList": "white_list", "blackList": "black_list"}


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    view_event: str = "view"
    item_entity_type: str = "item"
    json_aliases = {"appName": "app_name", "viewEvent": "view_event"}


@dataclasses.dataclass
class TrainingData(SanityCheck):
    rows: np.ndarray  # user idx
    cols: np.ndarray  # item idx
    vals: np.ndarray  # view counts
    user_index: BiMap
    item_index: BiMap
    categories: dict  # item id -> tuple of category strings

    def sanity_check(self) -> None:
        if self.rows.size == 0:
            raise ValueError("No view events found — check appName/viewEvent")


class SimilarProductDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_categories(self) -> dict[str, tuple]:
        """$set-only items included so catalog filters work for unviewed
        items."""
        categories: dict[str, tuple] = {}
        item_props = PEventStore.aggregate_properties(
            app_name=self.params.app_name,
            entity_type=self.params.item_entity_type,
        )
        for item_id, pm in item_props.items():
            cats = pm.opt("categories", list, [])
            categories[item_id] = tuple(str(c) for c in cats)
        return categories

    def _read_training_columnar(self, ctx: WorkflowContext) -> TrainingData:
        """Vectorized single-host read: columnar bulk scan + numpy
        per-pair view counting — the same no-per-event-Python path the
        recommendation template takes (VERDICT r3 next-round #1), with
        sum aggregation instead of latest-wins."""
        from predictionio_tpu.templates.columnar_util import (
            aggregate_pairs,
            densify_pairs,
        )

        p = self.params
        cols = PEventStore.find_columns(
            app_name=p.app_name, event_names=[p.view_event]
        )
        u_sel, i_sel, counts = aggregate_pairs(cols)
        # user vocab: viewed users only; item vocab: viewed + $set-only
        categories = self._read_categories()
        rows, cols_idx, user_vocab, item_vocab = densify_pairs(
            cols, u_sel, i_sel, extra_items=categories
        )
        return TrainingData(
            rows=rows,
            cols=cols_idx,
            vals=counts,
            user_index=BiMap.string_index(user_vocab),
            item_index=BiMap.string_index(item_vocab),
            categories=categories,
        )

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        p = self.params
        if ctx.num_hosts == 1:
            return self._read_training_columnar(ctx)
        counts: dict[tuple[str, str], float] = {}
        for e in PEventStore.find(
            app_name=p.app_name,
            event_names=[p.view_event],
            shard_index=ctx.host_index,
            num_shards=ctx.num_hosts,
        ):
            if e.target_entity_id is None:
                continue
            key = (e.entity_id, e.target_entity_id)
            counts[key] = counts.get(key, 0.0) + 1.0
        categories = self._read_categories()
        # cross-host coherence (round-1 advisor high finding): merge
        # per-host view counts by user, then build IDENTICAL global
        # BiMaps on every host from sorted vocabularies
        import operator

        from predictionio_tpu.parallel.exchange import global_vocab, merge_keyed

        counts = merge_keyed(counts, combine=operator.add)
        user_index = BiMap.string_index(global_vocab(u for u, _ in counts))
        item_index = BiMap.string_index(
            global_vocab(list(i for _, i in counts) + list(categories))
        )
        n = len(counts)
        rows = np.fromiter((user_index[u] for u, _ in counts), np.int64, n)
        cols = np.fromiter((item_index[i] for _, i in counts), np.int64, n)
        vals = np.fromiter(counts.values(), np.float32, n)
        return TrainingData(rows, cols, vals, user_index, item_index, categories)


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = 3
    json_aliases = {"numIterations": "num_iterations", "lambda": "lambda_"}


@dataclasses.dataclass
class SimilarProductModel:
    item_factors: Any  # [I, K], L2-normalized rows for cosine scoring
    item_index: BiMap
    categories: dict


class ALSAlgorithm(JaxAlgorithm):
    params_class = ALSAlgorithmParams
    query_class = Query

    def __init__(self, params: ALSAlgorithmParams):
        super().__init__(params)

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> SimilarProductModel:
        p = self.params
        factors = train_als(
            pd.rows, pd.cols, pd.vals,
            num_users=len(pd.user_index), num_items=len(pd.item_index),
            config=ALSConfig(
                rank=p.rank, iterations=p.num_iterations, reg=p.lambda_,
                implicit=True, alpha=p.alpha, seed=0 if p.seed is None else p.seed,
            ),
            mesh=ctx.mesh,
        )
        item = np.asarray(factors.item)
        norms = np.linalg.norm(item, axis=1, keepdims=True)
        norms[norms == 0] = 1.0
        return SimilarProductModel(
            item_factors=item / norms,
            item_index=pd.item_index,
            categories=pd.categories,
        )

    # --------------------------------------------------- ANN retrieval
    def build_ann_for_serving(
        self, model: SimilarProductModel, ann
    ) -> tuple[SimilarProductModel, dict]:
        """``--ann`` retrieval tier: IVF over the L2-normalized item
        factors (cosine scoring == inner product on unit rows, so the
        clustered layout is exactly the metric the queries use)."""
        from predictionio_tpu.ops import ivf

        index, info = ivf.build_ivf(
            np.asarray(model.item_factors),
            nlist=ann.nlist, seed=ann.seed, iters=ann.kmeans_iters,
        )
        model._pio_ann = ivf.AnnRuntime(index, ann.nprobe, info)
        info = dict(info, algorithm=type(self).__name__,
                    nprobe=model._pio_ann.nprobe)
        return model, info

    def release_ann_state(self, model: SimilarProductModel) -> None:
        if getattr(model, "_pio_ann", None) is not None:
            model._pio_ann = None

    def predict(self, model: SimilarProductModel, query: Query) -> PredictedResult:
        idxs = [model.item_index.get(i) for i in query.items]
        idxs = [i for i in idxs if i is not None]
        if not idxs:
            return PredictedResult(())
        target = model.item_factors[idxs].sum(axis=0)
        norm = np.linalg.norm(target)
        if norm == 0:
            return PredictedResult(())
        ann = getattr(model, "_pio_ann", None)
        if ann is not None and not query.white_list and not query.categories:
            # ANN path. Exclusions (query items + blacklist) are applied
            # by OVER-FETCHING num + |excluded| candidates before the
            # final merge: a post-hoc filter on an exact-num fetch
            # returns fewer than num items whenever the excluded items
            # are popular (high-scoring) — the latent hole approximate
            # retrieval amplifies. whiteList/categories queries fall
            # back to the exact masked path: a whitelisted item may live
            # in a cluster the probe never visits, so ANN cannot honor
            # those filters (docs/serving.md).
            from predictionio_tpu.ops import ivf

            num = int(query.num)
            if num <= 0:  # exact-path parity: k = min(num, ...) <= 0
                return PredictedResult(())
            exclude = set(idxs)
            for item in query.black_list or ():
                bidx = model.item_index.get(item)
                if bidx is not None:
                    exclude.add(bidx)
            ids, scores = ivf.query_topk(
                ann, target / norm, num + len(exclude)
            )
            return PredictedResult(
                tuple(
                    ItemScore(item=model.item_index.inverse(int(i)), score=float(s))
                    for i, s in zip(ids, scores)
                    if i not in exclude
                )[:num]
            )
        scores = model.item_factors @ (target / norm)  # cosine vs all items
        allowed = self._allowed_mask(model, query, exclude=set(idxs))
        scores = np.where(allowed, scores, -np.inf)
        k = min(int(query.num), int(allowed.sum()))
        if k <= 0:
            return PredictedResult(())
        from predictionio_tpu.ops.topk import top_k_host

        top, _ = top_k_host(scores, k)  # shared tie rule (ops/topk.py)
        return PredictedResult(
            tuple(
                ItemScore(item=model.item_index.inverse(int(i)), score=float(scores[i]))
                for i in top
                if np.isfinite(scores[i])
            )
        )

    @staticmethod
    def _allowed_mask(model: SimilarProductModel, query: Query, exclude: set) -> np.ndarray:
        n = model.item_factors.shape[0]
        allowed = np.ones(n, dtype=bool)
        for i in exclude:
            allowed[i] = False
        if query.white_list:
            allowed &= np.zeros(n, dtype=bool) | np.isin(
                np.arange(n),
                [model.item_index.get(i, -1) for i in query.white_list],
            )
        if query.black_list:
            for item in query.black_list:
                idx = model.item_index.get(item)
                if idx is not None:
                    allowed[idx] = False
        if query.categories:
            wanted = set(query.categories)
            for idx in np.nonzero(allowed)[0]:
                cats = model.categories.get(model.item_index.inverse(int(idx)), ())
                if not wanted.intersection(cats):
                    allowed[idx] = False
        return allowed


def engine_factory() -> Engine:
    return Engine(
        datasource_class=SimilarProductDataSource,
        preparator_class=IdentityPreparator,
        algorithms_class_map={"als": ALSAlgorithm},
        serving_class=FirstServing,
    )
