"""Text-Classification engine template (TF-IDF + LR / NB).

Capability parity with the reference's text-classification template:
``$set`` content events carrying text + category -> hashing TF-IDF ->
logistic-regression (or NB) classifier -> text queries.
"""

from predictionio_tpu.templates.textclassification.engine import (
    DataSourceParams,
    LRTextAlgorithm,
    LRTextParams,
    NBTextAlgorithm,
    NBTextParams,
    TextDataSource,
    engine_factory,
)

__all__ = [
    "DataSourceParams",
    "LRTextAlgorithm",
    "LRTextParams",
    "NBTextAlgorithm",
    "NBTextParams",
    "TextDataSource",
    "engine_factory",
]
