"""Text-Classification engine: text events -> TF-IDF -> LR/NB -> category.

Parity map (reference text-classification template):

* ``DataSource.scala`` — labeled text observations from ``$set`` events
  (+ an optional stopwords entity) -> :class:`TextDataSource`.
* ``Preparator.scala`` (``HashingTF``/``IDF``) -> the preparator here
  fits :func:`predictionio_tpu.ops.text.fit_tfidf` and vectorizes.
* ``NBAlgorithm.scala`` / ``LRAlgorithm.scala`` -> :class:`NBTextAlgorithm`
  / :class:`LRTextAlgorithm`.
* Query ``{"text": "..."}`` -> ``{"category": "...", "confidence": p}``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    JaxAlgorithm,
    Params,
    Preparator,
    SanityCheck,
    WorkflowContext,
)
from predictionio_tpu.data.aggregator import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.classify import (
    logreg_predict_proba,
    nb_predict_log_proba,
    train_logreg,
    train_naive_bayes,
)
from predictionio_tpu.ops.text import HashingTfIdf, fit_tfidf

__all__ = [
    "DataSourceParams",
    "TextDataSource",
    "TfIdfPreparator",
    "PreparatorParams",
    "NBTextParams",
    "NBTextAlgorithm",
    "LRTextParams",
    "LRTextAlgorithm",
    "PredictedResult",
    "engine_factory",
]


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    category: str
    confidence: float

    def to_json(self) -> dict[str, Any]:
        return {"category": self.category, "confidence": self.confidence}


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    entity_type: str = "content"
    text_property: str = "text"
    label_property: str = "category"
    eval_k: int = 3
    json_aliases = {
        "appName": "app_name",
        "entityType": "entity_type",
        "evalK": "eval_k",
    }


@dataclasses.dataclass
class TextTrainingData(SanityCheck):
    texts: list
    labels: list

    def sanity_check(self) -> None:
        if not self.texts:
            raise ValueError("No labeled text found — check appName/entityType")


class TextDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_rows(self, ctx: WorkflowContext) -> TextTrainingData:
        p = self.params
        props = PEventStore.aggregate_properties(
            app_name=p.app_name,
            entity_type=p.entity_type,
            required=[p.text_property, p.label_property],
        )
        texts, labels = [], []
        for _eid, pm in sorted(props.items()):
            texts.append(str(pm[p.text_property]))
            labels.append(str(pm[p.label_property]))
        return TextTrainingData(texts, labels)

    def read_training(self, ctx: WorkflowContext) -> TextTrainingData:
        return self._read_rows(ctx)

    def read_eval(self, ctx: WorkflowContext):
        td = self._read_rows(ctx)
        k = max(2, self.params.eval_k)
        folds = []
        for fold in range(k):
            tr_t = [t for i, t in enumerate(td.texts) if i % k != fold]
            tr_l = [l for i, l in enumerate(td.labels) if i % k != fold]
            qa = [
                ({"text": t}, l)
                for i, (t, l) in enumerate(zip(td.texts, td.labels))
                if i % k == fold
            ]
            folds.append((TextTrainingData(tr_t, tr_l), {"fold": fold}, qa))
        return folds


@dataclasses.dataclass(frozen=True)
class PreparatorParams(Params):
    num_features: int = 4096
    stopwords: tuple = ()
    json_aliases = {"numFeatures": "num_features"}


@dataclasses.dataclass
class PreparedTextData:
    x: np.ndarray  # [N, F] tf-idf
    y: np.ndarray  # [N]
    label_index: BiMap
    featurizer: HashingTfIdf


class TfIdfPreparator(Preparator):
    """Fits TF-IDF on the corpus and vectorizes
    (parity: the template's Preparator with HashingTF/IDF)."""

    params_class = PreparatorParams

    def __init__(self, params: PreparatorParams):
        super().__init__(params)

    def prepare(self, ctx: WorkflowContext, td: TextTrainingData) -> PreparedTextData:
        featurizer = fit_tfidf(
            td.texts,
            num_features=self.params.num_features,
            stopwords=self.params.stopwords,
        )
        label_index = BiMap.string_index(td.labels)
        x = featurizer.transform(td.texts)
        y = np.fromiter((label_index[l] for l in td.labels), np.int64, len(td.labels))
        return PreparedTextData(x, y, label_index, featurizer)


class _TextAlgoBase(JaxAlgorithm):
    def _query_text(self, query: Mapping[str, Any]) -> str:
        if not isinstance(query, Mapping) or "text" not in query:
            raise ValueError('Query must be {"text": "..."}')
        return str(query["text"])


@dataclasses.dataclass(frozen=True)
class NBTextParams(Params):
    lambda_: float = 1.0
    json_aliases = {"lambda": "lambda_"}


class NBTextAlgorithm(_TextAlgoBase):
    params_class = NBTextParams

    def __init__(self, params: NBTextParams):
        super().__init__(params)

    def train(self, ctx: WorkflowContext, pd: PreparedTextData):
        nb = train_naive_bayes(
            pd.x, pd.y, num_classes=len(pd.label_index), smoothing=self.params.lambda_
        )
        return {"nb": nb, "label_index": pd.label_index, "featurizer": pd.featurizer}

    def predict(self, model, query) -> PredictedResult:
        x = model["featurizer"].transform([self._query_text(query)])
        logp = np.asarray(nb_predict_log_proba(model["nb"], jnp.asarray(x)))[0]
        p = np.exp(logp - logp.max())
        p /= p.sum()
        idx = int(np.argmax(p))
        return PredictedResult(model["label_index"].inverse(idx), float(p[idx]))


@dataclasses.dataclass(frozen=True)
class LRTextParams(Params):
    iterations: int = 300
    step_size: float = 1.0
    reg: float = 1e-4
    json_aliases = {"stepSize": "step_size"}


class LRTextAlgorithm(_TextAlgoBase):
    params_class = LRTextParams

    def __init__(self, params: LRTextParams):
        super().__init__(params)

    def train(self, ctx: WorkflowContext, pd: PreparedTextData):
        lr = train_logreg(
            pd.x, pd.y, num_classes=len(pd.label_index),
            iterations=self.params.iterations, lr=self.params.step_size,
            reg=self.params.reg,
        )
        return {"lr": lr, "label_index": pd.label_index, "featurizer": pd.featurizer}

    def predict(self, model, query) -> PredictedResult:
        x = model["featurizer"].transform([self._query_text(query)])
        proba = np.asarray(logreg_predict_proba(model["lr"], jnp.asarray(x)))[0]
        idx = int(np.argmax(proba))
        return PredictedResult(model["label_index"].inverse(idx), float(proba[idx]))


def engine_factory() -> Engine:
    return Engine(
        datasource_class=TextDataSource,
        preparator_class=TfIdfPreparator,
        algorithms_class_map={"nb": NBTextAlgorithm, "lr": LRTextAlgorithm},
        serving_class=FirstServing,
    )
