from predictionio_tpu.templates.twotower.engine import engine_factory

__all__ = ["engine_factory"]
