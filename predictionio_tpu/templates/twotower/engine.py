"""Two-Tower retrieval engine: interaction events -> sharded-embedding
towers -> personalized top-N queries.

The DLRM/two-tower stretch family (BASELINE.md configs[4]). No reference
counterpart exists — PredictionIO ships no deep-retrieval template — so
this is parity-plus built on the framework's standard DASE shape:

* DataSource — implicit interaction pairs from the event store (any of
  ``eventNames``), with the same multi-host coherence recipe as the
  other templates (merge counts by key, global sorted vocabularies).
* Algorithm — :func:`predictionio_tpu.ops.twotower.train_two_tower`:
  embedding tables sharded over the mesh's ``model`` axis (the
  shard-local-gather + psum lookup shared with the ALS sweep),
  in-batch sampled-softmax, optax adam.
* Serving — cosine top-N from the L2-normalized tower outputs with the
  usual seen-item filter; same Query/PredictedResult wire shapes as the
  Recommendation template, so SDK clients need no changes.

engine.json::

    {"engineFactory": "predictionio_tpu.templates.twotower:engine_factory",
     "datasource": {"params": {"appName": "myapp",
                               "eventNames": ["view", "buy"]}},
     "algorithms": [{"name": "twotower",
                     "params": {"embeddingDim": 64, "batchSize": 512,
                                "epochs": 5, "learningRate": 0.05}}]}
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    JaxAlgorithm,
    OptionAverageMetric,
    Params,
    SanityCheck,
    WorkflowContext,
)
from predictionio_tpu.data.aggregator import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.twotower import TwoTowerConfig, train_two_tower

__all__ = [
    "DataSourceParams",
    "TrainingData",
    "TwoTowerDataSource",
    "TwoTowerParams",
    "TwoTowerAlgorithm",
    "Query",
    "PredictedResult",
    "ItemScore",
    "engine_factory",
]


# ------------------------------------------------------------------- queries
@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float

    def to_json(self) -> dict:
        return {"item": self.item, "score": self.score}


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple = ()

    def to_json(self) -> dict:
        return {"itemScores": [s.to_json() for s in self.item_scores]}


# --------------------------------------------------------------- data source
@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: Sequence[str] = ("view", "rate", "buy", "like")
    eval_k: int = 2
    json_aliases = {
        "appName": "app_name",
        "eventNames": "event_names",
        "evalK": "eval_k",
    }


@dataclasses.dataclass
class TrainingData(SanityCheck):
    rows: np.ndarray  # user idx, one entry per (user, item) pair
    cols: np.ndarray  # item idx
    user_index: BiMap
    item_index: BiMap
    seen: dict  # user id -> set of item ids (serving-time filter)

    def sanity_check(self) -> None:
        if self.rows.size == 0:
            raise ValueError("No interaction events found — check appName/eventNames")


class TwoTowerDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_pairs(self, ctx: WorkflowContext) -> list:
        """Sorted distinct (user, item) pairs — the GLOBAL set on every
        host. Training batches are replicated across a multi-host job and
        the saved model's seen-filter must cover every user, so a
        partitioned (per-host) merge would be incoherent; pairs are two
        ids each, small next to the raw events they dedup."""
        p = self.params
        if ctx.num_hosts == 1:
            # columnar fast path: dedup happens over code arrays, so the
            # remaining Python is O(distinct pairs), not O(events)
            from predictionio_tpu.templates.columnar_util import aggregate_pairs

            cols = PEventStore.find_columns(
                app_name=p.app_name, event_names=list(p.event_names)
            )
            u_sel, i_sel, _ = aggregate_pairs(cols)
            return sorted(
                zip(
                    cols.entity_vocab[u_sel].tolist(),
                    cols.target_vocab[i_sel].tolist(),
                )
            )
        pairs: dict[tuple[str, str], bool] = {}
        for e in PEventStore.find(
            app_name=p.app_name,
            event_names=list(p.event_names),
            shard_index=ctx.host_index,
            num_shards=ctx.num_hosts,
        ):
            if e.target_entity_id is None:
                continue
            pairs[(e.entity_id, e.target_entity_id)] = True
        if ctx.num_hosts > 1:
            from predictionio_tpu.parallel.exchange import allgather_objects

            merged = set()
            for contrib in allgather_objects(sorted(pairs)):
                merged.update(tuple(pr) for pr in contrib)
            return sorted(merged)
        return sorted(pairs)

    @staticmethod
    def _to_training_data(pairs: Sequence) -> TrainingData:
        user_index = BiMap.string_index(sorted({u for u, _ in pairs}))
        item_index = BiMap.string_index(sorted({i for _, i in pairs}))
        n = len(pairs)
        rows = np.fromiter((user_index[u] for u, _ in pairs), np.int64, n)
        cols = np.fromiter((item_index[i] for _, i in pairs), np.int64, n)
        seen: dict[str, set] = {}
        for u, i in pairs:
            seen.setdefault(u, set()).add(i)
        return TrainingData(rows, cols, user_index, item_index, seen)

    def _read_training_columnar(self, ctx: WorkflowContext) -> TrainingData:
        """Vectorized single-host read: columnar bulk scan + grouped pair
        dedup (in-batch softmax has no per-pair weight, so a distinct-
        pair set is the right shape) — no per-event Python. The seen-
        filter dict is built from the (much smaller) deduped pair set."""
        from predictionio_tpu.templates.columnar_util import (
            aggregate_pairs,
            densify_pairs,
        )

        p = self.params
        cols = PEventStore.find_columns(
            app_name=p.app_name, event_names=list(p.event_names)
        )
        u_sel, i_sel, _counts = aggregate_pairs(cols)
        rows, cols_idx, user_vocab, item_vocab = densify_pairs(
            cols, u_sel, i_sel
        )
        user_index = BiMap.string_index(user_vocab)
        item_index = BiMap.string_index(item_vocab)
        seen: dict[str, set] = {}
        for r, c in zip(rows.tolist(), cols_idx.tolist()):
            seen.setdefault(user_vocab[r], set()).add(item_vocab[c])
        return TrainingData(rows, cols_idx, user_index, item_index, seen)

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        # training consumes distinct (user, item) PAIRS — in-batch softmax
        # has no per-pair weight, so a set (not counts) is the right shape
        if ctx.num_hosts == 1:
            return self._read_training_columnar(ctx)
        return self._to_training_data(self._read_pairs(ctx))

    def read_eval(self, ctx: WorkflowContext):
        """K-fold split by stable hash of (user, item): train on k-1
        folds, query each user with held-out interactions for the full
        ranking, actual = the held-out item ids (consumed by
        :class:`RecallAtK`). Mirrors the Recommendation template's
        ``readEval`` shape."""
        import zlib

        pairs = self._read_pairs(ctx)
        k = max(2, self.params.eval_k)

        def fold_of(u: str, i: str) -> int:
            return zlib.crc32(f"{u}\x00{i}".encode()) % k

        folds = []
        for fold in range(k):
            train = [pr for pr in pairs if fold_of(*pr) != fold]
            held = [pr for pr in pairs if fold_of(*pr) == fold]
            td = self._to_training_data(train)
            by_user: dict[str, list] = {}
            for u, i in held:
                # only users the fold's model knows can be queried
                if u in td.user_index:
                    by_user.setdefault(u, []).append(i)
            num_items = len(td.item_index)
            qa = [
                (Query(user=u, num=num_items), tuple(items))
                for u, items in by_user.items()
                if items
            ]
            folds.append((td, {"fold": fold}, qa))
        return folds


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class TwoTowerParams(Params):
    embedding_dim: int = 32
    batch_size: int = 256
    epochs: int = 5
    learning_rate: float = 0.05
    temperature: float = 0.1
    seed: int = 0
    #: rank the catalog on the accelerator (huge catalogs / batched
    #: queries); guarded by the same deploy-time latency probe as the
    #: ALS template
    serve_on_device: bool = False
    device_latency_budget_ms: float = 10.0
    #: "bfloat16" (TPU-native default) or "float32" for bit-for-bit runs
    gemm_dtype: str = "bfloat16"
    #: fused softmax-CE kernel: "auto" | "off" | "interpret" (see
    #: ops/fused_ce.py) — the opt-out if the Pallas path misbehaves
    fused_ce: str = "auto"
    json_aliases = {
        "embeddingDim": "embedding_dim",
        "batchSize": "batch_size",
        "learningRate": "learning_rate",
        "serveOnDevice": "serve_on_device",
        "deviceLatencyBudgetMs": "device_latency_budget_ms",
        "gemmDtype": "gemm_dtype",
        "fusedCe": "fused_ce",
    }


@dataclasses.dataclass
class TwoTowerServingModel:
    user_vecs: Any  # [U, D] L2-normalized
    item_vecs: Any  # [I, D] L2-normalized
    user_index: BiMap
    item_index: BiMap
    seen: dict
    loss_history: tuple = ()


class TwoTowerAlgorithm(JaxAlgorithm):
    params_class = TwoTowerParams
    query_class = Query

    def __init__(self, params: TwoTowerParams):
        super().__init__(params)

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> TwoTowerServingModel:
        p = self.params
        init_user = init_item = None
        warm = ctx.warm_model
        if isinstance(warm, TwoTowerServingModel):
            # same carry-over as the ALS template: entities present in
            # both catalogs keep their embeddings; NEW ones draw the
            # tower's own signed-normal cold init (not ALS's abs draw)
            from predictionio_tpu.templates.serving_util import (
                aligned_factor_init,
            )

            def fresh(rng, shape):
                return rng.standard_normal(shape) / np.sqrt(shape[1])

            init_user, n_u = aligned_factor_init(
                warm.user_vecs, warm.user_index, pd.user_index,
                p.embedding_dim, p.seed, fresh=fresh,
            )
            init_item, n_i = aligned_factor_init(
                warm.item_vecs, warm.item_index, pd.item_index,
                p.embedding_dim, p.seed + 1, fresh=fresh,
            )
            logging.getLogger(__name__).info(
                "Warm start: carried %d/%d user and %d/%d item embeddings",
                n_u, len(pd.user_index), n_i, len(pd.item_index),
            )
        model = train_two_tower(
            pd.rows,
            pd.cols,
            num_users=len(pd.user_index),
            num_items=len(pd.item_index),
            config=TwoTowerConfig(
                dim=p.embedding_dim,
                batch_size=p.batch_size,
                epochs=p.epochs,
                learning_rate=p.learning_rate,
                temperature=p.temperature,
                seed=p.seed,
                gemm_dtype=p.gemm_dtype,
                fused_ce=p.fused_ce,
            ),
            mesh=ctx.mesh,
            init_user=init_user,
            init_item=init_item,
        )
        return TwoTowerServingModel(
            user_vecs=model.user_vecs,
            item_vecs=model.item_vecs,
            user_index=pd.user_index,
            item_index=pd.item_index,
            seen=pd.seen,
            loss_history=model.loss_history,
        )

    def prepare_model_for_serving(
        self, model: TwoTowerServingModel
    ) -> TwoTowerServingModel:
        if self.params.serve_on_device:
            import jax

            from predictionio_tpu.templates.serving_util import device_latency_ok

            model.user_vecs = jax.device_put(np.asarray(model.user_vecs))
            model.item_vecs = jax.device_put(np.asarray(model.item_vecs))
            if len(model.user_index):
                probe = Query(user=model.user_index.keys()[0], num=4)
                if not device_latency_ok(
                    lambda: self.predict(model, probe),
                    self.params.device_latency_budget_ms,
                ):
                    model.user_vecs = np.asarray(model.user_vecs)
                    model.item_vecs = np.asarray(model.item_vecs)
            return model
        model.user_vecs = np.ascontiguousarray(model.user_vecs)
        model.item_vecs = np.ascontiguousarray(model.item_vecs)
        if len(model.user_index):
            self.predict(model, Query(user=model.user_index.keys()[0], num=4))
        return model

    # ------------------------------------------------------ pinned serving
    def pin_model_for_serving(
        self, model: TwoTowerServingModel
    ) -> tuple[TwoTowerServingModel, int]:
        """``--pin-model`` cache tier (workflow/device_state.py): same
        contract as the recommendation template — tower matrices are
        ``device_put`` once per model generation, predictions flip onto
        the jitted device path, and the pinned bytes surface on
        ``/stats.json``."""
        import jax

        user = model.user_vecs
        item = model.item_vecs
        if isinstance(user, np.ndarray):
            user = jax.device_put(user)
        if isinstance(item, np.ndarray):
            item = jax.device_put(item)
        model.user_vecs = user
        model.item_vecs = item
        model._pio_pinned = True
        nbytes = int(user.size) * user.dtype.itemsize
        nbytes += int(item.size) * item.dtype.itemsize
        model._pio_bytes_by_dtype = {"float32": nbytes}
        return model, nbytes

    # ------------------------------------------------------ sharded serving
    def shard_model_for_serving(
        self, model: TwoTowerServingModel
    ) -> tuple[TwoTowerServingModel, int]:
        """``--shard-factors`` tier: same contract as the recommendation
        template — tower matrices shard row-wise over a one-axis model
        mesh (each device holds ``rows/S``), retrieval routes through
        the tie-stable shard_map kernel, single-device hosts fall back
        to plain pinning."""
        from predictionio_tpu.parallel import sharding

        mesh = sharding.serving_mesh()
        if mesh is None:
            logging.getLogger(__name__).warning(
                "--shard-factors requested but only one device is "
                "visible; falling back to --pin-model replication"
            )
            return self.pin_model_for_serving(model)
        user = sharding.shard_table(np.asarray(model.user_vecs), mesh)
        item = sharding.shard_table(np.asarray(model.item_vecs), mesh)
        info = sharding.ShardInfo(
            mesh=mesh,
            rows={
                "user": int(np.asarray(model.user_vecs).shape[0]),
                "item": int(np.asarray(model.item_vecs).shape[0]),
            },
        )
        model.user_vecs = user
        model.item_vecs = item
        model._pio_shards = info
        model._pio_pinned = True
        nbytes = int(user.size) * user.dtype.itemsize
        nbytes += int(item.size) * item.dtype.itemsize
        model._pio_bytes_by_dtype = {"float32": nbytes}
        return model, nbytes

    # ---------------------------------------------------- quantized serving
    def quantize_model_for_serving(
        self, model: TwoTowerServingModel, mode: str = "int8",
        shard: bool = False,
    ) -> tuple[TwoTowerServingModel, int]:
        """``--quantize int8`` tier: same contract as the recommendation
        template — tower matrices pin as int8 codes + per-row scales,
        retrieval runs the recall-guarded two-stage kernel, and
        ``shard=True`` shards codes and scales over the model mesh so
        the memory tiers compose multiplicatively."""
        from predictionio_tpu.ops import quant

        user_f = np.asarray(model.user_vecs, np.float32)
        item_f = np.asarray(model.item_vecs, np.float32)
        mesh = None
        if shard:
            from predictionio_tpu.parallel import sharding

            mesh = sharding.serving_mesh()
            if mesh is None:
                logging.getLogger(__name__).warning(
                    "--shard-factors requested but only one device is "
                    "visible; quantized tables pin replicated"
                )
        if mesh is not None:
            from predictionio_tpu.parallel import sharding

            user = sharding.shard_quantized_table(user_f, mesh)
            item = sharding.shard_quantized_table(item_f, mesh)
            model._pio_shards = sharding.ShardInfo(
                mesh=mesh,
                rows={
                    "user": int(user_f.shape[0]),
                    "item": int(item_f.shape[0]),
                },
            )
        else:
            user = quant.quantize_table(user_f)
            item = quant.quantize_table(item_f)
        model.user_vecs = user
        model.item_vecs = item
        model._pio_pinned = True
        breakdown = {
            "int8": user.nbytes_codes + item.nbytes_codes,
            "scalesFloat32": user.nbytes_scales + item.nbytes_scales,
        }
        model._pio_bytes_by_dtype = breakdown
        model._pio_quant = quant.QuantRuntime(
            mode=mode,
            bytes_by_dtype=breakdown,
            bytes_f32=user_f.nbytes + item_f.nbytes,
            error=quant.quantization_error(
                item_f,
                np.asarray(item.codes)[: item_f.shape[0]],
                np.asarray(item.scales)[: item_f.shape[0]],
            ),
        )
        return model, sum(breakdown.values())

    def release_pinned_model(self, model: TwoTowerServingModel) -> None:
        shards = getattr(model, "_pio_shards", None)
        quantized = getattr(model, "_pio_quant", None) is not None
        # the AOT runtime is lowered against this generation's tower
        # shapes — it retires with the pinned buffers
        if getattr(model, "_pio_aot", None) is not None:
            model._pio_aot = None
        if shards is not None:
            # every device's shard handles die here, and the host copy
            # strips the even-shard padding rows (np.asarray dequantizes
            # a --quantize table back to f32)
            model.user_vecs = np.asarray(model.user_vecs)[
                : shards.rows["user"]
            ]
            model.item_vecs = np.asarray(model.item_vecs)[
                : shards.rows["item"]
            ]
            model._pio_shards = None
            model._pio_pinned = False
            model._pio_quant = None
            return
        if getattr(model, "_pio_pinned", False) or quantized:
            model.user_vecs = np.asarray(model.user_vecs)
            model.item_vecs = np.asarray(model.item_vecs)
            model._pio_pinned = False
            model._pio_quant = None

    # --------------------------------------------------- AOT serving export
    def aot_export_for_serving(
        self, model: TwoTowerServingModel, buckets: list
    ) -> dict:
        """``--aot`` tier (workflow/aot.py): same contract as the
        recommendation template — serialize the pinned exact serving
        programs (k-independent ``predict_scores`` + per-bucket top-k,
        plus the chunked batch GEMM) so replicas deserialize at boot
        instead of tracing; the two-program split keeps results
        bit-identical to the jitted path by construction. Sharded and
        quantized generations export nothing (their kernels close over
        live runtime objects)."""
        if getattr(model, "_pio_shards", None) is not None:
            return {}
        if getattr(model, "_pio_quant", None) is not None:
            return {}
        import jax
        from jax import export as jax_export

        from predictionio_tpu.ops.als import predict_scores, top_k_items_batch
        from predictionio_tpu.ops.topk import top_k_scores
        from predictionio_tpu.templates.serving_util import TOPK_CHUNK

        n_users, rank = (int(d) for d in model.user_vecs.shape)
        n_items = int(model.item_vecs.shape[0])
        f32 = np.dtype(np.float32)
        vec = jax.ShapeDtypeStruct((rank,), f32)
        users = jax.ShapeDtypeStruct((n_users, rank), f32)
        items = jax.ShapeDtypeStruct((n_items, rank), f32)
        idx_chunk = jax.ShapeDtypeStruct((TOPK_CHUNK,), np.dtype(np.int32))
        out = {"predict_scores": jax_export.export(predict_scores)(vec, items)}
        for kb in buckets:
            out[f"top_k_scores_b{kb}"] = jax_export.export(
                jax.jit(lambda s, _k=kb: top_k_scores(s, _k))
            )(jax.ShapeDtypeStruct((n_items,), f32))
            out[f"top_k_items_batch_c{TOPK_CHUNK}_b{kb}"] = jax_export.export(
                jax.jit(
                    lambda u, um, im, _k=kb: top_k_items_batch(u, um, im, _k)
                )
            )(idx_chunk, users, items)
        return out

    def aot_warm_serving(self, model: TwoTowerServingModel) -> None:
        """Warm the pinned predict path's eager GLUE at boot: the
        ``user_vecs[uidx]`` row gather (dynamic_slice + squeeze) is
        index-operand cached by jax, so one call here compiles the
        executables every user's query will reuse (see the
        recommendation template's twin)."""
        if getattr(model, "_pio_pinned", False):
            _ = model.user_vecs[0]
    def build_ann_for_serving(
        self, model: TwoTowerServingModel, ann
    ) -> tuple[TwoTowerServingModel, dict]:
        """``--ann`` retrieval tier (workflow/device_state.py): IVF over
        the L2-normalized item-tower embeddings; serving scores only
        ``nprobe`` cluster slabs per query. The seen-item filter keeps
        its over-fetch (num + |seen| candidates fetched BEFORE the
        merge), so ANN answers still hold ``num`` unseen items whenever
        the probed clusters do."""
        from predictionio_tpu.ops import ivf

        shards = getattr(model, "_pio_shards", None)
        items = np.asarray(model.item_vecs)  # dequantizes under --quantize
        if shards is not None:
            items = items[: shards.rows["item"]]
        index, info = ivf.build_ivf(
            items,
            nlist=ann.nlist, seed=ann.seed, iters=ann.kmeans_iters,
            quantize=getattr(model, "_pio_quant", None) is not None,
        )
        model._pio_ann = ivf.AnnRuntime(index, ann.nprobe, info)
        if shards is not None:
            info = dict(info, **ivf.shard_runtime(model._pio_ann, shards.mesh))
        info = dict(info, algorithm=type(self).__name__,
                    nprobe=model._pio_ann.nprobe)
        return model, info

    def release_ann_state(self, model: TwoTowerServingModel) -> None:
        if getattr(model, "_pio_ann", None) is not None:
            model._pio_ann = None

    # ----------------------------------------------- online streaming SGD
    def online_trainer_spec(self, model: TwoTowerServingModel) -> dict:
        """Opt into the streaming mini-batch trainer (``pio deploy
        --online``; online/trainer.py): towers have no closed-form
        fold-in, so their online path is small SGD steps on fresh pairs
        with the SAME in-batch softmax objective training uses."""
        p = self.params
        return {
            "learning_rate": p.learning_rate,
            "temperature": p.temperature,
            "seed": p.seed,
        }

    def apply_online_update(self, model: TwoTowerServingModel, upd) -> dict:
        """Swap streamed rows into the live towers — called under the
        query service's generation lock (row scatters only; the SGD ran
        on the trainer thread). Also grows the serving-time seen-item
        filter with the folded pairs so fresh interactions filter out of
        recommendations immediately, coherent with the row updates."""
        from predictionio_tpu.workflow import device_state

        info = {"usersUpdated": 0, "itemsUpdated": 0,
                "usersAdded": 0, "itemsAdded": 0}
        if upd.user_ids:
            info["usersUpdated"], info["usersAdded"] = (
                device_state.swap_side_rows(
                    model, upd.user_ids, upd.user_rows,
                    "user_vecs", "user_index", rows_before_index=True,
                )
            )
        if upd.item_ids:
            info["itemsUpdated"], info["itemsAdded"] = (
                device_state.swap_side_rows(
                    model, upd.item_ids, upd.item_rows,
                    "item_vecs", "item_index", rows_before_index=False,
                )
            )
            ann_info = device_state.update_ann_items(
                model, upd.item_ids, upd.item_rows
            )
            if ann_info is not None:
                info["ann"] = ann_info
        for u, i in upd.seen_pairs:
            # copy-on-write per user: a reader iterating the old set must
            # never observe a concurrent mutation
            model.seen[u] = set(model.seen.get(u, ())) | {i}
        return info

    def batch_predict(
        self, model: TwoTowerServingModel, queries
    ) -> list[tuple[int, PredictedResult]]:
        """Batch-amortized retrieval (same chunked-GEMM core as the ALS
        template — `pio batchpredict` and eval sweeps go through here
        instead of one GEMV/dispatch per query). Seen-item filtering
        matches :meth:`predict`: fetch ``num + len(seen)`` candidates,
        then drop seen ones host-side."""
        from predictionio_tpu.templates.serving_util import chunked_topk

        n_items = len(model.item_index)
        results: list[tuple[int, PredictedResult]] = []
        valid: list[tuple[int, int, int]] = []
        seen_by_slot: dict[int, tuple] = {}
        nums: dict[int, int] = {}
        for idx, q in queries:
            uidx = model.user_index.get(q.user)
            num = int(q.num)
            if uidx is None or num <= 0:
                results.append((idx, PredictedResult(())))
                continue
            seen = model.seen.get(q.user, ())
            k = min(num + len(seen), n_items)
            if k <= 0:
                results.append((idx, PredictedResult(())))
                continue
            seen_by_slot[idx] = seen
            nums[idx] = num
            valid.append((idx, uidx, k))
        inverse = model.item_index.inverse
        for part, idx_l, score_l in chunked_topk(
            model.user_vecs, model.item_vecs, valid,
            ann=getattr(model, "_pio_ann", None),
            shards=getattr(model, "_pio_shards", None),
            quant=getattr(model, "_pio_quant", None),
            aot=getattr(model, "_pio_aot", None),
        ):
            for (oi, _, k), ids, scs in zip(part, idx_l, score_l):
                seen = seen_by_slot[oi]
                num = nums[oi]
                out = []
                for i, s in zip(ids[:k], scs[:k]):
                    item = inverse(i)
                    if item in seen:
                        continue
                    out.append(ItemScore(item=item, score=s))
                    if len(out) >= num:
                        break
                results.append((oi, PredictedResult(tuple(out))))
        return results

    def predict(self, model: TwoTowerServingModel, query: Query) -> PredictedResult:
        uidx = model.user_index.get(query.user)
        if uidx is None or int(query.num) <= 0:
            return PredictedResult(())
        seen = model.seen.get(query.user, ())
        # over-fetch num + |seen| BEFORE the top-K so the post-hoc seen
        # filter still leaves num items (applies to the exact and ANN
        # paths alike)
        k = min(int(query.num) + len(seen), len(model.item_index))
        if k <= 0:
            return PredictedResult(())
        ann = getattr(model, "_pio_ann", None)
        shards = getattr(model, "_pio_shards", None)
        quantrt = getattr(model, "_pio_quant", None)
        if ann is not None:
            from predictionio_tpu.ops import ivf

            if quantrt is not None:
                qvec = np.asarray(
                    model.user_vecs[np.asarray([uidx], np.int64)]
                )[0]
            elif shards is not None:
                from predictionio_tpu.parallel import sharding

                qvec = np.asarray(
                    sharding.gather_rows(
                        np.asarray([uidx], np.int32),
                        model.user_vecs, shards.mesh,
                    )
                )[0]
            else:
                qvec = np.asarray(model.user_vecs[uidx])
            ids, sc = ivf.query_topk(ann, qvec, k)
            pairs = list(zip(ids, sc))
        elif quantrt is not None:
            from predictionio_tpu.ops import quant

            ids_b, sc_b = quant.topk_users(
                quantrt, model.user_vecs, model.item_vecs, [uidx], k,
                shards=shards,
            )
            pairs = [(int(i), float(s)) for i, s in zip(ids_b[0], sc_b[0])]
        elif shards is not None:
            from predictionio_tpu.parallel import sharding

            ids_b, sc_b = sharding.topk_users(
                shards, model.user_vecs, model.item_vecs, [uidx], k
            )
            pairs = [(int(i), float(s)) for i, s in zip(ids_b[0], sc_b[0])]
        elif isinstance(model.item_vecs, np.ndarray):
            from predictionio_tpu.ops.topk import top_k_host

            scores = model.item_vecs @ np.asarray(model.user_vecs[uidx])
            # shared tie rule — descending score, ascending item index
            # (ops/topk.py), so host and device paths agree
            top, vals = top_k_host(scores, k)
            pairs = [(int(i), float(s)) for i, s in zip(top, vals)]
        else:
            # k buckets to a power of two (floor 16) so the jitted
            # selection compiles once per bucket — raw query.num would
            # key the jit cache at request cardinality (piolint PIO306).
            # Scoring runs in the k-independent predict_scores program so
            # GEMV rounding (and tie order vs the host path) cannot
            # drift with the chosen bucket
            from predictionio_tpu.ops.als import predict_scores
            from predictionio_tpu.ops.topk import bucket_k, top_k_scores

            kb = bucket_k(k, int(model.item_vecs.shape[0]))
            idx = sc = None
            aot = getattr(model, "_pio_aot", None)
            if aot is not None:
                # --aot tier 1: same two programs, deserialized at boot;
                # call-time failure disables the key and the jitted path
                # takes over on the next dispatch
                score_fn = aot.get("predict_scores")
                topk_fn = aot.get(f"top_k_scores_b{kb}")
                if score_fn is not None and topk_fn is not None:
                    try:
                        dev_scores = score_fn(
                            model.user_vecs[uidx], model.item_vecs
                        )
                        idx, sc = topk_fn(dev_scores)
                    except Exception as e:  # noqa: BLE001 - degrade, don't 500
                        aot.disable("predict_scores", str(e))
                        aot.disable(f"top_k_scores_b{kb}", str(e))
                        idx = sc = None
            if idx is None:
                dev_scores = predict_scores(
                    model.user_vecs[uidx], model.item_vecs
                )
                idx, sc = top_k_scores(dev_scores, kb)
            pairs = [
                (int(i), float(s))
                for i, s in zip(np.asarray(idx)[:k], np.asarray(sc)[:k])
            ]
        out = []
        for i, score in pairs:
            item = model.item_index.inverse(i)
            if item in seen:
                continue
            out.append(ItemScore(item=item, score=score))
            if len(out) >= int(query.num):
                break
        return PredictedResult(tuple(out))


class RecallAtK(OptionAverageMetric):
    """Fraction of held-out positives recovered in the top-k."""

    def __init__(self, k: int = 10):
        self.k = k

    def header(self) -> str:
        return f"Recall@{self.k}"

    def calculate_unit(self, query, predicted: PredictedResult, actual) -> float | None:
        positives = set(actual)
        if not positives:
            return None
        top = {s.item for s in predicted.item_scores[: self.k]}
        return len(top & positives) / len(positives)


def engine_factory() -> Engine:
    return Engine(
        datasource_class=TwoTowerDataSource,
        preparator_class=IdentityPreparator,
        algorithms_class_map={"twotower": TwoTowerAlgorithm},
        serving_class=FirstServing,
    )
