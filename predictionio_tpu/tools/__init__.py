"""Ops CLI and tooling.

Parity: ``tools/src/main/scala/org/apache/predictionio/tools/``
(SURVEY.md section 3.6): the ``pio`` console, app/accesskey/channel
management, import/export, status, and the train/deploy/eval launchers.
Unlike the reference there is no spark-submit bridge (``Runner.scala``) —
workflows run in-process on the TPU host.
"""
