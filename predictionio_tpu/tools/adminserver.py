"""Admin server — REST mirror of the app-management CLI.

Parity: ``tools/src/main/scala/.../admin/AdminServer.scala`` (the
experimental admin API): ``GET /`` status, ``GET /cmd/app`` list,
``POST /cmd/app`` create, ``DELETE /cmd/app/<name>`` delete,
``DELETE /cmd/app/<name>/data`` wipe events.
"""

from __future__ import annotations

from typing import Any, Mapping

from predictionio_tpu.data.storage import Storage, StorageError
from predictionio_tpu.tools import commands

__all__ = ["AdminService"]


class AdminService:
    def readiness(self) -> dict:
        """``GET /readyz``: the admin API is metadata CRUD — ready iff
        the metadata store answers."""
        from predictionio_tpu.api.health import readiness_report, storage_check

        return readiness_report(storage=storage_check())

    def dispatch(
        self,
        method: str,
        path: str,
        params: Mapping[str, str],
        body: Any = None,
        headers: Mapping[str, str] | None = None,
        form: Mapping[str, str] | None = None,
    ):
        from predictionio_tpu.api.service import Response

        method = method.upper()
        sink: list[str] = []
        try:
            if path == "/" and method == "GET":
                return Response(200, {"status": "alive"})
            if path == "/cmd/app" and method == "GET":
                apps = commands.app_list(out=sink.append)
                keys = Storage.get_meta_data_access_keys()
                return Response(
                    200,
                    [
                        {
                            "name": a.name,
                            "id": a.id,
                            "accessKeys": [k.key for k in keys.get_by_appid(a.id)],
                        }
                        for a in apps
                    ],
                )
            if path == "/cmd/app" and method == "POST":
                if not isinstance(body, Mapping) or not body.get("name"):
                    return Response(400, {"message": "Field 'name' is required."})
                app, key = commands.app_new(
                    str(body["name"]),
                    body.get("description"),
                    str(body.get("accessKey", "") or ""),
                    out=sink.append,
                )
                return Response(
                    201, {"name": app.name, "id": app.id, "accessKey": key.key}
                )
            if path.startswith("/cmd/app/") and method == "DELETE":
                rest = path[len("/cmd/app/"):]
                if rest.endswith("/data"):
                    commands.app_data_delete(rest[: -len("/data")], out=sink.append)
                    return Response(200, {"message": "Data deleted."})
                commands.app_delete(rest, out=sink.append)
                return Response(200, {"message": "App deleted."})
        except StorageError as e:
            return Response(400, {"message": str(e)})
        return Response(404, {"message": "Not Found"})
