"""Batch prediction: JSON-lines queries in, JSON-lines results out.

Parity: ``core/workflow/BatchPredict.scala`` (``pio batchpredict``) — load
the trained instance like ``deploy`` does, then map the query file through
the full supplement/predict/serve pipeline without binding an HTTP port.
"""

from __future__ import annotations

import json

from predictionio_tpu.workflow.engine_json import load_engine_variant
from predictionio_tpu.workflow.serving import QueryService

__all__ = ["run_batch_predict"]


def run_batch_predict(
    engine_json: str,
    input_path: str,
    output_path: str,
    engine_instance_id: str | None = None,
) -> int:
    variant = load_engine_variant(engine_json)
    service = QueryService(variant, instance_id=engine_instance_id)
    n = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        for line_no, line in enumerate(fin, 1):
            line = line.strip()
            if not line:
                continue
            try:
                query = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{input_path}:{line_no}: malformed JSON: {e}") from e
            try:
                status, payload = service.handle_query(query)
            except Exception as e:  # one bad query must not abort the batch
                status, payload = 500, {"message": str(e)}
            fout.write(
                json.dumps(
                    {"query": query, "prediction": payload}
                    if status == 200
                    else {"query": query, "error": payload, "status": status},
                    default=str,
                )
                + "\n"
            )
            n += 1
    return n
