"""Batch prediction: JSON-lines queries in, JSON-lines results out.

Parity: ``core/workflow/BatchPredict.scala`` (``pio batchpredict``) — load
the trained instance like ``deploy`` does, then map the query file through
the full supplement/predict/serve pipeline without binding an HTTP port.
"""

from __future__ import annotations

import json

from predictionio_tpu.workflow.engine_json import load_engine_variant
from predictionio_tpu.workflow.serving import QueryService

__all__ = ["run_batch_predict"]


#: queries handed to QueryService.handle_batch at a time — bounds resident
#: query/result memory while staying well above the algorithms' device
#: chunk size so the GEMM amortization is never starved
CHUNK = 8192


def run_batch_predict(
    engine_json: str,
    input_path: str,
    output_path: str,
    engine_instance_id: str | None = None,
) -> int:
    variant = load_engine_variant(engine_json)
    service = QueryService(variant, instance_id=engine_instance_id)
    n = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        batch: list = []

        def flush() -> None:
            nonlocal n
            if not batch:
                return
            # fast path first: payload strings straight from the
            # vectorized scorer (None = unavailable for this engine; a
            # None ENTRY = that body needs the exact slow path)
            fast = service.handle_batch_jsonlines(batch)
            slow_idx = (
                [i for i, line in enumerate(fast) if line is None]
                if fast is not None
                else list(range(len(batch)))
            )
            slow = {}
            if slow_idx:
                # ONE chunked device dispatch per algorithm (ref
                # BatchPredict.scala batchPredictBase) instead of a
                # supplement/predict/serve round trip per line
                slow = dict(zip(
                    slow_idx,
                    service.handle_batch([batch[i] for i in slow_idx]),
                ))
            for i, query in enumerate(batch):
                if fast is not None and fast[i] is not None:
                    # the input line IS the query JSON; compose without
                    # re-serializing either side
                    fout.write(
                        '{"query": %s, "prediction": %s}\n'
                        % (json.dumps(query), fast[i])
                    )
                else:
                    status, payload = slow[i]
                    fout.write(
                        json.dumps(
                            {"query": query, "prediction": payload}
                            if status == 200
                            else {
                                "query": query,
                                "error": payload,
                                "status": status,
                            },
                            default=str,
                        )
                        + "\n"
                    )
                n += 1
            batch.clear()

        for line_no, line in enumerate(fin, 1):
            line = line.strip()
            if not line:
                continue
            try:
                batch.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{input_path}:{line_no}: malformed JSON: {e}") from e
            if len(batch) >= CHUNK:
                flush()
        flush()
    return n
