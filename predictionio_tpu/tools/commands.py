"""CLI command implementations (transport- and argparse-free).

Parity: ``tools/console/App.scala``, ``AccessKey.scala``, ``Export.scala``,
``Import.scala``, the status checks of ``Console.scala``, and the
train/deploy orchestration of ``RunWorkflow.scala``/``RunServer.scala``.
Each function returns data (and prints human output via the ``out``
callback) so tests can drive them without capturing stdout.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Callable, Iterable

from predictionio_tpu.data.event import event_from_json, event_to_json
from predictionio_tpu.data.storage import Storage, StorageError
from predictionio_tpu.data.storage.base import AccessKey, App, Channel

__all__ = [
    "app_new",
    "app_list",
    "app_show",
    "app_delete",
    "app_data_delete",
    "channel_new",
    "channel_delete",
    "accesskey_new",
    "accesskey_list",
    "accesskey_delete",
    "import_events",
    "export_events",
    "status_check",
]

Out = Callable[[str], None]


def _print(line: str) -> None:
    print(line)


# ------------------------------------------------------------------- apps
def app_new(
    name: str, description: str | None = None, access_key: str = "", out: Out = _print
) -> tuple[App, AccessKey]:
    """``pio app new`` — create app, init its event stream, mint a key."""
    apps = Storage.get_meta_data_apps()
    if apps.get_by_name(name) is not None:
        raise StorageError(f"App '{name}' already exists.")
    app_id = apps.insert(App(id=0, name=name, description=description))
    Storage.get_l_events().init(app_id)
    key = Storage.get_meta_data_access_keys().insert(
        AccessKey(key=access_key, appid=app_id)
    )
    if key is None:
        # roll back the half-created app rather than leave it keyless
        Storage.get_l_events().remove(app_id)
        apps.delete(app_id)
        raise StorageError(f"Access key '{access_key}' already exists.")
    app = apps.get(app_id)
    out(f"Created a new app:")
    out(f"      Name: {name}")
    out(f"        ID: {app_id}")
    out(f"Access Key: {key}")
    return app, AccessKey(key=key, appid=app_id)


def app_list(out: Out = _print) -> list[App]:
    apps = sorted(Storage.get_meta_data_apps().get_all(), key=lambda a: a.name)
    keys = Storage.get_meta_data_access_keys()
    out(f"{'Name':<20} | {'ID':<4} | Access Key")
    for app in apps:
        app_keys = keys.get_by_appid(app.id)
        first = app_keys[0].key if app_keys else ""
        out(f"{app.name:<20} | {app.id:<4} | {first}")
    out(f"Finished listing {len(apps)} app(s).")
    return apps


def app_show(name: str, out: Out = _print) -> dict:
    app = Storage.get_meta_data_apps().get_by_name(name)
    if app is None:
        raise StorageError(f"App '{name}' does not exist.")
    keys = Storage.get_meta_data_access_keys().get_by_appid(app.id)
    channels = Storage.get_meta_data_channels().get_by_appid(app.id)
    out(f"    App Name: {app.name}")
    out(f"      App ID: {app.id}")
    out(f" Description: {app.description or ''}")
    for k in keys:
        events = ",".join(k.events) if k.events else "(all)"
        out(f"  Access Key: {k.key} | {events}")
    for ch in channels:
        out(f"     Channel: {ch.name} (id {ch.id})")
    return {"app": app, "access_keys": keys, "channels": channels}


def app_delete(name: str, out: Out = _print) -> None:
    """``pio app delete`` — drop the app, its keys, channels, events."""
    from predictionio_tpu.api.service import invalidate_access_key_caches

    app = Storage.get_meta_data_apps().get_by_name(name)
    if app is None:
        raise StorageError(f"App '{name}' does not exist.")
    le = Storage.get_l_events()
    for ch in Storage.get_meta_data_channels().get_by_appid(app.id):
        le.remove(app.id, ch.id)
        Storage.get_meta_data_channels().delete(ch.id)
    le.remove(app.id)
    deleted_keys = []
    for k in Storage.get_meta_data_access_keys().get_by_appid(app.id):
        Storage.get_meta_data_access_keys().delete(k.key)
        deleted_keys.append(k.key)
    Storage.get_meta_data_apps().delete(app.id)
    # revoke in any event server sharing this process; out-of-process
    # servers converge within the key-cache TTL (docs/eventserver.md)
    invalidate_access_key_caches(deleted_keys)
    out(f"Deleted app {name}.")


def _resolve_app_channel(name: str, channel: str | None):
    """(app, channel_id) for commands addressing one app's stream."""
    app = Storage.get_meta_data_apps().get_by_name(name)
    if app is None:
        raise StorageError(f"App '{name}' does not exist.")
    channel_id = None
    if channel is not None:
        matches = [
            c for c in Storage.get_meta_data_channels().get_by_appid(app.id)
            if c.name == channel
        ]
        if not matches:
            raise StorageError(f"Channel '{channel}' does not exist.")
        channel_id = matches[0].id
    return app, channel_id


def app_data_delete(name: str, channel: str | None = None, out: Out = _print) -> None:
    """``pio app data-delete`` — wipe events, keep the app."""
    app, channel_id = _resolve_app_channel(name, channel)
    le = Storage.get_l_events()
    le.remove(app.id, channel_id)
    le.init(app.id, channel_id)
    out(f"Deleted data of app {name}" + (f" channel {channel}." if channel else "."))


def app_compact(name: str, channel: str | None = None, out: Out = _print) -> int:
    """``pio app compact`` — seal the columnar event tail into segments
    (the HBase major-compaction role). Event ids survive. No-op error on
    backends without a tail/segment layout."""
    app, channel_id = _resolve_app_channel(name, channel)
    le = Storage.get_l_events()
    if not hasattr(le, "compact"):
        raise StorageError(
            "The configured EVENTDATA backend has no tail to compact "
            "(compaction applies to the columnar driver)."
        )
    moved = le.compact(app.id, channel_id)
    out(f"Compacted {moved} tail events of app {name} into segments.")
    return moved


# --------------------------------------------------------------- channels
def channel_new(app_name: str, channel_name: str, out: Out = _print) -> Channel:
    app = Storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise StorageError(f"App '{app_name}' does not exist.")
    if not Channel.is_valid_name(channel_name):
        raise StorageError(f"Channel name {Channel.NAME_CONSTRAINT}.")
    existing = Storage.get_meta_data_channels().get_by_appid(app.id)
    if any(c.name == channel_name for c in existing):
        raise StorageError(f"Channel '{channel_name}' already exists.")
    ch_id = Storage.get_meta_data_channels().insert(
        Channel(id=0, name=channel_name, appid=app.id)
    )
    Storage.get_l_events().init(app.id, ch_id)
    out(f"Created channel {channel_name} (id {ch_id}) for app {app_name}.")
    return Channel(id=ch_id, name=channel_name, appid=app.id)


def channel_delete(app_name: str, channel_name: str, out: Out = _print) -> None:
    app = Storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise StorageError(f"App '{app_name}' does not exist.")
    matches = [
        c for c in Storage.get_meta_data_channels().get_by_appid(app.id)
        if c.name == channel_name
    ]
    if not matches:
        raise StorageError(f"Channel '{channel_name}' does not exist.")
    Storage.get_l_events().remove(app.id, matches[0].id)
    Storage.get_meta_data_channels().delete(matches[0].id)
    out(f"Deleted channel {channel_name} of app {app_name}.")


# ------------------------------------------------------------ access keys
def accesskey_new(
    app_name: str, events: Iterable[str] = (), key: str = "", out: Out = _print
) -> str:
    app = Storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise StorageError(f"App '{app_name}' does not exist.")
    new_key = Storage.get_meta_data_access_keys().insert(
        AccessKey(key=key, appid=app.id, events=tuple(events))
    )
    if new_key is None:
        raise StorageError(f"Access key '{key}' already exists.")
    out(f"Created new access key: {new_key}")
    return new_key


def accesskey_list(app_name: str | None = None, out: Out = _print) -> list[AccessKey]:
    repo = Storage.get_meta_data_access_keys()
    if app_name is None:
        keys = repo.get_all()
    else:
        app = Storage.get_meta_data_apps().get_by_name(app_name)
        if app is None:
            raise StorageError(f"App '{app_name}' does not exist.")
        keys = repo.get_by_appid(app.id)
    for k in keys:
        events = ",".join(k.events) if k.events else "(all)"
        out(f"{k.key} | app {k.appid} | {events}")
    out(f"Finished listing {len(keys)} access key(s).")
    return keys


def accesskey_delete(key: str, out: Out = _print) -> None:
    from predictionio_tpu.api.service import invalidate_access_key_caches

    if not Storage.get_meta_data_access_keys().delete(key):
        raise StorageError(f"Access key '{key}' does not exist.")
    invalidate_access_key_caches([key])
    out(f"Deleted access key {key}.")


# ---------------------------------------------------------- import/export
def import_events(
    app_name: str,
    input_path: str,
    channel: str | None = None,
    out: Out = _print,
) -> int:
    """``pio import`` — JSON-lines file (or a columnar export directory,
    auto-detected) -> event store bulk write
    (parity: ``tools/imprt/FileToEvents.scala``).

    JSONL files ride the streaming bulk-ingest pipeline (the same
    parse→validate→append stages as ``POST /events/bulk.json``): byte
    blocks in, vectorized chunks out, dedup on — lines carrying an
    ``eventId`` are idempotency keys, so re-running an interrupted
    import never double-stores. The first invalid line aborts with its
    ``file:line`` position, matching the historical contract."""
    from predictionio_tpu.data.store import resolve_app

    app_id, channel_id = resolve_app(app_name, channel)
    counter = {"n": 0}

    if not os.path.isdir(input_path):
        return _import_jsonl_pipelined(
            app_name, input_path, app_id, channel_id, out
        )

    # a `pio export --format columnar` directory: stream its events
    # back through the portable object path (ids re-assigned by the
    # destination store). Anything else directory-shaped (e.g. a
    # --sharded JSONL export) must error, not silently import 0
    # events — and must not be mutated by instantiating a driver on
    # top of it.
    if not os.path.isdir(os.path.join(input_path, "export_events")):
        raise StorageError(
            f"{input_path} is a directory but not a columnar export "
            "(no export_events/ inside). For sharded JSONL exports, "
            "import each shard file individually."
        )
    src = _columnar_file_client(input_path).get_p_events()

    def gen():
        for event in src.find(0):
            counter["n"] += 1
            yield event.with_event_id(None) if event.event_id else event

    Storage.get_p_events().write(gen(), app_id, channel_id)
    out(f"Imported {counter['n']} events to app {app_name}.")
    return counter["n"]


def _import_jsonl_pipelined(
    app_name: str,
    input_path: str,
    app_id: int,
    channel_id: int | None,
    out: Out,
) -> int:
    """JSONL import over the bulk-ingest pipeline: the file is read in
    byte blocks and flows through the same parse→validate→append stages
    as the bulk route — no per-line ``Event`` construction, one columnar
    chunk append per 65536 lines. Aborts on the first invalid line
    (position reported 1-based like a compiler diagnostic)."""
    from predictionio_tpu.data.ingest import IngestPipeline, PipelineError

    pipeline = IngestPipeline(
        Storage.get_l_events(), app_id, channel_id, chunk_rows=65536
    )

    def check(results) -> None:
        for res in results:
            if res.errors:
                first = res.errors[0]
                pipeline.close()
                raise StorageError(
                    f"{input_path}:{first['line'] + 1}: {first['message']}"
                )
            if res.storage_error is not None:
                pipeline.close()
                raise StorageError(res.storage_error)

    try:
        with open(input_path, "rb") as f:
            while True:
                block = f.read(1 << 20)
                if not block:
                    break
                pipeline.feed(block)
                check(pipeline.poll())
        check(pipeline.finish())
    except PipelineError as e:
        raise StorageError(f"import pipeline failed: {e}") from e
    n = pipeline.stored + pipeline.duplicates
    dup_note = (
        f" ({pipeline.duplicates} duplicate eventIds absorbed)"
        if pipeline.duplicates
        else ""
    )
    out(f"Imported {n} events to app {app_name}.{dup_note}")
    return n


def _columnar_file_client(path: str):
    """A throwaway columnar driver rooted at ``path`` — the on-disk
    columnar interchange format IS the columnar store layout (the role
    `--format parquet` plays for the reference's EventsToFile)."""
    from predictionio_tpu.data.storage import columnar
    from predictionio_tpu.data.storage.base import StorageClientConfig

    return columnar.StorageClient(
        StorageClientConfig("FILE", "columnar", {"path": path, "prefix": "export"})
    )


def export_events(
    app_name: str,
    output_path: str,
    channel: str | None = None,
    num_shards: int = 0,
    format: str = "json",
    out: Out = _print,
) -> int:
    """``pio export`` — event store -> JSON-lines file, a directory of
    round-robin shard files (``num_shards > 0``, for multi-host training
    reads), or a columnar segment directory (``format="columnar"`` — the
    reference's ``--format parquet`` analog: dictionary-encoded, read
    back at array speed)
    (parity: ``tools/export/EventsToFile.scala``)."""
    from predictionio_tpu.data.store import resolve_app

    app_id, channel_id = resolve_app(app_name, channel)
    events = Storage.get_p_events().find(app_id, channel_id)
    if format == "columnar":
        if num_shards > 0:
            raise ValueError(
                "--sharded applies to the JSON format only; a columnar "
                "export is already a segment directory"
            )
        if os.path.isdir(os.path.join(output_path, "export_events")):
            # appending segments to a previous export would duplicate
            # every event on re-import (JSON exports overwrite; refuse
            # rather than silently differ)
            raise StorageError(
                f"{output_path} already holds a columnar export; remove it "
                "or export to a fresh directory"
            )
        n = 0

        def counted():
            nonlocal n
            for e in events:
                n += 1
                yield e

        _columnar_file_client(output_path).get_p_events().write(counted(), 0)
        out(f"Exported {n} events to columnar segments in {output_path}.")
        return n
    if format != "json":
        raise ValueError(f"unknown export format {format!r} (json|columnar)")
    if num_shards > 0:
        from predictionio_tpu.parallel.reader import write_event_shards

        paths = write_event_shards(events, output_path, num_shards=num_shards)
        out(f"Exported {len(paths)} shards to {output_path}.")
        return len(paths)
    n = 0
    with open(output_path, "w") as f:
        for event in events:
            f.write(json.dumps(event_to_json(event), default=str) + "\n")
            n += 1
    out(f"Exported {n} events to {output_path}.")
    return n


# ----------------------------------------------------------------- status
def status_check(out: Out = _print) -> dict:
    """``pio status`` — verify storage connectivity per repository role
    (parity: the storage checks in ``Console.scala``)."""
    import jax

    results: dict[str, str] = {}
    checks = [
        ("metadata", lambda: Storage.get_meta_data_apps().get_all()),
        ("eventdata", lambda: Storage.get_l_events()),
        ("modeldata", lambda: Storage.get_model_data_models()),
    ]
    ok = True
    for role, check in checks:
        try:
            check()
            results[role] = "OK"
        except Exception as e:  # surface the root cause, keep checking
            results[role] = f"FAILED: {e}"
            ok = False
    try:
        devices = jax.devices()
        results["devices"] = f"{len(devices)} x {devices[0].platform}"
    except Exception as e:
        results["devices"] = f"FAILED: {e}"
        ok = False
    for role, status in results.items():
        out(f"  {role:<10} {status}")
    fleets = fleet_status(out)
    try:
        aot_rows = aot_artifact_status(out)
    except Exception as e:  # a torn registry must not fail the storage check
        aot_rows = None
        results["aotArtifacts"] = f"FAILED: {e}"
    out("(sanity check) All systems go!" if ok else "Storage check FAILED")
    results["ok"] = ok
    if fleets:
        results["fleets"] = fleets
    if aot_rows is not None:
        results["aotArtifacts"] = aot_rows
    return results


def fleet_status(out: Out = _print) -> list[dict]:
    """Aggregate every active replica fleet on this host (``pio deploy
    --replicas``; ISSUE 15/17): the cross-host endpoint registry is the
    primary view (per-host replica rows with lease age, generation and
    readiness, ring membership, stale-lease and torn-entry warnings);
    the supervisor's per-host state files are the degraded fallback —
    they still list PIDs and liveness when the registry dir is absent
    (pre-elastic fleets) or unreadable."""
    import glob
    import urllib.request

    pattern = os.path.join(Storage.base_dir(), "deployments", "fleet-*.json")
    paths = sorted(glob.glob(pattern))
    registry_dir = os.path.join(Storage.base_dir(), "fleet", "endpoints")
    if not paths and not os.path.isdir(registry_dir):
        return []  # nothing fleet-ish on this host: never import the package
    from predictionio_tpu.fleet.supervisor import read_fleet_state

    fleets: list[dict] = []
    states = [s for s in (read_fleet_state(p) for p in paths) if s]
    # a fleet on a custom --endpoint-registry DIR reports its directory
    # on the router's /fleet/endpoints.json — ask each router so status
    # aggregates THAT registry, not just the default location
    registry_dirs: list[str] = []
    for state in states:
        reported = _router_registry_dir(state.get("routerPort"))
        if reported and reported not in registry_dirs:
            registry_dirs.append(reported)
    if os.path.isdir(registry_dir) and registry_dir not in registry_dirs:
        registry_dirs.append(registry_dir)
    for directory in registry_dirs:
        registry_view = _endpoint_registry_status(directory, out)
        if registry_view is not None:
            fleets.append({"endpointRegistry": registry_view})
    for state in states:
        replicas = []
        for rep in state.get("replicas", []):
            entry = {
                "id": rep.get("id"),
                "port": rep.get("port") or None,
                "ready": False,
                "generation": None,
                "alive": rep.get("alive"),
            }
            if not entry["port"]:
                # elastic replica: bound port 0 and self-reported through
                # the registry — the registry view above is authoritative;
                # this row only carries supervisor liveness
                entry["ready"] = None
                replicas.append(entry)
                continue
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{rep.get('port')}/readyz", timeout=2
                ) as resp:
                    report = json.loads(resp.read())
            except Exception:
                report = None
            if report is not None:
                entry["ready"] = bool(report.get("ready"))
                entry["generation"] = report.get("generation")
            replicas.append(entry)
        generations = {
            r["generation"] for r in replicas if r["generation"] is not None
        }
        fleet = {
            "routerPort": state.get("routerPort"),
            "replicas": replicas,
            "generationConverged": len(generations) == 1,
        }
        experiment = _fleet_experiment(state.get("routerPort"))
        if experiment is not None:
            fleet["experiment"] = experiment
        fleets.append(fleet)
        probed = [r for r in replicas if r["ready"] is not None]
        if probed:
            ready_part = (
                f"{sum(1 for r in probed if r['ready'])}/{len(probed)} "
                f"replicas ready, generations "
                f"{sorted(generations) if generations else '[]'}"
                f"{' (converged)' if fleet['generationConverged'] else ''}"
            )
        else:
            ready_part = (
                f"{len(replicas)} replica(s), readiness via the endpoint "
                "registry above"
            )
        out(
            f"  fleet      router :{fleet['routerPort']} — {ready_part}"
        )
        if experiment is not None:
            arms = ", ".join(
                f"{v['name']}:{v['weight']:g} "
                f"({v['routed']} routed, {v['rewardCount']} rewards)"
                for v in experiment.get("variants", [])
            )
            promoted = experiment.get("promoted")
            out(
                "  experiment "
                + (arms or "(no variants)")
                + (
                    f" — PROMOTED {promoted['variant']} at {promoted['at']}"
                    if promoted
                    else ""
                )
            )
    return fleets


def aot_artifact_status(out: Out = _print) -> list[dict] | None:
    """Per-generation AOT artifact readiness for ``pio status`` — the
    operator's answer to "will ``pio deploy --aot`` boot tier 1 on THIS
    host?" (ISSUE 19; docs/operations.md AOT runbook). Read-only over
    the fleet model registry and the artifact dirs it stamps:

    * ``present`` — manifest + blobs verify (sha256) and the recorded
      fingerprint matches this host's jax/jaxlib/backend;
    * ``fingerprint-stale`` — blobs verify but were exported under a
      different environment (boot would fall back loudly to tier 2/3);
    * ``missing`` — stamped but the dir is gone, torn, or corrupt.

    Generations published without ``pio train --aot`` show ``None``
    (the JIT path). Returns ``None`` — and prints nothing — when no
    generation carries an artifact stamp, so a fleet that never opted
    in sees zero new output (CI-guarded)."""
    from predictionio_tpu.fleet.registry import (
        ModelRegistry,
        verify_aot_artifacts,
    )

    registry = ModelRegistry(os.path.join(Storage.base_dir(), "fleet"))
    records = []
    cur = registry.current()
    if cur is not None:
        records.append(cur)
    records.extend(registry.history())  # history[0] repeats current
    if not any(r.artifacts for r in records):
        return None
    # lazy: only a stamped registry pays the jax-side fingerprint read
    from predictionio_tpu.workflow.aot import (
        current_fingerprint,
        fingerprint_mismatches,
    )

    live = current_fingerprint()
    rows: list[dict] = []
    seen: set[int] = set()
    for rec in records:
        if rec.generation in seen:
            continue
        seen.add(rec.generation)
        row: dict = {
            "generation": rec.generation,
            "engineInstanceId": rec.engine_instance_id,
            "artifacts": None,
        }
        if rec.artifacts:
            adir = rec.artifacts.get("dir", "")
            verdict = (
                verify_aot_artifacts(adir)
                if adir
                else {"ok": False, "fingerprint": None}
            )
            if not verdict["ok"]:
                row["artifacts"] = "missing"
            else:
                mismatches = fingerprint_mismatches(
                    verdict.get("fingerprint") or {}, live
                )
                if mismatches:
                    row["artifacts"] = "fingerprint-stale"
                    row["mismatches"] = mismatches
                else:
                    row["artifacts"] = "present"
            row["dir"] = adir
        rows.append(row)
    for row in rows:
        out(
            f"  aot        gen {row['generation']} "
            f"{row['engineInstanceId']}: {row['artifacts'] or '(jit)'}"
        )
    return rows


def _router_registry_dir(router_port: int | None) -> str | None:
    """The registry directory a live router actually serves from
    (``GET /fleet/endpoints.json``) — how status finds a custom
    ``--endpoint-registry DIR``. ``None`` when the router is down or
    pre-elastic (404)."""
    import urllib.request

    if not router_port:
        return None
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{router_port}/fleet/endpoints.json", timeout=2
        ) as resp:
            doc = json.loads(resp.read())
        return doc.get("registry", {}).get("directory") or None
    except Exception:
        return None


def _endpoint_registry_status(directory: str, out: Out = _print) -> dict | None:
    """Aggregate the cross-host endpoint registry for ``pio status``
    (ISSUE 17): per-host replica rows (lease age, generation, readiness
    probed at the self-reported address), ring membership, stale-lease
    warnings for expired-but-unevicted entries, and loud torn-entry
    problems. ``None`` when the registry dir is absent — callers fall
    back to the per-host supervisor state files."""
    import urllib.request

    if not os.path.isdir(directory):
        return None
    from predictionio_tpu.fleet.registry import EndpointRegistry

    # read-only aggregation: snapshot, never evict — eviction is the
    # routers' job (claimed exactly once); status just reports
    live, expired, problems = EndpointRegistry(directory).snapshot()
    hosts: dict[str, list[dict]] = {}
    for entry in live:
        row = {
            "id": entry.replica_id,
            "host": entry.host,
            "port": entry.port,
            "leaseAgeS": round(entry.lease_age_s(), 3),
            "generation": entry.generation,
            "ready": False,
        }
        try:
            with urllib.request.urlopen(
                f"http://{entry.host}:{entry.port}/readyz", timeout=2
            ) as resp:
                report = json.loads(resp.read())
            row["ready"] = bool(report.get("ready"))
            row["generation"] = report.get("generation", entry.generation)
        except Exception:
            pass
        hosts.setdefault(entry.host, []).append(row)
    for rows in hosts.values():
        rows.sort(key=lambda r: r["id"])
    view = {
        "directory": directory,
        "ring": sorted(e.replica_id for e in live),
        "hosts": hosts,
        "staleLeases": sorted(e.replica_id for e in expired),
        "problems": problems,
    }
    out(
        f"  endpoints  {len(live)} live replica(s) across "
        f"{len(hosts)} host(s) in {directory}"
    )
    for host in sorted(hosts):
        rows = hosts[host]
        out(
            f"    {host}: "
            + ", ".join(
                f"{r['id']}:{r['port']} gen={r['generation']} "
                f"lease={r['leaseAgeS']:.1f}s"
                f"{' ready' if r['ready'] else ' NOT-READY'}"
                for r in rows
            )
        )
    if view["ring"]:
        out(f"    ring members: {view['ring']}")
    if view["staleLeases"]:
        out(
            f"    WARNING: stale leases (expired, not yet evicted): "
            f"{view['staleLeases']}"
        )
    for problem in problems:
        out(
            f"    WARNING: torn registry entry {problem['file']}: "
            f"{problem['error']}"
        )
    return view


def _fleet_experiment(router_port) -> dict | None:
    """One fleet's active experiment (``pio status``; ISSUE 16): the
    router's live ``/experiments.json`` (variants, weights, sample
    counts, promotion stamp), falling back to the registry file's
    promotion record when the router is down. None = no experiment."""
    import urllib.request

    if router_port:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{router_port}/experiments.json", timeout=2
            ) as resp:
                if resp.status == 200:
                    return json.loads(resp.read())
        except Exception:
            pass
    # router unreachable (or answered non-200): the promotion stamp in
    # the fleet registry is still on disk
    registry_path = os.path.join(
        Storage.base_dir(), "fleet", "model-registry.json"
    )
    try:
        with open(registry_path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    meta = ((doc.get("current") or {}).get("meta")) or {}
    if meta.get("source") != "experiment_promotion":
        return None
    return {
        "variants": [],
        "promoted": {
            "variant": meta.get("variant"),
            "at": (doc.get("current") or {}).get("publishedAt"),
        },
    }


def _stop_token_path(port: int) -> str:
    return os.path.join(Storage.base_dir(), "deployments", f"{port}.token")


def write_stop_token(port: int) -> str:
    """Generate the per-deployment stop token and persist it (0600) where
    ``pio undeploy`` on the same host finds it. Gates ``GET /stop`` so a
    reachable port is not a remote shutdown primitive (advisor r3)."""
    import secrets

    token = secrets.token_urlsafe(16)
    path = _stop_token_path(port)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(token)
    return token


def read_stop_token(port: int) -> str | None:
    try:
        with open(_stop_token_path(port)) as f:
            return f.read().strip() or None
    except FileNotFoundError:
        return None


def undeploy(
    ip: str = "127.0.0.1",
    port: int = 8000,
    https: bool = False,
    insecure: bool = False,
    token: str | None = None,
    out: Out = _print,
) -> None:
    """``pio undeploy`` — ask a deployed query server to shut down via its
    ``GET /stop`` route (parity: Console's undeploy hitting CreateServer's
    stop endpoint). ``insecure`` skips TLS verification (self-signed
    deployments). ``token`` defaults to the basedir token file written by
    ``pio deploy`` for this port."""
    import ssl as _ssl
    import urllib.error
    import urllib.parse
    import urllib.request

    if token is None and (ip.startswith("127.") or ip in ("localhost", "::1")):
        # the basedir token file is only meaningful for THIS host's
        # deployments — falling back for a remote ip would transmit the
        # local deployment's secret to an unrelated server
        token = read_stop_token(port)
    scheme = "https" if https else "http"
    url = f"{scheme}://{ip}:{port}/stop"
    # token travels in a header — query strings are routinely recorded by
    # access logs and intermediary proxies (advisor r4). It is ALSO still
    # sent as ?token= for one transition: servers deployed by an older
    # version read only the query param, and undeploy must be able to
    # stop them.
    if token:
        url += "?token=" + urllib.parse.quote(token, safe="")
    req = urllib.request.Request(url)
    if token:
        req.add_header("X-PIO-Stop-Token", token)
    ctx = None
    if https:
        ctx = _ssl.create_default_context()
        if insecure:
            ctx.check_hostname = False
            ctx.verify_mode = _ssl.CERT_NONE
    try:
        with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
            resp.read()
    except urllib.error.HTTPError as e:
        # the server is UP but refused — report its actual answer, not a
        # bogus "unreachable" (501 = deployment without a stop hook)
        hint = (
            " (remote deployments require --token)" if e.code == 403 else ""
        )
        raise RuntimeError(
            f"Deployment at {ip}:{port} refused to stop: "
            f"HTTP {e.code} {e.reason}{hint}"
        ) from e
    except urllib.error.URLError as e:
        raise RuntimeError(
            f"Could not reach a deployment at {url}: {e.reason}"
        ) from e
    out(f"Undeployed engine server at {ip}:{port}.")


#: built-in engine templates: name -> (engineFactory, description, default
#: engine.json algorithm block). The reference-era `pio template get`
#: downloaded scaffolds from a gallery; templates here ship in-package,
#: so `get` writes a ready-to-train engine.json instead.
BUILTIN_TEMPLATES = {
    "recommendation": (
        "predictionio_tpu.templates.recommendation:engine_factory",
        "Personalized top-N via ALS (explicit + implicit), Pallas SPD solver",
        [{"name": "als", "params": {"rank": 32, "numIterations": 10, "lambda": 0.05}}],
    ),
    "classification": (
        "predictionio_tpu.templates.classification:engine_factory",
        "Attribute -> label classification (NaiveBayes / LogisticRegression)",
        [{"name": "naive", "params": {"lambda": 1.0}}],
    ),
    "similarproduct": (
        "predictionio_tpu.templates.similarproduct:engine_factory",
        "Items similar to a basket of items (implicit ALS, cosine)",
        [{"name": "als", "params": {"rank": 32, "numIterations": 10, "lambda": 0.01}}],
    ),
    "ecommerce": (
        "predictionio_tpu.templates.ecommerce:engine_factory",
        "E-commerce recommendations with serving-time business rules",
        [{"name": "ecomm", "params": {"rank": 32, "numIterations": 10, "lambda": 0.01}}],
    ),
    "textclassification": (
        "predictionio_tpu.templates.textclassification:engine_factory",
        "Text -> label via hashing TF-IDF + NB/LR",
        [{"name": "nb", "params": {"lambda": 1.0}}],
    ),
    "twotower": (
        "predictionio_tpu.templates.twotower:engine_factory",
        "Two-tower retrieval: sharded embeddings, in-batch sampled softmax",
        [
            {
                "name": "twotower",
                "params": {"embeddingDim": 64, "batchSize": 512, "epochs": 5},
            }
        ],
    ),
}


def template_list(out: Out = _print) -> dict:
    """``pio template list`` — built-in engine templates."""
    out(f"{'NAME':<20} ENGINE FACTORY")
    for name, (factory, desc, _) in BUILTIN_TEMPLATES.items():
        out(f"{name:<20} {factory}")
        out(f"{'':<20}   {desc}")
    return BUILTIN_TEMPLATES


def template_get(
    name: str, directory: str, app_name: str = "MyApp", out: Out = _print
) -> str:
    """``pio template get`` — scaffold a ready-to-train engine directory
    (engine.json + README) for a built-in template."""
    if name not in BUILTIN_TEMPLATES:
        raise ValueError(
            f"Unknown template '{name}'. Available: {', '.join(BUILTIN_TEMPLATES)}"
        )
    factory, desc, algorithms = BUILTIN_TEMPLATES[name]
    os.makedirs(directory, exist_ok=True)
    engine_path = os.path.join(directory, "engine.json")
    if os.path.exists(engine_path):
        raise ValueError(f"{engine_path} already exists; refusing to overwrite")
    variant = {
        "id": name,
        "version": "1",
        "engineFactory": factory,
        "datasource": {"params": {"appName": app_name}},
        "algorithms": algorithms,
    }
    with open(engine_path, "w") as f:
        json.dump(variant, f, indent=2)
        f.write("\n")
    readme = os.path.join(directory, "README.md")
    if not os.path.exists(readme):
        with open(readme, "w") as f:
            f.write(
                f"# {name} engine\n\n{desc}\n\n"
                "```bash\n"
                f"pio app new {app_name}\n"
                f"pio import --appname {app_name} --input events.json\n"
                "pio train --engine-json engine.json\n"
                "pio deploy --port 8000\n"
                "```\n"
            )
    out(f"Template '{name}' scaffolded in {directory}/ (edit appName in engine.json).")
    return engine_path
