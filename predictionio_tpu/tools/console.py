"""The ``pio`` console — argparse front-end over the command layer.

Parity: ``tools/console/Console.scala`` + ``console/Pio.scala`` (scopt →
argparse). Subcommand surface mirrors the reference:

    pio version | status
    pio app new|list|show|delete|data-delete|channel-new|channel-delete
    pio accesskey new|list|delete
    pio import|export
    pio train | deploy | eval | eventserver | dashboard | batchpredict

Run as ``python -m predictionio_tpu.tools.console`` or via ``bin/pio``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from predictionio_tpu.tools import commands
from predictionio_tpu.version import __version__

__all__ = ["main", "build_parser"]


def _int_at_least(floor: int):
    """argparse ``type=`` validator: int with a lower bound, so a bad
    value fails at parse time with the usual clean ``usage:`` error
    instead of a config-construction traceback."""

    def parse(text: str) -> int:
        try:
            value = int(text)
        except ValueError:
            raise argparse.ArgumentTypeError(f"invalid int value: {text!r}")
        if value < floor:
            raise argparse.ArgumentTypeError(f"must be >= {floor}")
        return value

    return parse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio", description="predictionio_tpu — TPU-native ML server"
    )
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version", help="print version")
    sub.add_parser("status", help="check storage + device connectivity")

    # ---- app
    app = sub.add_parser("app", help="manage apps")
    app_sub = app.add_subparsers(dest="app_command", required=True)
    ap_new = app_sub.add_parser("new")
    ap_new.add_argument("name")
    ap_new.add_argument("--description")
    ap_new.add_argument("--access-key", default="")
    app_sub.add_parser("list")
    for cmd in ("show", "delete", "data-delete", "compact"):
        sp = app_sub.add_parser(cmd)
        sp.add_argument("name")
        if cmd in ("data-delete", "compact"):
            sp.add_argument("--channel")
    ch_new = app_sub.add_parser("channel-new")
    ch_new.add_argument("name")
    ch_new.add_argument("channel")
    ch_del = app_sub.add_parser("channel-delete")
    ch_del.add_argument("name")
    ch_del.add_argument("channel")

    # ---- accesskey
    ak = sub.add_parser("accesskey", help="manage access keys")
    ak_sub = ak.add_subparsers(dest="accesskey_command", required=True)
    ak_new = ak_sub.add_parser("new")
    ak_new.add_argument("app_name")
    ak_new.add_argument("events", nargs="*")
    ak_list = ak_sub.add_parser("list")
    ak_list.add_argument("app_name", nargs="?")
    ak_del = ak_sub.add_parser("delete")
    ak_del.add_argument("key")

    # ---- import / export
    imp = sub.add_parser("import", help="bulk-load JSON-lines events")
    imp.add_argument("--appname", required=True)
    imp.add_argument("--input", required=True)
    imp.add_argument("--channel")
    exp = sub.add_parser("export", help="dump events to JSON-lines")
    exp.add_argument("--appname", required=True)
    exp.add_argument("--output", required=True)
    exp.add_argument("--channel")
    exp.add_argument(
        "--sharded",
        type=int,
        default=0,
        metavar="N",
        help="write N round-robin shard files into OUTPUT (a directory) "
        "for multi-host training reads",
    )
    exp.add_argument(
        "--format",
        choices=("json", "columnar"),
        default="json",
        help="json = JSON-lines; columnar = dictionary-encoded segment "
        "directory, re-importable and readable at array speed (the "
        "reference's --format parquet role)",
    )

    # ---- train
    train = sub.add_parser("train", help="run the training workflow")
    train.add_argument("--engine-json", default="engine.json")
    train.add_argument("--batch", default="")
    train.add_argument("--skip-sanity-check", action="store_true")
    train.add_argument("--stop-after-read", action="store_true")
    train.add_argument("--stop-after-prepare", action="store_true")
    train.add_argument(
        "--warm-start", action="store_true",
        help="seed algorithms from the latest COMPLETED instance's model "
        "(retrains converge in fewer sweeps)",
    )
    train.add_argument(
        "--mesh",
        default="auto",
        help="'auto' (all devices on data axis), 'none' (local), or "
        "'data=N,model=M' axis sizes",
    )
    # ---- deploy-time AOT serving (predictionio_tpu.workflow.aot;
    # docs/operations.md AOT runbook). Strictly opt-in: without --aot no
    # program is exported and training output is byte-identical
    # (CI-guarded).
    train.add_argument(
        "--aot", action="store_true",
        help="after training, lower + serialize every budgeted serving "
        "entrypoint per pow2 candidate bucket (jax.export) into "
        "<basedir>/fleet/aot/<instance>/ and stamp the artifact set into "
        "the fleet model registry — `pio deploy --aot` replicas then boot "
        "by deserializing instead of compiling (zero serve-time "
        "compiles; docs/operations.md)",
    )
    train.add_argument(
        "--compilation-cache-dir", default=None, metavar="DIR",
        help="persistent XLA compilation cache directory shared across "
        "replicas/hosts — the tier-2 fallback when AOT artifacts are "
        "missing or fingerprint-stale (default: "
        "$PIO_COMPILATION_CACHE_DIR or <basedir>/jax_cache; '0' "
        "disables)",
    )

    def add_ssl_flags(sp):
        sp.add_argument(
            "--cert", default=None,
            help="PEM certificate for https (default: $PIO_SSL_CERT)",
        )
        sp.add_argument(
            "--key", default=None,
            help="PEM private key for https (default: $PIO_SSL_KEY)",
        )

    def add_lifecycle_flags(sp):
        sp.add_argument(
            "--drain-deadline-s", type=float, default=0.0, metavar="S",
            help="graceful drain on SIGTERM/SIGINT: stop accepting (503 + "
            "Retry-After, /readyz flips unready), finish in-flight "
            "requests within S seconds, flush storage, exit 0; a second "
            "signal force-quits. 0 (default) keeps immediate exit "
            "(docs/operations.md)",
        )

    # ---- deploy
    deploy = sub.add_parser("deploy", help="serve the latest trained instance")
    deploy.add_argument("--engine-json", default="engine.json")
    deploy.add_argument("--ip", default="0.0.0.0")
    deploy.add_argument("--port", type=int, default=8000)
    deploy.add_argument("--engine-instance-id")
    # ---- replica fleet (predictionio_tpu.fleet; docs/operations.md).
    # Strictly opt-in: without --replicas no fleet module is imported, no
    # router process exists, and serving is byte-identical (CI-guarded).
    deploy.add_argument(
        "--replicas", type=_int_at_least(1), default=0, metavar="N",
        help="serve through a replica fleet: spawn N query-server "
        "subprocesses (each composing every other deploy flag, e.g. "
        "--shard-factors/--quantize/--ann) plus a router on --port that "
        "load-balances by consistent hash of the cache scope, health-"
        "gates on /readyz + passive failures + a per-replica circuit "
        "breaker, fails idempotent requests over to a peer, and "
        "orchestrates rolling /reload (docs/operations.md fleet runbook)",
    )
    deploy.add_argument(
        "--replica-id", default=None, metavar="ID",
        help="fleet-internal: this process is replica ID of a fleet "
        "(set by the supervisor; exposes replicaId/generation on "
        "/readyz, /stats.json and query response headers)",
    )
    deploy.add_argument(
        "--probe-interval-s", type=float, default=0.25, metavar="S",
        help="router: seconds between /readyz health probes of each "
        "replica — a killed or draining replica is routed around within "
        "one interval (default 0.25)",
    )
    deploy.add_argument(
        "--failover-retries", type=_int_at_least(0), default=1, metavar="N",
        help="router: most times one idempotent request (GETs and "
        "/queries.json) is re-dispatched to a peer after a replica "
        "fails mid-request; non-idempotent routes are never retried "
        "(default 1)",
    )
    deploy.add_argument(
        "--hedge-ms", type=float, default=0.0, metavar="MS",
        help="router: hedge a query to a second replica when the first "
        "has not answered within max(MS, observed p95) — bounds the "
        "tail one slow replica can impose; 0 (default) disables hedging",
    )
    deploy.add_argument(
        "--fleet-breaker-threshold", type=_int_at_least(1), default=2,
        metavar="N",
        help="router: consecutive transport failures that open one "
        "replica's circuit breaker (default 2)",
    )
    deploy.add_argument(
        "--fleet-breaker-reset-s", type=float, default=1.0, metavar="S",
        help="router: seconds an open replica breaker waits before "
        "probing again — the fleet's recovery-time unit (default 1.0)",
    )
    # ---- cross-host elastic fleet (ISSUE 17; docs/operations.md
    # multi-host runbook). All strictly opt-in: a fleet-less deploy
    # imports none of it, and a plain `--replicas N` fleet only gains
    # registry-driven discovery (replicas bind port 0 and self-report —
    # the pick-then-spawn port race is structurally gone).
    deploy.add_argument(
        "--endpoint-registry", default=None, metavar="DIR",
        help="fleet: shared endpoint-registry directory (a shared "
        "filesystem path) through which replicas on ANY host join this "
        "router's consistent-hash ring — lease-stamped atomic entry "
        "files, evicted on lease expiry, readable at GET "
        "/fleet/endpoints.json (default: <basedir>/fleet/endpoints, "
        "i.e. single-host unless pointed at a shared mount)",
    )
    deploy.add_argument(
        "--router-only", action="store_true",
        help="fleet: serve a router WITHOUT spawning replicas — a "
        "second router sharing --endpoint-registry with the primary is "
        "router-tier HA: same registry, same ring, client-visible "
        "failover between the two",
    )
    deploy.add_argument(
        "--autoscale", default="", metavar="MIN:MAX",
        help="fleet: autoscale the replica fleet between MIN and MAX on "
        "the watermarks below; scale-down retires drain-aware (SIGTERM "
        "→ finish in-flight → withdraw registry entry; zero queries "
        "lost). Requires --replicas (the initial size)",
    )
    deploy.add_argument(
        "--scale-up-qps", type=float, default=50.0, metavar="Q",
        help="autoscale: add a replica when per-replica q/s exceeds Q "
        "(default 50)",
    )
    deploy.add_argument(
        "--scale-up-p99-ms", type=float, default=250.0, metavar="MS",
        help="autoscale: add a replica when router p99 exceeds MS "
        "regardless of q/s (default 250)",
    )
    deploy.add_argument(
        "--scale-down-qps", type=float, default=5.0, metavar="Q",
        help="autoscale: drain one replica away when per-replica q/s "
        "falls below Q and p99 is calm (default 5; must be < "
        "--scale-up-qps — the gap is the hysteresis band)",
    )
    deploy.add_argument(
        "--scale-cooldown-s", type=float, default=10.0, metavar="S",
        help="autoscale: seconds between scaling actions (default 10)",
    )
    deploy.add_argument(
        "--stale-cache-ttl-s", type=float, default=0.0, metavar="S",
        help="router: keep each scope's last good answer S seconds and "
        "serve it marked `X-PIO-Stale: true` ONLY when no replica can "
        "serve at all — a fresh-capable scope never sees a stale "
        "answer. 0 (default) disables the stale-while-down cache",
    )
    deploy.add_argument(
        "--lease-ttl-s", type=float, default=5.0, metavar="S",
        help="endpoint registry: seconds a replica's lease lives "
        "between heartbeats; an entry unrenewed past this is evicted "
        "from every router's ring (default 5)",
    )
    deploy.add_argument(
        "--announce-dir", default=None, metavar="DIR",
        help="replica: announce this server's actually-bound address "
        "(use with --port 0) into the endpoint-registry directory and "
        "heartbeat the lease — how a replica on another host joins a "
        "fleet (set automatically by the fleet supervisor)",
    )
    deploy.add_argument(
        "--announce-host", default="127.0.0.1", metavar="HOST",
        help="replica: the address other hosts reach this replica at "
        "(written into the registry entry; default 127.0.0.1)",
    )
    deploy.add_argument("--feedback", action="store_true")
    deploy.add_argument("--event-server-ip", default="127.0.0.1")
    deploy.add_argument("--event-server-port", type=int, default=7070)
    deploy.add_argument("--accesskey", default="")
    # ---- cross-request micro-batching (predictionio_tpu.serving)
    deploy.add_argument(
        "--batching", action="store_true",
        help="coalesce concurrent /queries.json requests into batched "
        "device dispatches (docs/serving.md)",
    )
    deploy.add_argument(
        "--max-batch-size", type=int, default=32,
        help="most queries per batched dispatch (default 32)",
    )
    deploy.add_argument(
        "--max-batch-delay-ms", type=float, default=2.0,
        help="longest wait for batchmates past the oldest queued request; "
        "0 = dispatch immediately, batch only what is already queued",
    )
    deploy.add_argument(
        "--batch-queue", type=int, default=256,
        help="bounded admission queue size (default 256)",
    )
    deploy.add_argument(
        "--admission-policy", choices=("reject", "block"), default="reject",
        help="full queue behavior: reject = 429 + Retry-After (default), "
        "block = wait up to --admission-timeout-ms, then 503",
    )
    deploy.add_argument(
        "--admission-timeout-ms", type=float, default=1000.0,
        help="block policy only: longest wait for a queue slot",
    )
    deploy.add_argument(
        "--batch-buckets", default="",
        help="comma-separated batch sizes to pad to (default: powers of "
        "two up to --max-batch-size); each bucket is one jit shape",
    )
    deploy.add_argument(
        "--batch-warmup-query", default=None, metavar="JSON",
        help="sample query body; every bucket shape is pre-compiled with "
        "it at startup so live traffic never recompiles",
    )
    # ---- query-path caching & coalescing (predictionio_tpu.serving.cache;
    # docs/performance.md). Each tier is individually opt-in; with none of
    # these flags the serving path is byte-identical to a cache-less build.
    deploy.add_argument(
        "--result-cache", action="store_true",
        help="serve repeated identical queries from an in-memory LRU with "
        "TTL and event-driven invalidation (POST /cache/invalidate.json; "
        "/reload flushes)",
    )
    deploy.add_argument(
        "--result-cache-entries", type=int, default=4096,
        help="most entries the result LRU holds (default 4096)",
    )
    deploy.add_argument(
        "--result-cache-ttl-s", type=float, default=30.0,
        help="seconds a cached result may serve before it expires "
        "(<= 0: no TTL — entries die only by eviction or invalidation)",
    )
    deploy.add_argument(
        "--result-cache-max-mb", type=float, default=64.0,
        help="approximate payload-byte budget of the result LRU in MiB "
        "(<= 0: unbounded)",
    )
    deploy.add_argument(
        "--cache-scope-field", default="user", metavar="FIELD",
        help="query field naming the per-entity invalidation scope "
        "(default 'user'); 'none' disables per-scope invalidation",
    )
    deploy.add_argument(
        "--coalesce", action="store_true",
        help="collapse identical in-flight queries into one scored "
        "computation whose result fans out to all waiters (singleflight; "
        "composes with --batching so a batch never holds duplicate work)",
    )
    deploy.add_argument(
        "--pin-model", action="store_true",
        help="pin factor matrices and the jitted score+top-K programs "
        "device-resident across requests (no per-request staging or "
        "re-trace; bytes pinned reported on /stats.json)",
    )
    deploy.add_argument(
        "--shard-factors", action="store_true",
        help="pin factor SHARDS per device instead of a full replica: "
        "tables split row-wise over a one-axis model mesh of the local "
        "devices, so per-device factor memory is table/num_devices and "
        "catalogs bigger than one device's memory serve; exact top-K "
        "stays tie-stable-identical to the replicated path, and --ann "
        "slabs shard over the same axis (docs/serving.md)",
    )
    # ---- quantized serving (predictionio_tpu.ops.quant; docs/serving.md).
    # Strictly opt-in: without --quantize every table serves f32 and the
    # module is never imported.
    deploy.add_argument(
        "--quantize", choices=("int8",), default=None, metavar="DTYPE",
        help="serve factor tables (and --ann IVF slabs) as int8 codes + "
        "per-row f32 scales: ~4x more catalog per device and ~4x less "
        "gather traffic, recall-guarded by a two-stage kernel (int8 "
        "coarse scan over-fetching max(4k, k+64), f32 rescore of only "
        "the gathered candidates). Composes with --shard-factors "
        "(catalog/S/4 bytes per device), --pin-model, --ann and "
        "--online (touched rows re-quantize on fold-in); /stats.json "
        "grows a 'quant' section (docs/serving.md)",
    )
    # ---- deploy-time AOT serving (predictionio_tpu.workflow.aot;
    # docs/operations.md AOT runbook). Strictly opt-in: without --aot no
    # artifact is read and serving is byte-identical (CI-guarded).
    deploy.add_argument(
        "--aot", action="store_true",
        help="boot by deserializing the instance's `pio train --aot` "
        "exported programs instead of compiling: fingerprint-checked "
        "(jaxlib/backend/shape-bucket), warmed before the first query, "
        "ZERO serve-time compiles. A missing/stale/corrupt artifact set "
        "falls back LOUDLY to the persistent compilation cache (tier 2, "
        "--compilation-cache-dir) and then plain JIT (tier 3) — results "
        "stay bit-identical on every tier; implies --pin-model; "
        "/stats.json grows an 'aot' section with serveTimeCompiles "
        "(docs/operations.md)",
    )
    deploy.add_argument(
        "--compilation-cache-dir", default=None, metavar="DIR",
        help="persistent XLA compilation cache directory shared across "
        "replicas/hosts — the tier-2 fallback when AOT artifacts are "
        "missing or fingerprint-stale (default: "
        "$PIO_COMPILATION_CACHE_DIR or <basedir>/jax_cache; '0' "
        "disables)",
    )
    # ---- approximate retrieval (predictionio_tpu.ops.ivf; docs/serving.md).
    # Strictly opt-in: without --ann every query scores the exact path.
    deploy.add_argument(
        "--ann", action="store_true",
        help="serve top-K through an on-device IVF (clustered) index "
        "built at (re)load time: score nprobe cluster slabs per query "
        "instead of the whole catalog (recall/latency trade-off in "
        "docs/performance.md; /stats.json grows an 'ann' section)",
    )
    deploy.add_argument(
        "--ann-nlist", type=_int_at_least(0), default=0, metavar="N",
        help="k-means cluster count for --ann (default 0 = auto, "
        "~sqrt(catalog items))",
    )
    deploy.add_argument(
        "--ann-nprobe", type=_int_at_least(1), default=8, metavar="N",
        help="clusters scored per query for --ann (default 8); "
        "nprobe >= nlist reproduces exact top-K bit-identically",
    )
    deploy.add_argument(
        "--ann-seed", type=int, default=0,
        help="k-means seed for --ann (index build is deterministic per "
        "(factors, seed))",
    )
    deploy.add_argument(
        "--ann-kmeans-iters", type=_int_at_least(0), default=8, metavar="N",
        help="Lloyd iterations after k-means++ seeding (default 8)",
    )
    # ---- online learning (predictionio_tpu.online; docs/operations.md).
    # Strictly opt-in: without --online no follower thread starts and the
    # serving path is byte-identical to a build without the subsystem.
    deploy.add_argument(
        "--online", action="store_true",
        help="tail the event store and fold fresh events into the live "
        "model without a retrain: incremental ALS fold-in / streaming "
        "two-tower mini-batches, hot-swapped row-by-row with per-scope "
        "cache invalidation and incremental IVF index updates "
        "(/stats.json grows an 'online' section; columnar event store "
        "required)",
    )
    deploy.add_argument(
        "--online-interval-s", type=float, default=1.0, metavar="S",
        help="seconds between watermark polls of the event tail "
        "(default 1.0)",
    )
    deploy.add_argument(
        "--online-batch", type=_int_at_least(1), default=4096, metavar="N",
        help="most events folded per batch; larger bursts fold over "
        "consecutive batches (default 4096)",
    )
    deploy.add_argument(
        "--online-algos", default="", metavar="NAMES",
        help="comma-separated algorithm-class allowlist (e.g. "
        "'als,twotower'); empty (default) = every deployed algorithm "
        "that implements the online hooks",
    )
    deploy.add_argument(
        "--online-prior-weight", type=float, default=1.0, metavar="W",
        help="anchor strength toward each entity's trained row in the "
        "fold-in re-solve; 0 = pure fold-in from online-observed events "
        "(default 1.0)",
    )
    deploy.add_argument(
        "--online-from-start", action="store_true",
        help="fold events already in the store at deploy time too "
        "(default: start at the end of the stream)",
    )
    # ---- experimentation (predictionio_tpu.experiments; docs/serving.md).
    # Strictly opt-in: without --explore/--variants the package is never
    # imported and serving is byte-identical (CI-guarded).
    deploy.add_argument(
        "--explore", choices=("epsilon", "thompson"), default=None,
        metavar="POLICY",
        help="rerank each query's top-K through a bandit exploration "
        "policy (epsilon-greedy or Thompson sampling over per-item "
        "posteriors); reward events fold back through --online's "
        "follower or POST /experiments/reward.json, and /stats.json "
        "grows an 'explore' section with the cumulative regret counter "
        "(docs/serving.md)",
    )
    deploy.add_argument(
        "--explore-epsilon", type=float, default=0.1, metavar="E",
        help="epsilon policy: probability a query serves an exploration "
        "slate instead of the exploit ranking (default 0.1)",
    )
    deploy.add_argument(
        "--explore-seed", type=int, default=0,
        help="PRNG seed of the exploration policy (per-query keys are "
        "folded from a served-query counter; default 0)",
    )
    deploy.add_argument(
        "--explore-reward-event", default="reward", metavar="NAME",
        help="event name counted as bandit reward when folding the event "
        "tail back into the policy posterior (default 'reward')",
    )
    deploy.add_argument(
        "--variants", default="", metavar="NAME[:W],NAME[:W],...",
        help="router-only (requires --replicas): split /queries.json "
        "traffic into weighted A/B variants sticky by cache scope — "
        "assignment is a pure hash of (salt, weights, scope), so it "
        "survives router restarts and replica failover; per-variant "
        "q/s, p50/p99 and reward counters appear on the router's "
        "/stats.json, and POST /experiments/promote.json collapses "
        "traffic onto the winner and rolls it fleet-wide "
        "(docs/operations.md experiment runbook)",
    )
    # ---- resilience (predictionio_tpu.resilience; docs/operations.md).
    # Defaults are the do-nothing configuration: single-attempt storage
    # calls, no breaker — identical to a build without these flags.
    deploy.add_argument(
        "--retry-reads", type=int, default=0, metavar="N",
        help="retry idempotent storage reads up to N extra times with "
        "exponential backoff + full jitter (default 0 = single attempt)",
    )
    deploy.add_argument(
        "--retry-writes", action="store_true",
        help="also retry storage writes; only safe when writes are "
        "idempotent (client-generated ids / upserts)",
    )
    deploy.add_argument(
        "--breaker-threshold", type=int, default=0, metavar="N",
        help="consecutive storage transport failures that open the "
        "circuit breaker (fail fast instead of stacking timeouts); "
        "0 = breaker disabled",
    )
    deploy.add_argument(
        "--breaker-reset-s", type=float, default=5.0,
        help="seconds an open breaker waits before letting one probe "
        "request through (half-open)",
    )
    deploy.add_argument(
        "--rpc-deadline-s", type=float, default=0.0,
        help="overall per-call budget consumed across retries, so a "
        "retried storage call never exceeds it (0 = per-attempt "
        "timeout only)",
    )
    deploy.add_argument(
        "--feedback-timeout", type=float, default=5.0, metavar="S",
        help="socket timeout for feedback event posts (worker thread, "
        "never the query path)",
    )
    deploy.add_argument(
        "--feedback-block-ms", type=float, default=0.0,
        help="when the feedback queue is full, block the query thread up "
        "to this long for a slot before dropping (default 0 = drop "
        "immediately)",
    )
    deploy.add_argument(
        "--no-feedback-blocking", action="store_true",
        help="force the feedback loop to never block the query path "
        "(overrides --feedback-block-ms; this is also the default)",
    )
    deploy.add_argument(
        "--feedback-breaker-threshold", type=int, default=0, metavar="N",
        help="consecutive failed feedback posts that open the feedback "
        "breaker (drop instantly while the event server is down instead "
        "of paying a connect timeout per event); 0 = disabled",
    )
    deploy.add_argument(
        "--feedback-breaker-reset-s", type=float, default=5.0,
        help="seconds an open feedback breaker waits before probing the "
        "event server again",
    )
    add_ssl_flags(deploy)
    add_lifecycle_flags(deploy)

    # ---- undeploy
    und = sub.add_parser(
        "undeploy", help="stop a deployed engine server via GET /stop"
    )
    und.add_argument("--ip", default="127.0.0.1")
    und.add_argument("--port", type=int, default=8000)
    und.add_argument("--https", action="store_true")
    und.add_argument(
        "--insecure", action="store_true",
        help="skip TLS certificate verification (self-signed deployments)",
    )
    und.add_argument(
        "--token", default=None,
        help="deployment stop token (default: read from the basedir token "
        "file written by `pio deploy` for this port)",
    )

    # ---- eval
    ev = sub.add_parser("eval", help="run an evaluation sweep")
    ev.add_argument("evaluation", help="import path of the Evaluation object")
    ev.add_argument(
        "params_generator",
        nargs="?",
        help="import path of the EngineParamsGenerator (optional if the "
        "Evaluation supplies engine_params_list)",
    )
    ev.add_argument("--batch", default="")
    ev.add_argument("--output-path", default="best.json")
    ev.add_argument(
        "--grid", action="store_true",
        help="train and score every candidate in ONE vmapped jit per "
        "fold shape (one compile per sweep, not per candidate) when the "
        "generator sweeps numeric ALS axes (lambda/alpha/seed); any "
        "non-vmappable sweep falls back to the sequential evaluator "
        "with the same output contract (docs/evaluation.md)",
    )
    ev.add_argument(
        "--promote-to", default=None, metavar="URL",
        help="after the sweep, POST the winning candidate's variant to "
        "URL/experiments/promote.json on a fleet router deployed with "
        "--variants — the sweep's candidate order must match the "
        "router's variant order (closing the eval → promote loop "
        "without an operator POST). Example: --promote-to "
        "http://127.0.0.1:8000",
    )

    # ---- eventserver
    es = sub.add_parser("eventserver", help="start the event server")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true")
    # ---- background compaction scheduler (docs/operations.md). Strictly
    # opt-in: 0 (default) starts no scheduler thread — tail compaction
    # stays the manual `pio app compact` it always was (CI-guarded).
    es.add_argument(
        "--compact-interval-s", type=float, default=0.0, metavar="S",
        help="sweep the columnar event store every S seconds and compact "
        "streams past the watermarks below (0 = no background "
        "compaction, the historical default; requires the columnar "
        "EVENTDATA backend)",
    )
    es.add_argument(
        "--compact-tail-mb", type=float, default=32.0, metavar="MB",
        help="tail-size watermark: compact a stream whose live JSONL "
        "tail exceeds MB mebibytes (default 32)",
    )
    es.add_argument(
        "--compact-dead-tombstones", type=int, default=10000, metavar="N",
        help="dead-bytes watermark: compact a stream with >= N "
        "tombstoned tail events (default 10000)",
    )
    es.add_argument(
        "--compact-min-interval-s", type=float, default=30.0, metavar="S",
        help="rate limit: never compact the same stream twice within S "
        "seconds (default 30)",
    )
    add_ssl_flags(es)
    add_lifecycle_flags(es)

    # ---- dashboard
    db = sub.add_parser("dashboard", help="start the evaluation dashboard")
    db.add_argument("--ip", default="127.0.0.1")
    db.add_argument("--port", type=int, default=9000)
    add_ssl_flags(db)
    add_lifecycle_flags(db)

    # ---- adminserver
    adm = sub.add_parser("adminserver", help="start the admin REST server")
    adm.add_argument("--ip", default="127.0.0.1")
    adm.add_argument("--port", type=int, default=7071)
    add_ssl_flags(adm)
    add_lifecycle_flags(adm)

    # ---- template
    tpl = sub.add_parser("template", help="built-in engine templates")
    tpl_sub = tpl.add_subparsers(dest="template_command", required=True)
    tpl_sub.add_parser("list")
    tpl_get = tpl_sub.add_parser("get")
    tpl_get.add_argument("name")
    tpl_get.add_argument("directory")
    tpl_get.add_argument("--appname", default="MyApp")

    # ---- storageserver
    ss = sub.add_parser(
        "storageserver",
        help="expose this host's storage backend over the network "
        "(server side of the TYPE=remote driver)",
    )
    ss.add_argument(
        "--ip", default="127.0.0.1",
        help="bind address; binding beyond loopback requires --secret "
        "(the server grants read/write on apps, keys, events and models)",
    )
    ss.add_argument("--port", type=int, default=7072)
    ss.add_argument(
        "--secret", default=None,
        help="shared secret clients must present (default: $PIO_STORAGE_SERVER_SECRET)",
    )
    add_ssl_flags(ss)
    add_lifecycle_flags(ss)

    # ---- chaos-ingest (predictionio_tpu.resilience.chaos)
    ch = sub.add_parser(
        "chaos-ingest",
        help="crash-safety drill: SIGKILL a real event-server subprocess "
        "under concurrent retrying writers and verify exactly-once "
        "ingestion, clean recovery, and graceful drain",
    )
    ch.add_argument("--cycles", type=int, default=3, help="SIGKILL/restart cycles")
    ch.add_argument("--writers", type=int, default=4, help="concurrent writer threads")
    ch.add_argument(
        "--events", type=int, default=120,
        help="events per writer across the whole run",
    )
    ch.add_argument(
        "--backend", choices=("sqlite", "columnar"), default="sqlite",
        help="EVENTDATA backend under test (columnar runs with FSYNC=true)",
    )
    ch.add_argument("--seed", type=int, default=0, help="kill-schedule RNG seed")
    ch.add_argument(
        "--bulk-events", type=int, default=1000,
        help="events streamed through POST /events/bulk.json in the "
        "bulk-writer phase (SIGKILL lands mid-stream; 0 disables)",
    )
    ch.add_argument(
        "--drain-deadline-s", type=float, default=5.0,
        help="drain deadline for the final SIGTERM-under-load phase",
    )
    ch.add_argument(
        "--partitions", type=_int_at_least(1), default=1,
        help=">1 adds the kill-one-partition drill: a columnar store "
        "with PARTITIONS=P, one partition's appender chaos-killed "
        "mid-bulk-stream plus a whole-server SIGKILL mid-retry — zero "
        "acked loss, zero duplicates, surviving partitions never stall, "
        "the killed partition catches up",
    )
    ch.add_argument(
        "--replication", type=int, default=0,
        help="with --partitions: replicas per partition (0 off, else "
        ">= 2); the drill also kills one non-leader replica and asserts "
        "loud quorum-loss degradation plus replica catch-up",
    )
    ch.add_argument(
        "--ack-quorum", type=int, default=0,
        help="fsync-durable copies required per ack (default: majority "
        "of --replication)",
    )
    ch.add_argument(
        "--keep", action="store_true",
        help="keep the scratch storage directory for inspection",
    )

    # ---- chaos-serve (predictionio_tpu.resilience.chaos; ISSUE 15)
    cs = sub.add_parser(
        "chaos-serve",
        help="serving-fleet drill: train a tiny model, deploy "
        "`--replicas N` behind the router, SIGKILL replicas under >= 16 "
        "concurrent query clients and rolling-/reload the fleet — "
        "verifying ZERO failed queries, zero cross-generation results, "
        "and p99 recovery within one breaker reset",
    )
    cs.add_argument(
        "--replicas", type=_int_at_least(1), default=2,
        help="fleet size for the kill/rolling phases (default 2)",
    )
    cs.add_argument(
        "--clients", type=_int_at_least(1), default=16,
        help="concurrent query clients (default 16)",
    )
    cs.add_argument(
        "--kills", type=_int_at_least(1), default=1,
        help="replica SIGKILLs during the kill phase (default 1)",
    )
    cs.add_argument(
        "--seconds", type=float, default=6.0,
        help="kill-phase duration in seconds (default 6)",
    )
    cs.add_argument(
        "--reloads", type=_int_at_least(0), default=1,
        help="rolling /reload rotations under load (default 1)",
    )
    cs.add_argument(
        "--events", type=int, default=400,
        help="synthetic training events (default 400)",
    )
    cs.add_argument("--seed", type=int, default=0, help="workload RNG seed")
    cs.add_argument(
        "--aot", action="store_true",
        help="run the drill AOT-on: `pio train --aot` exports the "
        "generation's programs, replicas deploy with --aot, and the "
        "rolling-reload phase additionally asserts ZERO serve-time "
        "compiles across the full rotation (reload-p99 gated against "
        "steady-state; docs/operations.md AOT runbook)",
    )
    cs.add_argument(
        "--sharded-point", action="store_true",
        help="also measure one fleet whose replicas serve with "
        "--shard-factors (8-way virtual host mesh)",
    )
    cs.add_argument(
        "--keep", action="store_true",
        help="keep the scratch storage directory for inspection",
    )

    # ---- chaos-fleet (predictionio_tpu.resilience.chaos; ISSUE 17)
    cf = sub.add_parser(
        "chaos-fleet",
        help="cross-host elastic-fleet drill: two 'hosts' (separate "
        "basedirs) share one endpoint registry behind an HA router "
        "pair; SIGKILL an entire host's fleet under concurrent "
        "never-retrying clients (zero failed queries, the survivor "
        "absorbs, the dead host rejoins via the registry), drive the "
        "autoscaler through a watermark scale-up and a drain-aware "
        "scale-down (zero in-flight loss), and prove the "
        "stale-while-down cache serves marked answers only when every "
        "replica is dead",
    )
    cf.add_argument(
        "--replicas-per-host", type=_int_at_least(1), default=1,
        help="replica fleet size on each 'host' (default 1)",
    )
    cf.add_argument(
        "--clients", type=_int_at_least(1), default=16,
        help="concurrent query clients (default 16)",
    )
    cf.add_argument(
        "--seconds", type=float, default=6.0,
        help="host-kill phase duration in seconds (default 6)",
    )
    cf.add_argument(
        "--events", type=int, default=400,
        help="synthetic training events (default 400)",
    )
    cf.add_argument(
        "--lease-ttl-s", type=float, default=1.0,
        help="endpoint-registry lease TTL under test (default 1.0)",
    )
    cf.add_argument("--seed", type=int, default=0, help="workload RNG seed")
    cf.add_argument(
        "--skip-autoscale", action="store_true",
        help="skip the autoscaler phase (host-kill + stale only)",
    )
    cf.add_argument(
        "--keep", action="store_true",
        help="keep the scratch storage directories for inspection",
    )

    # ---- batchpredict
    bp = sub.add_parser("batchpredict", help="bulk predictions from a query file")
    bp.add_argument("--engine-json", default="engine.json")
    bp.add_argument("--input", required=True, help="JSON-lines query file")
    bp.add_argument("--output", required=True, help="JSON-lines results file")
    bp.add_argument("--engine-instance-id")

    # ---- build (no-op parity)
    sub.add_parser(
        "build", help="no-op (Python engines need no compilation; kept for parity)"
    )

    # ---- run: execute a command with the storage/config env injected
    # (parity: Console.scala `pio run <main class>` launching user code
    # against the configured storage; here the subprocess inherits the
    # resolved PIO_* env so ad-hoc scripts see the same storage the CLI
    # does)
    run_p = sub.add_parser(
        "run", help="run a command with the framework environment injected"
    )
    run_p.add_argument(
        "run_args", nargs=argparse.REMAINDER,
        help="command and arguments (e.g. `pio run python myscript.py`)",
    )

    # ---- lint (piolint: predictionio_tpu.analysis; docs/development.md)
    lint = sub.add_parser(
        "lint",
        help="run piolint — AST layering/concurrency/JAX-hygiene analysis "
        "over the source tree (exits 1 on any non-baselined finding)",
    )
    lint.add_argument(
        "--root", default=None,
        help="tree to lint (default: this checkout's repo root)",
    )
    lint.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="text = file:line diagnostics; json = machine-readable "
        "summary + findings; sarif = SARIF 2.1.0 for inline code-review "
        "annotations (new findings level=error, baselined level=note)",
    )
    lint.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline file (default: <root>/piolint-baseline.json)",
    )
    lint.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current findings (keeps "
        "existing justifications); add one-line justifications before "
        "committing",
    )
    lint.add_argument(
        "--prune-baseline", action="store_true",
        help="drop baseline entries no current finding matches (fixed "
        "findings) AND compile-budget.json entries whose entrypoint no "
        "longer exists, without accepting anything new; CI fails on "
        "stale entries, this is the one-command cleanup",
    )
    lint.add_argument(
        "--witness", default=None, metavar="REPORT",
        help="cross-check a recorded lock-witness report (pytest "
        "--lock-witness or pio tsan JSON output) against the static "
        "lock graph, both directions: a witnessed acquisition-order "
        "edge missing from the static digraph (analyzer gap) or a "
        "static cycle that neither manifested nor carries a "
        "lock-witness-waivers.json entry fails the lint",
    )

    # ---- tsan (runtime lock-witness: predictionio_tpu.analysis.witness)
    tsan = sub.add_parser(
        "tsan",
        help="run a pio command under the lock-witness sanitizer: "
        "records the lock acquisition-order digraph, hold-time "
        "percentiles and sleeps-under-lock, reports witnessed "
        "lock-order inversions, and classifies every static PIO207 "
        "cycle as CONFIRMED or PLAUSIBLE (docs/operations.md)",
    )
    tsan.add_argument(
        "--report", default=None, metavar="FILE",
        help="also write the JSON report to FILE",
    )
    tsan.add_argument(
        "--long-hold-ms", type=float, default=50.0,
        help="hold time above which an acquisition counts as a long "
        "hold (default 50)",
    )
    tsan.add_argument(
        "tsan_args", nargs=argparse.REMAINDER,
        help="command to run under the witness, e.g. "
        "`pio tsan -- chaos-ingest --cycles 1`",
    )

    # ---- jitwitness (runtime jit-witness: predictionio_tpu.analysis
    # .jit_witness — the compile/transfer sibling of `pio tsan`)
    jitw = sub.add_parser(
        "jitwitness",
        help="run a pio command under the jit-witness sanitizer: counts "
        "XLA compiles per call site (with first-compile latency), "
        "device->host transfer bytes, and per-call jax.jit "
        "constructions; classifies every static PIO306-308 finding "
        "CONFIRMED or PLAUSIBLE and checks the compile-budget.json "
        "ledger (docs/operations.md)",
    )
    jitw.add_argument(
        "--report", default=None, metavar="FILE",
        help="also write the JSON report to FILE",
    )
    jitw.add_argument(
        "--ledger", default=None, metavar="FILE",
        help="compile-budget ledger (default: <repo>/compile-budget.json)",
    )
    jitw.add_argument(
        "jitwitness_args", nargs=argparse.REMAINDER,
        help="command to run under the witness, e.g. "
        "`pio jitwitness -- batchpredict --input q.json --output o.json`",
    )

    # ---- upgrade (informational parity stub)
    sub.add_parser(
        "upgrade",
        help="print upgrade guidance (pip-managed; no in-place upgrader)",
    )
    return p


def _parse_mesh(spec: str):
    from predictionio_tpu.controller.context import local_context, mesh_context

    if spec == "none":
        return local_context()
    if spec == "auto":
        return mesh_context()
    sizes = {}
    for part in spec.split(","):
        axis, _, n = part.partition("=")
        sizes[axis.strip()] = int(n)
    return mesh_context(
        axis_sizes=list(sizes.values()), axis_names=list(sizes.keys())
    )


def _setup_compilation_cache(explicit: str | None = None) -> None:
    """Persist compiled XLA programs across runs: a repeat ``pio train``
    on the same shapes skips the (tens-of-seconds, possibly remote)
    compile entirely. Precedence: the ``--compilation-cache-dir`` flag
    (``explicit``), then ``PIO_COMPILATION_CACHE_DIR``, then the
    ``<PIO_FS_BASEDIR>/jax_cache`` default; ``0`` disables. Under
    ``--aot`` this same directory doubles as the tier-2 fallback shared
    across replicas (docs/operations.md AOT runbook). Costs no jax
    import of its own: env vars configure a not-yet-imported jax lazily,
    and only an already-imported jax (preloaded interpreters) gets
    config.update."""
    if explicit is None:
        explicit = os.environ.get("PIO_COMPILATION_CACHE_DIR")
    if explicit == "0":
        return
    if explicit:
        cache_dir = os.path.expanduser(explicit)
    else:
        from predictionio_tpu.data.storage import Storage

        cache_dir = os.path.join(Storage.base_dir(), "jax_cache")
    if "jax" in sys.modules:
        jax = sys.modules["jax"]
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:
            if explicit:
                print(
                    f"WARNING: could not enable the compilation cache at "
                    f"{cache_dir}: {e}",
                    file=sys.stderr,
                )
    else:
        # jax reads these at import; operator-set JAX_* values win
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
        os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")


def _train_aot_export(variant, ctx, instance) -> None:
    """``pio train --aot``: lower + serialize the just-trained
    instance's serving programs (workflow/aot.py) and stamp the
    artifact set into the fleet model registry beside the generation.

    The instance is re-hydrated exactly the way ``pio deploy`` will
    (``prepare_deploy`` over the stored blob), so what is exported is
    what will serve. A failed export never fails the train — artifacts
    are an optimization and deploy falls back loudly to tier 2/3."""
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.fleet.registry import ModelRegistry
    from predictionio_tpu.workflow import aot

    engine = variant.build_engine()
    engine_params = engine.params_from_json(variant.raw)
    model = Storage.get_model_data_models().get(instance.id)
    if model is None:
        print(
            "WARNING: --aot: no model blob stored for this instance; "
            "nothing to export",
            file=sys.stderr,
        )
        return
    _, pairs = engine.prepare_deploy(
        ctx, engine_params, instance.id, model.models
    )
    base_dir = Storage.base_dir()
    root = os.path.join(base_dir, "fleet", "aot")
    manifest = aot.export_instance(pairs, instance.id, root)
    if manifest is None:
        print(
            "WARNING: --aot: no algorithm exported a serving program "
            "(algorithms without the aot_export_for_serving hook "
            "contribute nothing); `pio deploy --aot` will fall back to "
            "tier 2/3",
            file=sys.stderr,
        )
        return
    total = sum(int(e.get("bytes", 0)) for e in manifest.get("entries", []))
    record = ModelRegistry(os.path.join(base_dir, "fleet")).publish(
        instance.id,
        meta={"publisher": "train --aot"},
        artifacts={
            "dir": aot.artifact_dir(root, instance.id),
            "programs": len(manifest.get("entries", [])),
            "bytes": total,
            "fingerprint": manifest.get("fingerprint", {}),
        },
    )
    print(
        f"AOT export: {len(manifest.get('entries', []))} programs "
        f"({total} bytes) for instance {instance.id} "
        f"(fleet generation {record.generation})"
    )


def _replica_argv(args, replica_id: str, announce_dir: str) -> list[str]:
    """Reconstruct a single-replica ``deploy`` argv from the parsed fleet
    args: every non-default deploy flag is carried over (so
    ``--shard-factors``/``--quantize``/``--ann``/... compose per
    replica), while the fleet/router flags, the public bind, and TLS are
    stripped — replicas listen plaintext on loopback (the router
    terminates TLS) with their own identity. Each replica binds **port
    0** and self-reports its actually-bound address through the endpoint
    registry (``--announce-dir``), so no port is ever picked before the
    bind — the pick-then-spawn race is structurally impossible. Derived
    from the parsed namespace, not raw argv, so ``--flag=value``
    spellings and future flags need no special-casing."""
    defaults = build_parser().parse_args(["deploy"])
    skip = {
        "command",
        # fleet/router-only flags never reach a replica
        "replicas", "replica_id", "probe_interval_s", "failover_retries",
        "hedge_ms", "fleet_breaker_threshold", "fleet_breaker_reset_s",
        "variants", "endpoint_registry", "router_only", "autoscale",
        "scale_up_qps", "scale_up_p99_ms", "scale_down_qps",
        "scale_cooldown_s", "stale_cache_ttl_s",
        # rebound below / router-terminated
        "ip", "port", "cert", "key", "announce_dir", "announce_host",
        "lease_ttl_s",
    }
    argv = ["-m", "predictionio_tpu.tools.console", "deploy"]
    for name, value in sorted(vars(args).items()):
        if name in skip or value == getattr(defaults, name, None):
            continue
        if value is None or value is False:
            continue
        flag = "--" + name.replace("_", "-")
        if value is True:
            argv.append(flag)
        else:
            argv.extend([flag, str(value)])
    argv.extend(
        [
            "--ip", "127.0.0.1", "--port", "0",
            "--replica-id", replica_id,
            "--announce-dir", announce_dir,
            "--announce-host", args.announce_host,
            "--lease-ttl-s", str(args.lease_ttl_s),
        ]
    )
    return argv


def _deploy_fleet(args) -> int:
    """``pio deploy --replicas N`` (and ``--router-only``): spawn the
    replica subprocesses under the self-healing supervisor and serve the
    fleet router on the public port. Replicas bind port 0 and join the
    ring by announcing their bound address through the shared endpoint
    registry — the router starts with an EMPTY ring and reconciles
    membership from the registry every probe interval, so replicas on
    other hosts (same ``--endpoint-registry`` directory) join the same
    ring. SIGTERM/SIGINT, ``GET /stop`` (token-gated) and ``pio
    undeploy`` all stop the WHOLE fleet — replicas must never outlive
    their router."""
    import atexit
    import signal as _signal
    import threading

    from predictionio_tpu.api.http import serve
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.fleet import (
        EndpointRegistry,
        FleetSupervisor,
        ModelRegistry,
        ReplicaSpec,
        RouterConfig,
        RouterService,
        fleet_state_path,
    )
    from predictionio_tpu.tools import commands

    if args.router_only and args.autoscale:
        raise SystemExit(
            "--router-only serves no supervisor to scale; run --autoscale "
            "on the fleet that owns the replicas"
        )
    base_dir = Storage.base_dir()
    endpoints_dir = args.endpoint_registry or os.path.join(
        base_dir, "fleet", "endpoints"
    )
    endpoint_registry = EndpointRegistry(
        endpoints_dir, lease_ttl_s=args.lease_ttl_s
    )
    # With an EXPLICIT shared registry, many supervisors feed one ring —
    # replica ids minted per-host (r0, scale1, ...) would collide across
    # hosts and silently overwrite each other's registry entries, so
    # each id carries a host-unique token. The default (private)
    # registry keeps the bare ids.
    if args.endpoint_registry:
        import socket

        host_token = f"{socket.gethostname().split('.')[0]}-{os.getpid()}"

        def _rid(base: str) -> str:
            return f"{base}@{host_token}"
    else:
        def _rid(base: str) -> str:
            return base

    specs: list[ReplicaSpec] = []
    if not args.router_only:
        for i in range(args.replicas):
            rid = _rid(f"r{i}")
            specs.append(
                ReplicaSpec(
                    rid, 0, tuple(_replica_argv(args, rid, endpoints_dir))
                )
            )
    config = RouterConfig(
        probe_interval_s=args.probe_interval_s,
        failover_retries=args.failover_retries,
        hedge_ms=args.hedge_ms,
        breaker_threshold=args.fleet_breaker_threshold,
        breaker_reset_s=args.fleet_breaker_reset_s,
        scope_field=(
            None
            if args.cache_scope_field.lower() in ("none", "")
            else args.cache_scope_field
        ),
        stale_cache_ttl_s=args.stale_cache_ttl_s,
    )
    registry = ModelRegistry(os.path.join(base_dir, "fleet"))
    split = None
    if args.variants:
        # lazy: without --variants no experiments module is imported
        from predictionio_tpu.experiments.split import SplitConfig, TrafficSplit

        split = TrafficSplit(SplitConfig.parse(args.variants))
        print(
            "A/B experiment: "
            + ", ".join(
                f"{v.name}:{v.weight:g}" for v in split.config.variants
            )
            + f" (sticky by {config.scope_field or 'whole-body hash'})"
        )
    router = RouterService(
        [], config, registry=registry, split=split,
        endpoint_registry=endpoint_registry,
    )
    supervisor = None
    autoscaler = None
    if not args.router_only:
        supervisor = FleetSupervisor(
            specs, fleet_state_path(base_dir, args.port), args.port
        )
        supervisor.start()
        if args.autoscale:
            from predictionio_tpu.fleet.autoscaler import (
                Autoscaler,
                AutoscalerConfig,
            )

            lo, _, hi = args.autoscale.partition(":")
            try:
                scale_cfg = AutoscalerConfig(
                    min_replicas=int(lo),
                    max_replicas=int(hi or lo),
                    scale_up_qps=args.scale_up_qps,
                    scale_up_p99_ms=args.scale_up_p99_ms,
                    scale_down_qps=args.scale_down_qps,
                    cooldown_s=args.scale_cooldown_s,
                )
            except ValueError as e:
                raise SystemExit(f"--autoscale: {e}")
            autoscaler = Autoscaler(
                router,
                supervisor,
                lambda rid: ReplicaSpec(
                    _rid(rid), 0,
                    tuple(_replica_argv(args, _rid(rid), endpoints_dir)),
                ),
                scale_cfg,
            )
            autoscaler.start()
    router.start()
    stopped = threading.Event()

    def shutdown_fleet():
        if stopped.is_set():
            return
        stopped.set()
        if autoscaler is not None:
            autoscaler.stop()
        router.close()
        if supervisor is not None:
            supervisor.stop()

    atexit.register(shutdown_fleet)

    def wire_stop(server):
        router.stop_token = commands.write_stop_token(args.port)

        def stop_all():
            def run():
                shutdown_fleet()
                server.shutdown()

            threading.Thread(target=run, daemon=True).start()

        router.stop_server = stop_all
        # first signal stops the fleet (replicas get SIGTERM, so each
        # drains per its own --drain-deadline-s, withdraws its registry
        # entry, and only then exits); the router's listener follows
        _signal.signal(_signal.SIGTERM, lambda s, f: stop_all())
        _signal.signal(_signal.SIGINT, lambda s, f: stop_all())

    role = "HA router" if args.router_only else "router"
    print(
        f"Fleet is deployed: {role} on {args.ip}:{args.port}, "
        f"{len(specs)} replica(s) self-reporting via {endpoints_dir}"
        + (f", autoscale {args.autoscale}" if autoscaler else "")
    )
    serve(
        router.dispatch, args.ip, args.port,
        ssl_context=_ssl_from_args(args), ready_callback=wire_stop,
    )
    shutdown_fleet()
    return 0


def _start_announcer(args, service, server) -> None:
    """Replica self-report (ISSUE 17): publish this server's
    *actually-bound* address (``--port 0`` capable — the port is read
    off the live socket, never picked in advance) into the shared
    endpoint registry, heartbeat the lease, and withdraw on drain/exit
    so clean retirement leaves no entry to expire. Lazy import: only
    ``--announce-dir`` pays for the fleet module."""
    import atexit
    import threading

    from predictionio_tpu.fleet.registry import EndpointRegistry

    host, port = args.announce_host, server.server_address[1]
    rid = args.replica_id or f"pid{os.getpid()}"
    registry = EndpointRegistry(
        args.announce_dir, lease_ttl_s=args.lease_ttl_s
    )
    stop = threading.Event()

    def generation() -> int:
        try:
            return int(getattr(service, "model_generation", 0) or 0)
        except (TypeError, ValueError):
            return 0

    registry.announce(rid, host, port, generation=generation())
    print(
        f"Announced replica {rid} at {host}:{port} in "
        f"{args.announce_dir} (lease {args.lease_ttl_s:g}s)"
    )

    def heartbeat() -> None:
        interval = max(0.05, args.lease_ttl_s / 3.0)
        while not stop.wait(interval):
            try:
                registry.heartbeat(rid, host, port, generation=generation())
            except OSError:
                pass  # sharedfs hiccup: the next beat renews the lease

    threading.Thread(
        target=heartbeat, name="endpoint-heartbeat", daemon=True
    ).start()

    def withdraw() -> None:
        stop.set()
        try:
            registry.withdraw(rid)
        except OSError:
            pass

    # drain withdraws FIRST (routers reconcile this replica out before
    # the listener closes); atexit covers non-drain exits
    if hasattr(service, "on_close"):
        service.on_close.append(withdraw)
    atexit.register(withdraw)


def _lifecycle_from_args(args):
    """Opt-in :class:`~predictionio_tpu.api.lifecycle.DrainManager` from
    ``--drain-deadline-s``. 0 (the default) returns None — signals keep
    their historical immediate-exit behavior, guarded by
    tests/test_ci_guards.py. When enabled, SIGTERM/SIGINT handlers are
    installed here (console main runs on the main thread, a signal-API
    requirement) and the process-wide storage flush is registered as the
    final drain hook; the served service's own ``drain`` hook (e.g. the
    query server's batcher close) is discovered by the HTTP wrapper and
    runs before it."""
    deadline = getattr(args, "drain_deadline_s", 0.0)
    if not deadline or deadline <= 0:
        return None
    from predictionio_tpu import resilience
    from predictionio_tpu.api.lifecycle import DrainManager
    from predictionio_tpu.data.storage import Storage

    lifecycle = DrainManager(deadline)
    lifecycle.install_signals()
    lifecycle.add_drain_hook(Storage.close)
    # drain state (in-flight count, rejections) joins the resilience
    # section of GET /stats.json on servers that serve one
    resilience.register_stats("lifecycle", lifecycle)
    return lifecycle


def _promote_winner(router_url: str, result) -> dict:
    """``pio eval --grid --promote-to URL``: close the sweep → promote
    loop (ROADMAP item 4's leftover). Maps the sweep's winning candidate
    INDEX onto the router's variant ORDER — ``GET /experiments.json``
    lists variants in ``--variants`` order, so the operator deploys one
    variant per sweep candidate in the same order — then POSTs the
    promotion (which rolls the fleet). Loud ``SystemExit`` on any
    mismatch: a silently mis-mapped promotion would roll the wrong model
    fleet-wide."""
    import urllib.error
    import urllib.request

    url = router_url.rstrip("/")
    try:
        with urllib.request.urlopen(
            url + "/experiments.json", timeout=10
        ) as r:
            experiments = json.load(r)
    except (urllib.error.URLError, json.JSONDecodeError, OSError) as e:
        raise SystemExit(
            f"--promote-to: cannot read {url}/experiments.json: {e}"
        )
    variants = [v.get("name") for v in experiments.get("variants", [])]
    candidates = len(result.engine_params_scores)
    if len(variants) != candidates:
        raise SystemExit(
            f"--promote-to: the router serves {len(variants)} variant(s) "
            f"{variants} but the sweep scored {candidates} candidate(s) — "
            "refusing to guess the mapping; deploy --variants with one "
            "variant per sweep candidate, in the same order"
        )
    winner = variants[result.best_index]
    req = urllib.request.Request(
        url + "/experiments/promote.json",
        data=json.dumps({"variant": winner}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        # the promotion rolls every replica through /reload — budget it
        # like a rolling reload, not like a GET
        with urllib.request.urlopen(req, timeout=600) as r:
            payload = json.load(r)
            status = r.status
    except urllib.error.HTTPError as e:
        raise SystemExit(
            f"--promote-to: promotion of {winner!r} failed "
            f"({e.code}): {e.read()[:300]!r}"
        )
    except (urllib.error.URLError, json.JSONDecodeError, OSError) as e:
        raise SystemExit(f"--promote-to: promotion of {winner!r} failed: {e}")
    return {
        "promotedVariant": winner,
        "bestIndex": result.best_index,
        "status": status,
        "router": payload,
    }


def _ssl_from_args(args):
    """TLS context from --cert/--key flags, falling back to the
    PIO_SSL_CERT / PIO_SSL_KEY env vars; None = plain http. A
    half-specified pair is an error — silently starting plain HTTP when
    the operator passed --cert would leak traffic they meant to encrypt."""
    from predictionio_tpu.api.http import make_ssl_context, ssl_context_from_env

    cert = getattr(args, "cert", None)
    key = getattr(args, "key", None)
    if bool(cert) != bool(key):
        raise ValueError("--cert and --key must be given together")
    if cert and key:
        return make_ssl_context(cert, key)
    return ssl_context_from_env()


def main(argv: list[str] | None = None) -> int:
    # PIO_JAX_PLATFORMS=cpu forces the JAX platform even when the
    # interpreter preloaded jax with a different one (CPU CI runs,
    # multi-host rehearsals on hosts whose default platform is a single
    # accelerator). Must happen before any backend initializes.
    platform_override = os.environ.get("PIO_JAX_PLATFORMS")
    if platform_override:
        import jax

        jax.config.update("jax_platforms", platform_override)
    args = build_parser().parse_args(argv)
    _setup_compilation_cache(
        explicit=getattr(args, "compilation_cache_dir", None)
    )
    cmd = args.command
    try:
        if cmd == "version":
            print(__version__)
        elif cmd == "status":
            results = commands.status_check()
            return 0 if results["ok"] else 1
        elif cmd == "app":
            ac = args.app_command
            if ac == "new":
                commands.app_new(args.name, args.description, args.access_key)
            elif ac == "list":
                commands.app_list()
            elif ac == "show":
                commands.app_show(args.name)
            elif ac == "delete":
                commands.app_delete(args.name)
            elif ac == "data-delete":
                commands.app_data_delete(args.name, args.channel)
            elif ac == "compact":
                commands.app_compact(args.name, args.channel)
            elif ac == "channel-new":
                commands.channel_new(args.name, args.channel)
            elif ac == "channel-delete":
                commands.channel_delete(args.name, args.channel)
        elif cmd == "accesskey":
            akc = args.accesskey_command
            if akc == "new":
                commands.accesskey_new(args.app_name, args.events)
            elif akc == "list":
                commands.accesskey_list(args.app_name)
            elif akc == "delete":
                commands.accesskey_delete(args.key)
        elif cmd == "import":
            commands.import_events(args.appname, args.input, args.channel)
        elif cmd == "export":
            commands.export_events(
                args.appname, args.output, args.channel,
                num_shards=args.sharded, format=args.format,
            )
        elif cmd == "train":
            from predictionio_tpu.parallel import initialize_from_env
            from predictionio_tpu.workflow import load_engine_variant, run_train
            from predictionio_tpu.workflow.core import WorkflowParams

            initialize_from_env()  # multi-host when PIO_COORDINATOR_* set
            variant = load_engine_variant(args.engine_json)
            ctx = _parse_mesh(args.mesh)
            instance = run_train(
                variant,
                ctx,
                WorkflowParams(
                    batch=args.batch,
                    skip_sanity_check=args.skip_sanity_check,
                    stop_after_read=args.stop_after_read,
                    stop_after_prepare=args.stop_after_prepare,
                    warm_start=args.warm_start,
                ),
            )
            if args.aot:
                # lazy: without --aot no AOT module is imported and the
                # train output is byte-identical (CI-guarded)
                _train_aot_export(variant, ctx, instance)
            print(f"Training completed. Engine instance: {instance.id}")
        elif cmd == "deploy":
            if (args.replicas and args.replicas > 0) or args.router_only:
                # replica-fleet path (ISSUE 15/17): router + replica
                # subprocesses (or a bare HA router). Gated here so a
                # fleet-less deploy never imports predictionio_tpu.fleet
                # (CI-guarded).
                return _deploy_fleet(args)
            from predictionio_tpu import resilience
            from predictionio_tpu.api.http import serve
            from predictionio_tpu.serving import BatcherConfig
            from predictionio_tpu.workflow import load_engine_variant
            from predictionio_tpu.workflow.serving import FeedbackConfig, QueryService

            # before any storage client exists: the lazily-built remote
            # driver reads these process-wide defaults (per-source
            # PIO_STORAGE_SOURCES_<ID>_* properties still win)
            resilience.set_rpc_defaults(
                retries=args.retry_reads,
                retry_writes=args.retry_writes,
                breaker_threshold=args.breaker_threshold,
                breaker_reset_s=args.breaker_reset_s,
                deadline_s=args.rpc_deadline_s,
            )
            variant = load_engine_variant(args.engine_json)
            feedback = None
            if args.feedback:
                feedback = FeedbackConfig(
                    event_server_url=(
                        f"http://{args.event_server_ip}:{args.event_server_port}"
                    ),
                    access_key=args.accesskey,
                    timeout_s=args.feedback_timeout,
                    block_ms=(
                        0.0 if args.no_feedback_blocking else args.feedback_block_ms
                    ),
                    breaker_threshold=args.feedback_breaker_threshold,
                    breaker_reset_s=args.feedback_breaker_reset_s,
                )
            batching = None
            if args.batching:
                batching = BatcherConfig(
                    max_batch_size=args.max_batch_size,
                    max_batch_delay_ms=args.max_batch_delay_ms,
                    max_queue=args.batch_queue,
                    admission=args.admission_policy,
                    block_timeout_ms=args.admission_timeout_ms,
                    buckets=tuple(
                        int(x) for x in args.batch_buckets.split(",") if x.strip()
                    ),
                    warmup_body=(
                        json.loads(args.batch_warmup_query)
                        if args.batch_warmup_query
                        else None
                    ),
                )
            cache = None
            if (
                args.result_cache or args.coalesce or args.pin_model
                or args.shard_factors or args.quantize
            ):
                from predictionio_tpu.serving import CacheConfig

                cache = CacheConfig(
                    result_cache=args.result_cache,
                    result_cache_entries=args.result_cache_entries,
                    result_cache_ttl_s=args.result_cache_ttl_s,
                    result_cache_max_bytes=int(
                        args.result_cache_max_mb * 1024 * 1024
                    ),
                    coalesce=args.coalesce,
                    pin_model=args.pin_model,
                    shard_factors=args.shard_factors,
                    quantize=args.quantize,
                    scope_field=(
                        None
                        if args.cache_scope_field.lower() in ("none", "")
                        else args.cache_scope_field
                    ),
                )
            ann = None
            if args.ann:
                from predictionio_tpu.serving import AnnConfig

                ann = AnnConfig(
                    enabled=True,
                    nlist=args.ann_nlist,
                    nprobe=args.ann_nprobe,
                    seed=args.ann_seed,
                    kmeans_iters=args.ann_kmeans_iters,
                )
            online = None
            if args.online:
                from predictionio_tpu.online import OnlineConfig

                online = OnlineConfig(
                    enabled=True,
                    interval_s=args.online_interval_s,
                    batch_size=args.online_batch,
                    algorithms=tuple(
                        t.strip()
                        for t in args.online_algos.split(",")
                        if t.strip()
                    ),
                    prior_weight=args.online_prior_weight,
                    from_start=args.online_from_start,
                )
            explore = None
            if args.explore:
                # lazy: without --explore no experiments module is imported
                from predictionio_tpu.experiments.explore import ExploreConfig

                explore = ExploreConfig(
                    policy=args.explore,
                    epsilon=args.explore_epsilon,
                    seed=args.explore_seed,
                    reward_event=args.explore_reward_event,
                )
            aot = None
            if args.aot:
                # lazy: without --aot no AOT module is imported and the
                # serving path is byte-identical (CI-guarded)
                from predictionio_tpu.data.storage import Storage
                from predictionio_tpu.workflow.aot import AotConfig

                aot = AotConfig(
                    enabled=True,
                    root=os.path.join(Storage.base_dir(), "fleet", "aot"),
                )
            service = QueryService(
                variant, feedback=feedback, instance_id=args.engine_instance_id,
                batching=batching, cache=cache, ann=ann, online=online,
                explore=explore, replica_id=args.replica_id, aot=aot,
            )

            def wire_stop(server):
                # GET /stop answers first, then the server shuts down on a
                # helper thread (shutdown() from a handler would deadlock).
                # The stop token is written only after a successful bind so
                # a failed re-deploy on a busy port cannot clobber the live
                # deployment's token file. Keyed by the BOUND port, so
                # --port 0 deployments get a usable token too.
                import threading

                bound_port = server.server_address[1]
                service.stop_token = commands.write_stop_token(bound_port)
                service.stop_server = lambda: threading.Thread(
                    target=server.shutdown, daemon=True
                ).start()
                if args.port == 0:
                    print(f"Bound port {bound_port}")
                if args.announce_dir:
                    _start_announcer(args, service, server)

            print(f"Engine is deployed and running. Listening on {args.ip}:{args.port}")
            serve(
                service.dispatch, args.ip, args.port,
                ssl_context=_ssl_from_args(args), ready_callback=wire_stop,
                lifecycle=_lifecycle_from_args(args),
            )
        elif cmd == "undeploy":
            commands.undeploy(
                args.ip, args.port, args.https, args.insecure, token=args.token
            )
        elif cmd == "eval":
            from predictionio_tpu.controller import local_context
            from predictionio_tpu.controller.evaluation import EngineParamsGenerator
            from predictionio_tpu.utils.reflection import resolve_attr
            from predictionio_tpu.workflow.core import WorkflowParams, run_evaluation

            evaluation = resolve_attr(args.evaluation)
            if callable(evaluation) and not hasattr(evaluation, "engine"):
                evaluation = evaluation()
            if args.params_generator:
                generator = resolve_attr(args.params_generator)
                if callable(generator) and not hasattr(generator, "engine_params_list"):
                    generator = generator()
            else:
                generator = EngineParamsGenerator(
                    getattr(evaluation, "engine_params_list", ())
                )
            if args.grid:
                # lazy: without --grid no experiments module is imported
                from predictionio_tpu.experiments.sweep import (
                    run_grid_evaluation,
                )

                instance, result = run_grid_evaluation(
                    evaluation,
                    generator,
                    local_context(),
                    WorkflowParams(batch=args.batch),
                    evaluation_class=args.evaluation,
                    generator_class=args.params_generator or "",
                )
            else:
                instance, result = run_evaluation(
                    evaluation,
                    generator,
                    local_context(),
                    WorkflowParams(batch=args.batch),
                    evaluation_class=args.evaluation,
                    generator_class=args.params_generator or "",
                )
            print(result.leaderboard())
            with open(args.output_path, "w") as f:
                json.dump(result.to_json(), f, indent=2, default=str)
            print(f"Best params written to {args.output_path}")
            if args.promote_to:
                report = _promote_winner(args.promote_to, result)
                print(json.dumps(report, indent=2, default=str))
        elif cmd == "eventserver":
            from predictionio_tpu.api import EventService
            from predictionio_tpu.api.http import serve

            service = EventService(stats=args.stats)
            if args.compact_interval_s and args.compact_interval_s > 0:
                from predictionio_tpu.data.storage import Storage
                from predictionio_tpu.data.storage.compaction import (
                    CompactionConfig,
                    CompactionScheduler,
                )

                le = Storage.get_l_events()
                if not (
                    hasattr(le, "stream_stats") and hasattr(le, "compact")
                ):
                    raise SystemExit(
                        "--compact-interval-s needs an EVENTDATA backend "
                        "with a tail to compact (TYPE=columnar)"
                    )
                service.compaction_scheduler = CompactionScheduler(
                    le,
                    CompactionConfig(
                        interval_s=args.compact_interval_s,
                        tail_bytes_high=int(
                            args.compact_tail_mb * 1024 * 1024
                        ),
                        dead_tombstones_high=args.compact_dead_tombstones,
                        min_interval_s=args.compact_min_interval_s,
                    ),
                )
                service.compaction_scheduler.start()
                print(
                    "Background compaction: every "
                    f"{args.compact_interval_s:g}s, tail >= "
                    f"{args.compact_tail_mb:g} MiB or >= "
                    f"{args.compact_dead_tombstones} dead tombstones"
                )
            print(f"Event Server is listening on {args.ip}:{args.port}")
            serve(
                service.dispatch, args.ip, args.port,
                ssl_context=_ssl_from_args(args),
                lifecycle=_lifecycle_from_args(args),
            )
        elif cmd == "dashboard":
            from predictionio_tpu.api.http import serve
            from predictionio_tpu.tools.dashboard import DashboardService

            print(f"Dashboard is listening on {args.ip}:{args.port}")
            serve(
                DashboardService().dispatch, args.ip, args.port,
                ssl_context=_ssl_from_args(args),
                lifecycle=_lifecycle_from_args(args),
            )
        elif cmd == "adminserver":
            from predictionio_tpu.api.http import serve
            from predictionio_tpu.tools.adminserver import AdminService

            print(f"Admin server is listening on {args.ip}:{args.port}")
            serve(
                AdminService().dispatch, args.ip, args.port,
                ssl_context=_ssl_from_args(args),
                lifecycle=_lifecycle_from_args(args),
            )
        elif cmd == "template":
            if args.template_command == "list":
                commands.template_list()
            elif args.template_command == "get":
                commands.template_get(args.name, args.directory, args.appname)
        elif cmd == "storageserver":
            from predictionio_tpu.api.http import serve
            from predictionio_tpu.data.storage.remote import StorageRpcService

            secret = args.secret or os.environ.get("PIO_STORAGE_SERVER_SECRET")
            loopback = args.ip.startswith("127.") or args.ip in ("localhost", "::1")
            if not loopback and not secret:
                raise SystemExit(
                    "storageserver grants unauthenticated read/write of apps, "
                    "access keys, events and model blobs; refusing to bind "
                    f"non-loopback address {args.ip!r} without --secret / "
                    "$PIO_STORAGE_SERVER_SECRET"
                )
            print(f"Storage server is listening on {args.ip}:{args.port}")
            serve(
                StorageRpcService(secret=secret).dispatch, args.ip, args.port,
                ssl_context=_ssl_from_args(args),
                lifecycle=_lifecycle_from_args(args),
            )
        elif cmd == "batchpredict":
            from predictionio_tpu.tools.batchpredict import run_batch_predict

            n = run_batch_predict(
                args.engine_json, args.input, args.output, args.engine_instance_id
            )
            print(f"Wrote {n} predictions to {args.output}")
        elif cmd == "build":
            print(
                "Nothing to build: Python engines are imported directly. "
                "(kept for command-line parity with the reference)"
            )
        elif cmd == "run":
            import subprocess

            cmdline = list(args.run_args)
            if cmdline and cmdline[0] == "--":
                cmdline = cmdline[1:]
            if not cmdline:
                print("ERROR: pio run needs a command to execute",
                      file=sys.stderr)
                return 1
            env = dict(os.environ)
            from predictionio_tpu.data.storage import Storage

            env.setdefault("PIO_FS_BASEDIR", Storage.base_dir())
            repo_root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            env["PYTHONPATH"] = (
                repo_root + os.pathsep + env.get("PYTHONPATH", "")
            ).rstrip(os.pathsep)
            return subprocess.run(cmdline, env=env).returncode
        elif cmd == "lint":
            # stdlib-only AST analysis: imports nothing it lints, never
            # initializes jax — safe and fast on any CI host. Exit code
            # contract (docs/development.md): 0 clean, 1 findings (or a
            # failed witness crosscheck), 2 internal error — so a CI job
            # can tell "the tree is dirty" from "the linter broke".
            try:
                from predictionio_tpu.analysis import run_lint

                res = run_lint(
                    root=args.root,
                    baseline_path=args.baseline,
                    update_baseline=args.update_baseline,
                    prune_stale=args.prune_baseline,
                )
                pruned_ledger = 0
                if args.prune_baseline:
                    # the compile-budget ledger prunes alongside the
                    # finding baseline: an entrypoint whose file or
                    # function is gone is the same class of stale debt
                    # (still stdlib-only — the prune is an AST existence
                    # check)
                    from predictionio_tpu.analysis import jit_witness

                    pruned_ledger = jit_witness.prune_ledger(
                        jit_witness.default_ledger_path(res.root), res.root
                    )
                xcheck = None
                if args.witness:
                    from predictionio_tpu.analysis import lock_witness

                    with open(args.witness, encoding="utf-8") as fh:
                        doc = json.load(fh)
                    # accept any recorded shape: a pytest --lock-witness
                    # / pio tsan payload ({"witness": {...}}) or a raw
                    # witness report ({"edges": [...]})
                    wrep = doc.get("witness", doc) if isinstance(
                        doc, dict
                    ) else {}
                    xcheck = lock_witness.crosscheck(wrep, root=res.root)
                ok = res.ok and (xcheck is None or xcheck["ok"])
                if args.format == "json":
                    payload = res.to_json()
                    # the ledger prune rewrites a checked-in file; a CI
                    # job reading the JSON must see that happened, same
                    # as prunedBaselineEntries
                    payload["prunedCompileBudgetEntries"] = pruned_ledger
                    if xcheck is not None:
                        payload["witnessCrosscheck"] = xcheck
                        payload["ok"] = ok
                    print(json.dumps(payload, indent=2))
                elif args.format == "sarif":
                    print(json.dumps(res.to_sarif(), indent=2))
                else:
                    for f in res.new_findings:
                        print(f.render())
                    summary = (
                        f"piolint: {res.files_scanned} files, "
                        f"{len(res.new_findings)} new finding(s), "
                        f"{len(res.baselined)} baselined, "
                        f"{res.suppressed_count} suppressed"
                    )
                    if res.pruned_baseline:
                        summary += (
                            f", {res.pruned_baseline} stale baseline entr"
                            f"{'y' if res.pruned_baseline == 1 else 'ies'} "
                            "pruned"
                        )
                    if pruned_ledger:
                        summary += (
                            f", {pruned_ledger} stale compile-budget entr"
                            f"{'y' if pruned_ledger == 1 else 'ies'} pruned"
                        )
                    if res.stale_baseline:
                        summary += (
                            f", {res.stale_baseline} stale baseline entr"
                            f"{'y' if res.stale_baseline == 1 else 'ies'} "
                            "(fixed findings — prune with --prune-baseline)"
                        )
                    print(summary)
                    if xcheck is not None:
                        print(
                            f"lock-witness crosscheck: "
                            f"{xcheck['dynamicEdges']} dynamic edge(s) vs "
                            f"{xcheck['staticEdges']} static, "
                            f"{len(xcheck['gaps'])} analyzer gap(s), "
                            f"{len(xcheck['unwaivedStaticCycles'])} "
                            f"unwaived static cycle(s), "
                            f"{len(xcheck['staleWaivers'])} stale waiver(s)"
                        )
                        for g in xcheck["gaps"]:
                            print(
                                f"  GAP: witnessed {g['from']} -> "
                                f"{g['to']} (x{g['count']}) has no static "
                                f"edge {g['staticFrom']} -> {g['staticTo']}"
                            )
                        for c in xcheck["unwaivedStaticCycles"]:
                            print(
                                "  UNWAIVED CYCLE: "
                                + " -> ".join(c["cycle"])
                                + f" ({c['witnessedEdges']}/"
                                f"{c['totalEdges']} edges witnessed; add "
                                "a lock-witness-waivers.json entry or "
                                "exercise it)"
                            )
                return 0 if ok else 1
            except Exception as e:  # noqa: BLE001 — exit-code contract
                print(f"piolint: internal error: {e}", file=sys.stderr)
                return 2
        elif cmd == "tsan":
            # run a nested pio command in-process under the lock-witness
            # sanitizer (stdlib-only; docs/operations.md "Lock-witness
            # runbook"). The child's locks allocated AFTER install are
            # recorded; its exit code is combined with the witness
            # verdict (any witnessed inversion fails the run).
            from predictionio_tpu.analysis import witness

            cmdline = list(args.tsan_args)
            if cmdline and cmdline[0] == "--":
                cmdline = cmdline[1:]
            if cmdline and cmdline[0] == "pio":
                cmdline = cmdline[1:]
            if not cmdline:
                print("ERROR: pio tsan needs a command to execute, e.g. "
                      "`pio tsan -- chaos-ingest --cycles 1`",
                      file=sys.stderr)
                return 1
            def run_child() -> int:
                # a nested command may leave via SystemExit (argparse
                # errors, server refusals) — fold that into an exit code
                # so the witness report survives; real witnessed work
                # already happened by then and must not be discarded
                try:
                    return main(cmdline)
                except SystemExit as e:
                    code = e.code
                    if code is None:
                        return 0
                    return code if isinstance(code, int) else 1

            from predictionio_tpu.analysis import lock_witness

            child_rc, payload = lock_witness.run_with_lock_witness(
                run_child,
                long_hold_ms=args.long_hold_ms,
                waivers=lock_witness.load_waivers(),
            )
            payload["command"] = cmdline
            payload["exitCode"] = child_rc
            if args.report:
                witness.write_report(args.report, payload)
            print(json.dumps(payload, indent=2, sort_keys=True))
            return 0 if (payload["ok"] and not child_rc) else 1
        elif cmd == "jitwitness":
            # run a nested pio command in-process under the jit-witness
            # sanitizer (docs/operations.md "Jit-witness runbook"): XLA
            # compiles per call site, transfer bytes, per-call jit
            # constructions; classifies the static PIO306-308 findings
            # and checks the compile-budget ledger. Exit 1 on a budget
            # VIOLATION or child failure — unbudgeted compiles are
            # reported, not fatal (arbitrary commands train/cold-start).
            from predictionio_tpu.analysis import jit_witness

            cmdline = list(args.jitwitness_args)
            if cmdline and cmdline[0] == "--":
                cmdline = cmdline[1:]
            if cmdline and cmdline[0] == "pio":
                cmdline = cmdline[1:]
            if not cmdline:
                print(
                    "ERROR: pio jitwitness needs a command to execute, "
                    "e.g. `pio jitwitness -- deploy ...`",
                    file=sys.stderr,
                )
                return 1

            def run_child_jw() -> int:
                try:
                    return main(cmdline)
                except SystemExit as e:
                    code = e.code
                    if code is None:
                        return 0
                    return code if isinstance(code, int) else 1

            child_rc, rep = jit_witness.run_with_jit_witness(run_child_jw)
            payload = jit_witness.jitwitness_report(
                rep, ledger_path=args.ledger
            )
            payload["command"] = cmdline
            payload["exitCode"] = child_rc
            if args.report:
                jit_witness.write_report(args.report, payload)
            print(json.dumps(payload, indent=2, sort_keys=True))
            return 0 if (payload["ok"] and not child_rc) else 1
        elif cmd == "chaos-ingest":
            # spawns real event-server subprocesses and SIGKILLs them;
            # stdlib-only harness (docs/operations.md "Crash safety")
            from predictionio_tpu.resilience.chaos import (
                ChaosConfig,
                run_chaos_ingest,
            )

            report = run_chaos_ingest(
                ChaosConfig(
                    cycles=args.cycles,
                    writers=args.writers,
                    events_per_writer=args.events,
                    backend=args.backend,
                    seed=args.seed,
                    bulk_events=args.bulk_events,
                    drain_deadline_s=args.drain_deadline_s,
                    partitions=args.partitions,
                    replication=args.replication,
                    ack_quorum=args.ack_quorum,
                    keep_dir=args.keep,
                )
            )
            print(json.dumps(report, indent=2))
            return 0 if report["ok"] else 1
        elif cmd == "chaos-serve":
            # serving-fleet robustness drill (ISSUE 15): SIGKILL replicas
            # under concurrent clients, rolling /reload, zero failed
            # queries (docs/operations.md "Fleet runbook")
            from predictionio_tpu.resilience.chaos import (
                ServeChaosConfig,
                run_chaos_serve,
            )

            report = run_chaos_serve(
                ServeChaosConfig(
                    replicas=args.replicas,
                    clients=args.clients,
                    kills=args.kills,
                    phase_seconds=args.seconds,
                    reloads=args.reloads,
                    train_events=args.events,
                    seed=args.seed,
                    sharded_point=args.sharded_point,
                    aot=args.aot,
                    keep_dir=args.keep,
                )
            )
            print(json.dumps(report, indent=2))
            return 0 if report["ok"] else 1
        elif cmd == "chaos-fleet":
            # cross-host elastic-fleet drill (ISSUE 17): two-"host" kill
            # with HA router failover, autoscaler watermark scale-up +
            # drain-aware scale-down, stale-while-down proof
            # (docs/operations.md "Multi-host fleet runbook")
            from predictionio_tpu.resilience.chaos import (
                FleetChaosConfig,
                run_chaos_fleet,
            )

            report = run_chaos_fleet(
                FleetChaosConfig(
                    replicas_per_host=args.replicas_per_host,
                    clients=args.clients,
                    phase_seconds=args.seconds,
                    train_events=args.events,
                    lease_ttl_s=args.lease_ttl_s,
                    seed=args.seed,
                    autoscale_phase=not args.skip_autoscale,
                    keep_dir=args.keep,
                )
            )
            print(json.dumps(report, indent=2))
            return 0 if report["ok"] else 1
        elif cmd == "upgrade":
            print(
                "predictionio_tpu is a Python package: upgrade with your "
                "package manager (e.g. `pip install -U predictionio_tpu`). "
                "Storage formats are forward-compatible within a major "
                "version; no in-place upgrader is needed."
            )
        return 0
    except Exception as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
